"""Module — Symbol + Executor + Optimizer + KVStore.

Reference: ``python/mxnet/module/module.py`` (bind ``:351``,
init_optimizer ``:460``, forward ``:556``, backward ``:598``, update
``:615``) over ``DataParallelExecutorGroup``.

TPU-native difference: there is no per-device executor group.  One
executor holds the whole bound graph as a single XLA program; *device*
parallelism is SPMD — the batch is sharded over the mesh's 'data' axis
and XLA replicates the program and inserts the gradient all-reduce
(kvstore types containing 'dist'/'device' activate this via
``mxnet_tpu.parallel``).  ``update()`` keeps the reference's
push-then-pull kvstore protocol with ``priority=-index`` ordering.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .. import optimizer as opt
from .. import kvstore as kvs
from ..initializer import InitDesc
from ..ndarray import NDArray, zeros
from .base_module import BaseModule

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, pipeline_stages=0,
                 pipeline_microbatches=None, pipeline_schedule="1f1b"):
        """``pipeline_stages=S`` trains through pipeline parallelism:
        the symbol is cut into S heterogeneous stages
        (``parallel.pipeline.split_symbol``), parameters/optimizer
        states shard over the active mesh's 'pipe' axis, and ``fit``
        runs the ``pipeline_schedule`` ('1f1b' or 'gpipe') microbatch
        wave — requires a mesh with ``{'pipe': S}`` and a dist kvstore.
        """
        super().__init__(logger=logger)
        from ..context import current_context

        if context is None:
            context = [current_context()]
        if not isinstance(context, (list, tuple)):
            context = [context]
        self._context = list(context)
        self._symbol = symbol
        self._pipeline_stages = int(pipeline_stages)
        self._pipeline_microbatches = pipeline_microbatches
        self._pipeline_schedule = pipeline_schedule
        self._pipeline_stale = False
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = False
        self._updater = None
        self._preload_opt_states = None
        self._grad_req = None

    # -- introspection --------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return [o.shape for o in self._exec.outputs] if self._exec.outputs \
            else self._symbol._infer_outputs(
                {d.name: d.shape for d in self._data_shapes +
                 (self._label_shapes or [])})

    # -- bind -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req

        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = [_as_desc(l) for l in (label_shapes or [])]

        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({l.name: l.shape for l in self._label_shapes})

        req = grad_req
        if isinstance(req, str) and not for_training:
            req = "null"
        if isinstance(req, str) and self._fixed_param_names:
            req = {n: ("null" if n in self._fixed_param_names else grad_req)
                   for n in self._param_names}
        if inputs_need_grad and isinstance(req, dict):
            for n in self._data_names:
                req[n] = grad_req
        elif inputs_need_grad and isinstance(req, str):
            req = {n: grad_req for n in
                   self._param_names + self._data_names}

        shared_exec = shared_module._exec if shared_module is not None else None
        self._exec = self._symbol.simple_bind(
            self._context[0], grad_req=req, shared_exec=shared_exec,
            **shapes)
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            self.params_initialized = True

    # -- params ---------------------------------------------------------
    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before initializing parameters"
        if initializer is None:
            # reference Module.init_params default (module.py:246):
            # leaving params at their simple_bind zeros would dead-relu
            # every net whose caller skipped the initializer argument
            from ..initializer import Uniform

            initializer = Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params is not None and name in arg_params:
                arg_params[name].copyto(arr)
            elif arg_params is not None and not allow_missing:
                raise MXNetError("parameter %s missing from arg_params" % name)
            else:
                # covers both no-arg_params and allow_missing fine-tune
                # flows: missing params get the initializer, never zeros
                desc = InitDesc(name, self._symbol.attr_dict().get(name, {}))
                initializer(desc, arr)
        for name in self._aux_names:
            arr = self._exec.aux_dict[name]
            if aux_params is not None and name in aux_params:
                aux_params[name].copyto(arr)
            elif initializer is not None:
                desc = InitDesc(name, self._symbol.attr_dict().get(name, {}))
                initializer(desc, arr)
        self.params_initialized = True
        # a live pipelined step caches params/states in packed
        # stage-sharded buffers; newly set params must invalidate them
        # (optimizer states carry over) or the next step trains on
        # stale weights.  When arg_dict is already in sync
        # (_pipeline_stale False — e.g. fit's per-epoch
        # get_params/set_params round-trip just ran _sync_pipeline),
        # the states dict is current too and the device unpack is
        # skipped; the one repack on the next step is the price of
        # honoring a potential external write.
        fused = getattr(self, "_fused", None)
        if fused is not None and \
                getattr(fused, "_packed_params", None) is not None:
            from ..parallel.pipeline import PipelineTrainStep

            if isinstance(fused, PipelineTrainStep):
                if getattr(self, "_pipeline_stale", False):
                    self._fused_states = fused.unpack_states()
                # newly set params/aux win over the packed buffers (the
                # same stance as arg_dict: external writes are honored,
                # the next step repacks all three)
                fused._packed_params = None
                fused._packed_states = None
                fused._packed_aux = None
                self._pipeline_stale = False
        # same stance for ZeRO-3 at-rest tiles: external writes to
        # arg_dict win; the next step repacks from the canonical dict
        if getattr(self, "_zero3_params", None) is not None:
            self._zero3_params = None
            self._zero3_stale = False

    def _sync_zero3(self):
        """Unpack ZeRO-3 at-rest parameter tiles back into the executor
        arg_dict (lazy sync point, mirroring ``_sync_pipeline``)."""
        if not getattr(self, "_zero3_stale", False):
            return
        import jax.numpy as jnp

        live = self._fused.unpack_params(self._zero3_params)
        for n, v in live.items():
            self._exec.arg_dict[n]._set_data(jnp.asarray(v))
        self._zero3_stale = False

    def _export_zero_params(self):
        """Flat ZeRO-3 parameter tiles for elastic checkpointing, or
        ``None`` when params are not sharded at rest."""
        fused = getattr(self, "_fused", None)
        if fused is None or not getattr(fused, "zero3", False):
            return None
        if getattr(self, "_zero3_params", None) is None:
            return None
        from ..parallel import zero as _zero_mod

        return _zero_mod.export_params(self._zero3_params, fused._zero_lay)

    def _sync_pipeline(self):
        """Gather live packed pipeline params/states back into the
        executor dicts (lazy sync point for the stage-sharded step)."""
        if not getattr(self, "_pipeline_stale", False):
            return
        import jax.numpy as jnp

        live = self._fused.unpack_params()
        for n, v in live.items():
            self._exec.arg_dict[n]._set_data(jnp.asarray(v))
        for n, v in self._fused.unpack_aux().items():
            self._exec.aux_dict[n]._set_data(jnp.asarray(v))
        self._fused_states = self._fused.unpack_states()
        self._pipeline_stale = False

    def get_params(self):
        assert self.binded and self.params_initialized
        self._sync_pipeline()
        self._sync_zero3()
        arg_params = {n: self._exec.arg_dict[n].copy()
                      for n in self._param_names}
        aux_params = {n: self._exec.aux_dict[n].copy()
                      for n in self._aux_names}
        return arg_params, aux_params

    # -- optimizer ------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False, param_sharding=None,
                       compute_dtype=None, steps_per_call=None,
                       health=None, loss_scale=None, zero=None,
                       plan=None):
        """``param_sharding``: 'replicated' (default), 'fsdp', 'tp', or a
        rule list (see ``parallel.sharding.param_sharding_rules``) —
        applied to the fused step's parameter/optimizer-state layouts
        over the active mesh.  This is the working equivalent of the
        reference's ``group2ctx`` model parallelism
        (``graph_executor.cc:395`` PlaceDevice) plus the ZeRO-style
        sharded-optimizer layout the reference approximated with
        parameter-server key sharding (``kvstore_dist.h:431``).  Also
        settable via ``MXNET_PARAM_SHARDING``.

        ``steps_per_call=K``: multi-step dispatch — the fused step scans
        K donated updates over a packed (K, batch, …) super-batch per
        device call (``fit`` packs via ``DevicePrefetchIter``).  Also
        settable via ``MXNET_STEPS_PER_CALL``.

        ``health``: run-health sentinel — True / a policy string / a
        :class:`~mxnet_tpu.health.HealthMonitor` (also via
        ``MXNET_HEALTH_MONITOR=1``); ``loss_scale``: 'dynamic', a fixed
        number, or a :class:`~mxnet_tpu.health.DynamicLossScaler` for
        low-precision runs (also via ``MXNET_LOSS_SCALE``).  See
        docs/health_monitoring.md.

        ``zero``: 'auto' (default) | 'on' | 'off' — ZeRO-style sharding
        of the optimizer state and the weight update across the data
        axis (``MXNET_ZERO``; see docs/performance.md).

        ``plan``: a :class:`~mxnet_tpu.parallel.ParallelPlan` (or its
        ``"data=4,model=2,zero=3"`` spec string, also via
        ``MXNET_PLAN``) — ONE declaration composing TP x PP x DP/ZeRO;
        it replaces ``param_sharding``/``zero`` and, for ``pipe>1``
        plans, routes training through ``PipelineTrainStep`` (see
        docs/performance.md "Composing parallelisms")."""
        from ..base import get_env
        from ..health import DynamicLossScaler, resolve_monitor
        from ..parallel import zero as _zero_mod

        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        if plan is None:
            plan = get_env("MXNET_PLAN", "", str).strip() or None
        if plan is not None:
            from ..parallel.plan import ParallelPlan

            plan = ParallelPlan.parse(plan)
            if plan.pipe > 1:
                if self._pipeline_stages and \
                        self._pipeline_stages != plan.pipe:
                    raise MXNetError(
                        "plan pipe=%d conflicts with Module("
                        "pipeline_stages=%d)"
                        % (plan.pipe, self._pipeline_stages))
                self._pipeline_stages = plan.pipe
                self._pipeline_schedule = plan.schedule
                if plan.n_microbatches:
                    self._pipeline_microbatches = plan.n_microbatches
        self._plan = plan
        self._health_monitor = resolve_monitor(health)
        if loss_scale is None:
            loss_scale = get_env("MXNET_LOSS_SCALE", "", str) or None
        self._loss_scaler = DynamicLossScaler.from_spec(loss_scale)
        self._last_health_stats = None
        if param_sharding is None:
            param_sharding = get_env("MXNET_PARAM_SHARDING", "", str) \
                or None
        self._param_sharding = param_sharding
        if steps_per_call is None:
            steps_per_call = get_env("MXNET_STEPS_PER_CALL", 1, int)
        self._steps_per_call = max(1, int(steps_per_call))
        # mixed precision for the fused step: bf16 activations over fp32
        # master weights (also via MXNET_COMPUTE_DTYPE=bfloat16)
        if compute_dtype is None:
            compute_dtype = get_env("MXNET_COMPUTE_DTYPE", "", str) or None
        self._compute_dtype = compute_dtype
        # normalized to auto|on|off (explicit arg wins over MXNET_ZERO);
        # a plan that pins zero owns the mode when the arg is unset —
        # without this the plan's zero=3 would silently degrade to the
        # MXNET_ZERO default on the Module path
        if zero is None and plan is not None and plan.zero is not None:
            zero = plan.zero
        self._zero = _zero_mod.zero_mode(zero)
        kvstore_inst, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context), self._exec.arg_dict)

        batch_size = self._data_shapes[0].shape[0]
        rescale_grad = 1.0 / batch_size
        if kvstore_inst and "dist" in kvstore_inst.type and \
                "_sync" in kvstore_inst.type:
            rescale_grad /= kvstore_inst.num_workers

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                optimizer_params["rescale_grad"] = rescale_grad
            optimizer = opt.create(optimizer, sym=self._symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        else:
            assert isinstance(optimizer, opt.Optimizer)

        self._optimizer = optimizer
        self._kvstore = kvstore_inst
        self._update_on_kvstore = update_on_kvstore
        optimizer.set_lr_mult({})
        optimizer.set_wd_mult({})
        self._mesh = self._decide_mesh(kvstore_inst)

        if kvstore_inst:
            # init keys: index -> weight
            for i, name in enumerate(self._param_names):
                kvstore_inst.init(i, self._exec.arg_dict[name])
            if update_on_kvstore:
                kvstore_inst.set_optimizer(optimizer)
            if getattr(kvstore_inst, "_is_async", False):
                # hosts must start from one common point; one averaging
                # round over the (identically- or differently-) seeded
                # initial params establishes it
                kvstore_inst.sync_params(self._async_params())
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)

        self._maybe_compile_fused()
        self.optimizer_initialized = True
        if self._preload_opt_states is not None:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _decide_mesh(self, kvstore_inst):
        """Choose the device mesh for this fit (reference: kvstore type
        selects the comm layer, ``src/kvstore/kvstore.cc:34-62``; here
        'device'/'dist*' types select SPMD over a ``jax.sharding.Mesh``
        and XLA inserts the gradient all-reduce over ICI)."""
        plan = getattr(self, "_plan", None)
        if plan is None:
            if kvstore_inst is None:
                return None
            if not ("dist" in kvstore_inst.type
                    or "device" in kvstore_inst.type):
                return None
        import jax

        from ..parallel import current_mesh, create_mesh

        mesh = current_mesh()
        if mesh is None and plan is not None:
            # the plan declares its own topology over this host's devices
            # (a plan needs no kvstore: GSPMD owns every collective)
            mesh = plan.mesh()
        elif mesh is not None and plan is not None:
            plan.validate_mesh(mesh)
        if mesh is None:
            # meshes stay process-LOCAL: in-jit collectives ride ICI
            # within this host's slice; cross-process traffic goes
            # through the kvstore DCN branch (sync) or the averaging
            # rounds (async)
            devices = [c.jax_device for c in self._context] \
                if len(self._context) > 1 else list(jax.local_devices())
            if len(devices) <= 1:
                return None
            mesh = create_mesh({"data": len(devices)}, devices=devices)
        # the global batch must divide over the data axis
        axis = mesh.shape.get("data", 1)
        batch = self._data_shapes[0].shape[0]
        if axis > 1 and batch % axis != 0:
            if plan is not None:
                raise MXNetError(
                    "batch size %d not divisible by the plan's data axis "
                    "%d (plan=%r)" % (batch, axis, plan))
            self.logger.warning(
                "batch size %d not divisible by mesh data axis %d; "
                "running replicated", batch, axis)
            return None
        if kvstore_inst is not None:
            kvstore_inst._mesh = mesh
        return mesh

    def _maybe_compile_fused(self):
        """Compile fwd+bwd+allreduce+update into ONE XLA program.

        This is the TPU analogue of the reference's bulk-exec segments
        (``InitOpSegs``, env ``MXNET_EXEC_BULK_EXEC_TRAIN``) taken to its
        limit: the whole train step — including the optimizer and, under a
        mesh, the gradient all-reduce — is a single device call per batch,
        which removes the per-op host round-trips that dominate when the
        device is behind a network tunnel.  Works for every optimizer with
        a ``fused_update`` (the whole built-in family); per-param lr/wd
        multipliers and fixed params are honored.  Set MXNET_FUSED_STEP=0
        to disable (falls back to forward/backward/update calls)."""
        from ..base import get_env

        self._fused = None
        self._fused_states = None
        self._fused_ran = False

        def _bail(reason):
            # an EXPLICIT mixed-precision request must not silently train
            # fp32 through the split path (same stance as param_sharding)
            if getattr(self, "_compute_dtype", None) is not None:
                raise MXNetError(
                    "compute_dtype=%r was requested but the fused step is "
                    "unavailable: %s" % (self._compute_dtype, reason))
            # likewise an explicit multi-step dispatch request: the split
            # path has no scanned form
            if getattr(self, "_steps_per_call", 1) > 1:
                raise MXNetError(
                    "steps_per_call=%d was requested but the fused step "
                    "is unavailable: %s" % (self._steps_per_call, reason))
            # and loss scaling: the split path cannot thread scaler state
            # through per-parameter updates, so silently training
            # unscaled would defeat the overflow protection asked for
            if getattr(self, "_loss_scaler", None) is not None:
                raise MXNetError(
                    "loss_scale was requested but the fused step is "
                    "unavailable: %s" % (reason,))
            # an explicit ZeRO request only exists inside the fused step
            if getattr(self, "_zero", None) in ("on", "3"):
                raise MXNetError(
                    "zero=%s was requested but the fused step is "
                    "unavailable: %s" % (self._zero, reason))
            # likewise a composed plan: the split path has no TP/ZeRO
            # composition, so training replicated would silently ignore it
            if getattr(self, "_plan", None) is not None:
                raise MXNetError(
                    "plan=%r was requested but the fused step is "
                    "unavailable: %s" % (self._plan, reason))

        if self._pipeline_stages > 1:
            if getattr(self, "_steps_per_call", 1) > 1:
                raise MXNetError(
                    "steps_per_call cannot combine with pipeline_stages "
                    "(the pipelined step already runs its own microbatch "
                    "wave per call)")
            if getattr(self, "_loss_scaler", None) is not None:
                raise MXNetError(
                    "loss_scale cannot combine with pipeline_stages (the "
                    "pipelined step does not thread scaler state)")
            if getattr(self, "_health_monitor", None) is not None:
                # the pipelined step computes no in-step stats; the
                # liveness side (watchdog, heartbeats) still applies
                self.logger.warning(
                    "health monitor: in-step numerics are unavailable "
                    "with pipeline_stages — disabling the monitor "
                    "(step watchdog and heartbeats remain active)")
                self._health_monitor = None
            # an EXPLICIT pipeline request never falls back silently
            from ..parallel.pipeline import PipelineTrainStep

            if self._kvstore is not None and \
                    getattr(self._kvstore, "_is_async", False):
                raise MXNetError(
                    "pipeline_stages cannot combine with dist_async "
                    "(packed stage-sharded params have no averaging "
                    "round); use a sync kvstore")
            if self.inputs_need_grad:
                raise MXNetError(
                    "pipeline_stages cannot serve inputs_need_grad "
                    "(the pipelined step does not populate data input "
                    "gradients); use the non-pipelined module")
            if self._mesh is None or \
                    self._mesh.shape.get("pipe") != self._pipeline_stages:
                raise MXNetError(
                    "pipeline_stages=%d needs a dist kvstore under an "
                    "active mesh with {'pipe': %d} (parallel.mesh_scope)"
                    % (self._pipeline_stages, self._pipeline_stages))
            self._fused = PipelineTrainStep(
                self._symbol, optimizer=self._optimizer, mesh=self._mesh,
                n_microbatches=self._pipeline_microbatches,
                data_names=self._data_names,
                label_names=self._label_names,
                schedule=self._pipeline_schedule,
                fixed_param_names=self._fixed_param_names,
                plan=getattr(self, "_plan", None))
            return
        if not get_env("MXNET_FUSED_STEP", True, bool):
            _bail("MXNET_FUSED_STEP=0")
            return
        import jax

        if jax.process_count() > 1 and self._kvstore is not None and \
                "dist" in self._kvstore.type and \
                not getattr(self._kvstore, "_is_async", False):
            # multi-process SYNC training reduces gradients over DCN in
            # the kvstore push path; the fused in-jit step only covers
            # this host's mesh, so it would silently skip the
            # cross-process merge — use the split path
            _bail("multi-process sync kvstore uses the split push/pull "
                  "path for the DCN gradient merge")
            return
        if self.inputs_need_grad:
            # the fused step does not populate grad_dict for data inputs;
            # get_input_grads needs the split executor path
            _bail("inputs_need_grad requires the split executor")
            return
        o = self._optimizer
        if not o.supports_fused:
            self.logger.debug("optimizer %s has no fused form; using the "
                              "split update path", type(o).__name__)
            _bail("optimizer %s has no fused form" % type(o).__name__)
            return
        req = self._grad_req
        if isinstance(req, str):
            ok = req == "write"
        else:  # dict: fixed params null, everything else write
            ok = all(v == "write" or (k in self._fixed_param_names and
                                      v == "null")
                     for k, v in req.items())
        if not ok:
            _bail("grad_req %r is not fusable" % (req,))
            return
        try:
            from ..fused import TrainStep
            from ..health import StepHealth

            remat = "full" if get_env("MXNET_BACKWARD_DO_MIRROR", False,
                                      bool) else None
            scaler = getattr(self, "_loss_scaler", None)
            step_health = None
            if scaler is not None or \
                    getattr(self, "_health_monitor", None) is not None:
                step_health = StepHealth(scaler=scaler)
            self._fused = TrainStep(
                self._symbol, optimizer=o, mesh=self._mesh,
                data_names=self._data_names, label_names=self._label_names,
                fixed_param_names=self._fixed_param_names, remat=remat,
                param_sharding=getattr(self, "_param_sharding", None),
                compute_dtype=getattr(self, "_compute_dtype", None),
                steps_per_call=getattr(self, "_steps_per_call", 1),
                health=step_health,
                zero=getattr(self, "_zero", None),
                plan=getattr(self, "_plan", None))
            # the sharded-update dispatch attaches the kvstore's peer
            # diagnosis to bounded-collective timeouts
            self._fused._kvstore = self._kvstore
        except Exception as e:  # fall back to the split path
            if getattr(self, "_compute_dtype", None) is not None:
                raise MXNetError(
                    "compute_dtype=%r was requested but the fused step "
                    "could not be built: %s"
                    % (self._compute_dtype, e)) from e
            if getattr(self, "_loss_scaler", None) is not None:
                raise MXNetError(
                    "loss_scale was requested but the fused step could "
                    "not be built: %s" % (e,)) from e
            if getattr(self, "_steps_per_call", 1) > 1:
                raise MXNetError(
                    "steps_per_call=%d was requested but the fused step "
                    "could not be built: %s"
                    % (self._steps_per_call, e)) from e
            if getattr(self, "_param_sharding", None) not in (
                    None, "replicated"):
                # an EXPLICIT sharding request must not silently train
                # replicated single-device
                raise MXNetError(
                    "param_sharding=%r was requested but the fused step "
                    "could not be built: %s"
                    % (self._param_sharding, e)) from e
            if getattr(self, "_zero", None) in ("on", "3"):
                raise MXNetError(
                    "zero=%s was requested but the fused step could not "
                    "be built: %s" % (self._zero, e)) from e
            if getattr(self, "_plan", None) is not None:
                raise MXNetError(
                    "plan=%r was requested but the fused step could not "
                    "be built: %s" % (self._plan, e)) from e
            self.logger.debug("fused step unavailable: %s", e)
            self._fused = None
        if self._fused is None and self._mesh is not None and \
                max(self._mesh.shape.values()) > 1:
            self.logger.warning(
                "dist kvstore requested but the fused SPMD step is "
                "unavailable; training runs single-device (full batch)")

    def _init_fused_states(self):
        """Seed fused optimizer states, honoring any states preloaded into
        the updater (checkpoint resume) or handed over canonically by the
        elastic ZeRO restore.  Under the sharded update every seed —
        fresh, updater-preloaded, or canonical — lands in the flat 1/N
        zero layout (re-tiling is bit-exact: padding lanes are zeros)."""
        o = self._optimizer
        fused = getattr(self, "_fused", None)
        lay = None
        if fused is not None and getattr(fused, "zero_axis", None):
            pdict = {n: self._exec.arg_dict[n]._data
                     for n in self._param_names}
            lay = fused.zero_layout(pdict)
        states = {}
        preloaded = self._updater.states if self._updater is not None else \
            (self._kvstore.updater.states
             if self._kvstore is not None and self._kvstore.updater else {})
        canonical = getattr(self, "_preloaded_zero_states", None) or {}
        for i, n in enumerate(self._param_names):
            if n in canonical:
                st = canonical[n]
            elif i in preloaded and preloaded[i] is not None:
                st = o.fused_state_from_nd(preloaded[i])
            else:
                st = None
            if lay is not None:
                from ..parallel import zero as _zero

                if st is None:
                    states[n] = _zero.init_state(
                        o, pdict[n], lay[n], fused.mesh, fused.zero_axis)
                else:
                    states[n] = _zero.shard_state(
                        st, lay[n], fused.mesh, fused.zero_axis)
            else:
                states[n] = st if st is not None else \
                    o.init_fused_state(self._exec.arg_dict[n]._data)
        self._preloaded_zero_states = None
        return states

    def set_fused_optimizer_states(self, states):
        """Hand the fused step canonical (weight-shaped, by-name) fused
        optimizer states in memory — the elastic checkpoint's ZeRO
        restore path.  Applied (and re-tiled to the live layout) when the
        fused step next seeds its states."""
        assert self.binded
        self._preloaded_zero_states = dict(states)
        self._fused_states = None

    def _export_zero_states(self):
        """v2-checkpoint export descriptor of the live ZeRO-sharded fused
        states (``parallel.zero.export_states``), or None when the fused
        step is not running the sharded update."""
        fused = getattr(self, "_fused", None)
        if fused is None or not getattr(fused, "zero_axis", None) or \
                getattr(self, "_fused_states", None) is None:
            return None
        from ..parallel import zero as _zero

        pdict = {n: self._exec.arg_dict[n]._data
                 for n in self._param_names}
        return _zero.export_states(self._fused_states,
                                   fused.zero_layout(pdict))

    def reconfigure_plan(self, plan):
        """Rebuild the mesh + fused step under a NEW
        :class:`~mxnet_tpu.parallel.ParallelPlan` without re-running
        ``init_optimizer`` — the reshard half of the in-memory plan
        migration (``parallel/elastic.py``).  The live optimizer object
        is kept, so ``num_update`` and the lr schedule continue
        uninterrupted; the caller is responsible for capturing the fused
        optimizer states BEFORE this call (the rebuild drops them) and
        re-installing the canonical trees afterwards via
        :meth:`set_fused_optimizer_states`."""
        from ..parallel.plan import ParallelPlan
        from ..parallel import zero as _zero_mod

        assert self.binded and self.optimizer_initialized, \
            "reconfigure_plan needs a bound, optimizer-initialized module"
        plan = ParallelPlan.parse(plan)
        if plan.pipe > 1:
            raise MXNetError(
                "live migration onto a pipe>1 plan is not supported — "
                "the pipelined step packs state per stage, which has no "
                "in-memory reshard path yet (restart from a checkpoint)")
        if self._pipeline_stages > 1:
            raise MXNetError(
                "live migration off a pipelined module is not supported")
        old_plan = getattr(self, "_plan", None)
        self._plan = plan
        if plan.zero is not None:
            self._zero = _zero_mod.zero_mode(plan.zero)
        try:
            self._mesh = self._decide_mesh(self._kvstore)
            self._zero3_params = None
            self._zero3_stale = False
            self._preloaded_zero_states = None
            self._maybe_compile_fused()
            if self._fused is None:
                raise MXNetError(
                    "plan=%r was requested but the fused step is "
                    "unavailable after the rebuild" % (plan,))
        except Exception:
            # leave the module describing the plan it actually runs
            self._plan = old_plan
            raise
        return self._fused

    def prepare_compiled(self, dtype="float32"):
        """AOT warmup: lower-and-compile the fused train step for the
        bound shapes NOW instead of inside the first ``forward_backward``
        (``Module.fit`` runs this in a background thread that overlaps
        ``DevicePrefetchIter`` spin-up; see docs/compilation.md).

        Returns the compile stats dict (also on
        ``self._fused.compile_stats``), or None when no AOT-compilable
        fused step exists (split path, pipeline step, or shape-dependent
        sharding) — those paths keep their lazy first-call compile."""
        assert self.binded, "call bind before prepare_compiled"
        fused = getattr(self, "_fused", None)
        if fused is None or not hasattr(fused, "compile") or \
                (getattr(fused, "_jit_step", None) is None and
                 not getattr(fused, "_aot_capable", False)):
            return None
        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({l.name: l.shape
                       for l in (self._label_shapes or [])})
        stats = fused.compile(shapes, dtype=dtype)
        self.logger.debug("AOT compile %s: %.2fs%s", stats.get("name"),
                          stats.get("duration_s", 0.0),
                          " (persistent-cache hit)"
                          if stats.get("cache_hit") else "")
        return stats

    def _fused_forward_backward_update(self, data_batch):
        import jax.numpy as jnp

        from .. import random as _rnd
        from ..ndarray import NDArray

        o = self._optimizer
        z3 = getattr(self._fused, "zero3", False)
        if z3 and getattr(self, "_zero3_params", None) is not None:
            # ZeRO-3 steady state: params live step-side as flat 1/N
            # tiles; arg_dict is synced lazily (_sync_zero3) on read
            params = self._zero3_params
        else:
            params = {n: self._exec.arg_dict[n]._data
                      for n in self._param_names}
            if z3:
                # first step (or after an external arg_dict write): tile
                # the canonical params into the at-rest layout — this is
                # also the canonical-shape seeding point for the cached
                # zero layout
                params = self._fused.pack_params(params)
                self._zero3_params = params
        aux = {n: self._exec.aux_dict[n]._data for n in self._aux_names}
        if self._fused_states is None:
            self._fused_states = self._init_fused_states()
        batch = {}
        for name, arr in zip(self._data_names, data_batch.data):
            batch[name] = arr._data if isinstance(arr, NDArray) else \
                jnp.asarray(arr)
        for name, arr in zip(self._label_names, data_batch.label or []):
            batch[name] = arr._data if isinstance(arr, NDArray) else \
                jnp.asarray(arr)
        K = getattr(self._fused, "_steps_per_call", 1)
        if getattr(data_batch, "staged", False):
            # the DevicePrefetchIter staging thread already placed this
            # batch (device or NamedSharding) — re-placing would be a
            # synchronous no-op at best and an axis-0 re-shard at worst
            # for packed super-batches
            pass
        elif self._mesh is not None:
            from ..parallel.sharding import shard_batch

            lead = 1 if K > 1 else 0
            batch = {k: shard_batch(self._mesh, v, leading=lead)
                     for k, v in batch.items()}
        else:
            # load_data semantics: batches follow the module's device, not
            # the default platform (a cpu-context module on a TPU host gets
            # NDArrayIter batches materialized on the accelerator)
            import jax

            dev = self._context[0].jax_device
            batch = {k: jax.device_put(v, dev) for k, v in batch.items()}
        from ..testing import faults

        poison = faults.inject("numerics")
        if poison is not None:
            # poison one element of the first data tensor: the NaN/Inf
            # flows through forward AND backward, exercising the on-step
            # non-finite sentinel end to end (deterministic via
            # MXNET_FAULT_INJECT=numerics:nan:after=N)
            name = self._data_names[0]
            v = batch[name]
            v = v.at[(0,) * v.ndim].set(poison)
            batch = dict(batch)
            batch[name] = v
        # split-path parity: the scheduler is consulted at the
        # PRE-increment num_update (Optimizer.update calls _get_lr before
        # _update_count); bias-correction t is the POST-increment count.
        # A multi-step call advances the count by K (lr holds for the K
        # inner steps; t increments per step inside the scan).
        lr = o.lr_scheduler(o.num_update) if o.lr_scheduler else o.lr
        for _ in range(K):
            for i in range(len(self._param_names)):
                o._update_count(i)
        t = o.num_update - K + 1
        new_params, new_aux, self._fused_states, outs = self._fused(
            params, aux, self._fused_states, batch, _rnd.next_key(), lr, t)
        self._last_health_stats = getattr(self._fused, "last_health", None)
        from ..parallel.pipeline import PipelineTrainStep

        if isinstance(self._fused, PipelineTrainStep):
            # params/states live as packed stage-sharded buffers inside
            # the step; arg_dict is synced lazily (_sync_pipeline) when
            # something reads it (eval forward, get_params, checkpoint)
            self._pipeline_stale = True
        elif z3:
            # at-rest tiles stay step-side; aux (batchnorm stats) are
            # canonical-shaped and land in aux_dict as usual
            self._zero3_params = new_params
            self._zero3_stale = True
            for n, v in new_aux.items():
                self._exec.aux_dict[n]._set_data(v)
        else:
            for n, v in new_params.items():
                self._exec.arg_dict[n]._set_data(v)
            for n, v in new_aux.items():
                self._exec.aux_dict[n]._set_data(v)
        self._exec.outputs = [NDArray(o, self._context[0]) for o in outs]
        self._fused_ran = True

    # -- compute --------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self._sync_pipeline()
        self._sync_zero3()
        if is_train is None:
            is_train = self.for_training
        inputs = {}
        for name, arr in zip(self._data_names, data_batch.data):
            inputs[name] = arr
        if self._label_names and data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                inputs[name] = arr
        # rebind on batch-size change (reference reshapes executors)
        cur = self._exec.arg_dict[self._data_names[0]].shape
        new = inputs[self._data_names[0]].shape
        if cur != new:
            self._exec = self._exec.reshape(
                **{k: v.shape for k, v in inputs.items()})
        self._exec.forward(is_train=is_train, **inputs)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        if getattr(self, "_fused", None) is not None and \
                self._exec._monitor_callback is None:
            # an installed Monitor needs the per-node executor path; the
            # fused one-program step has no node boundaries to observe
            self._fused_forward_backward_update(data_batch)
            return
        self.forward(data_batch, is_train=True)
        self.backward()

    def update(self):
        """Push gradients / pull weights (reference ``Module.update`` →
        ``_update_params_on_kvstore``, priority=-index for comm overlap)."""
        assert self.binded and self.params_initialized and \
            self.optimizer_initialized
        if getattr(self, "_fused_ran", False):
            self._fused_ran = False  # fused step already applied the update
            self._async_tick()
            return
        o = self._optimizer
        if o is not None and (getattr(o, "clip_global_norm", None)
                              or getattr(self, "_health_monitor", None)
                              is not None):
            self._split_health_pass()
        if self._kvstore:
            # one batched push in priority order (priority=-i: earliest
            # layers first, the reference's overlap hint order,
            # model.py:105-116); the kvstore reduces the whole batch in
            # a single DCN round trip instead of one per key
            live = [(i, name) for i, name in enumerate(self._param_names)
                    if self._exec.grad_dict.get(name) is not None]
            keys = [i for i, _ in live]
            grads = [self._exec.grad_dict[name] for _, name in live]
            self._kvstore.push(keys, grads, priority=0)
            if self._update_on_kvstore:
                self._kvstore.pull(
                    keys, [self._exec.arg_dict[name] for _, name in live])
            else:
                merged = [zeros(g.shape, g.context) for g in grads]
                self._kvstore.pull(keys, merged)
                for (i, name), m in zip(live, merged):
                    self._updater(i, m, self._exec.arg_dict[name])
        else:
            for i, name in enumerate(self._param_names):
                w = self._exec.arg_dict[name]
                g = self._exec.grad_dict.get(name)
                if g is not None:
                    self._updater(i, g, w)
        self._async_tick()

    def _split_health_pass(self):
        """Split-path analogue of the in-step sentinel: one lazy pass
        over ``grad_dict`` computing the global norm, applying
        ``clip_global_norm``, and zeroing the gradients on a non-finite
        batch so the update is skipped.  All ops trace asynchronously —
        no host sync.  Unlike the fused path the skip is APPROXIMATE:
        momentum still decays and weight decay still applies over the
        zeroed gradients (the bit-exact guarantee is the fused path's)."""
        import jax.numpy as jnp

        o = self._optimizer
        names = [n for n in self._param_names
                 if self._exec.grad_dict.get(n) is not None]
        if not names:
            return
        grads = {n: self._exec.grad_dict[n]._data for n in names}
        gnorm = opt.global_grad_norm(grads, o.rescale_grad)
        finite = jnp.isfinite(gnorm)
        factor = jnp.asarray(1.0, "float32")
        if getattr(o, "clip_global_norm", None):
            factor = opt.global_norm_scale(gnorm, o.clip_global_norm)
        zero_bad = getattr(self, "_health_monitor", None) is not None
        if zero_bad:
            self._last_health_stats = {"grad_norm": gnorm,
                                       "nonfinite": ~finite}
        for n in names:
            g = grads[n] * factor.astype(grads[n].dtype)
            if zero_bad:
                # 0 * NaN is NaN — a multiplicative skip would leak the
                # poison into the optimizer state, so select instead
                g = jnp.where(finite, g, jnp.zeros_like(g))
            self._exec.grad_dict[n]._set_data(g)

    def _async_params(self):
        # aux states (BN moving stats) average too — per-shard moving
        # stats would diverge without bound otherwise
        return [self._exec.arg_dict[n] for n in self._param_names] + \
               [self._exec.aux_dict[n] for n in self._aux_names]

    def _async_tick(self):
        kv = self._kvstore
        if kv is not None and getattr(kv, "_is_async", False):
            kv._async_tick(self._async_params)

    def _epoch_end_sync(self):
        """dist_async: epoch-boundary parameter-averaging round (the
        always-on bounded-staleness sync point)."""
        kv = self._kvstore
        if kv is not None and getattr(kv, "_is_async", False):
            kv.sync_params(self._async_params())

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, outputs=None):
        from ..executor_manager import pair_metric_outputs

        outs = self._exec.outputs if outputs is None else outputs
        eval_metric.update(labels, pair_metric_outputs(
            self._symbol, self._label_names, labels, outs))

    def install_monitor(self, monitor):
        assert self.binded
        monitor.install(self._exec)

    # -- checkpoint -----------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Reference format contract: ``prefix-symbol.json`` +
        ``prefix-%04d.params`` (``module.py:152``)."""
        from ..model import save_checkpoint

        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(symbol=sym, **kwargs)
        mod._arg_params = args
        mod._aux_params = auxs
        mod._preloaded_params = (args, auxs)
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        # defer set_params until bind; stash for init_params
        orig_init = mod.init_params

        def init_with_loaded(initializer=None, arg_params=None,
                             aux_params=None, **kw):
            orig_init(initializer=initializer,
                      arg_params=arg_params or args,
                      aux_params=aux_params or auxs, **kw)
        mod.init_params = init_with_loaded
        return mod

    def save_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if getattr(self, "_fused_states", None) is not None:
            # sync live fused states back into the updater structure so
            # the on-disk format is identical to the split path's
            import pickle

            o = self._optimizer
            src = self._fused_states
            fused = getattr(self, "_fused", None)
            if fused is not None and getattr(fused, "zero_axis", None):
                import jax

                if jax.process_count() > 1:
                    raise MXNetError(
                        "save_optimizer_states cannot pickle ZeRO-sharded "
                        "state in a multi-process run (remote shards are "
                        "not addressable from this host); save through "
                        "the v2 elastic CheckpointManager instead")
                from ..parallel import zero as _zero

                pdict = {n: self._exec.arg_dict[n]._data
                         for n in self._param_names}
                lay = fused.zero_layout(pdict)
                src = {n: _zero.unshard_state(src[n], lay[n])
                       for n in src}
            states = {i: o.fused_state_to_nd(src[n], self._context[0])
                      for i, n in enumerate(self._param_names)}
            with open(fname, "wb") as f:
                f.write(pickle.dumps(states))
            return
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore and self._kvstore.updater is not None:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())
        # force the fused path to re-seed from the freshly loaded states
        # (and drop any stale canonical ZeRO handover)
        self._preloaded_zero_states = None
        self._fused_states = None

    def reshape(self, data_shapes, label_shapes=None):
        assert self.binded
        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = [_as_desc(l) for l in (label_shapes or [])]
        shapes = {d.name: d.shape for d in self._data_shapes}
        shapes.update({l.name: l.shape for l in self._label_shapes})
        self._exec = self._exec.reshape(**shapes)


def _as_desc(d):
    from ..io import DataDesc

    if isinstance(d, DataDesc):
        return d
    name, shape = d[0], d[1]
    return DataDesc(name, shape)


def _create_kvstore(kvstore, num_device, arg_params):
    """Reference ``model.py:57`` ``_create_kvstore``: decide the store and
    whether updates run on it."""
    if kvstore is None:
        return None, False
    if isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            return None, False
        kv = kvs.create(kvstore)
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    # update_on_kvstore: the reference defaults True unless explicitly
    # disabled via MXNET_UPDATE_ON_KVSTORE=0 (env_var.md) — then the
    # worker-side updater runs on pulled merged gradients instead
    from ..base import get_env

    update_on_kvstore = get_env("MXNET_UPDATE_ON_KVSTORE", True, bool)
    if getattr(kv, "_is_async", False):
        # dist_async updates are LOCAL by design; pulling weights from
        # the store's private copies would undo the averaging rounds
        # (sync_params rewrites the executor arrays, not the store)
        update_on_kvstore = False
    return kv, update_on_kvstore
