"""SequentialModule + PythonModule (reference
``python/mxnet/module/sequential_module.py`` / ``python_module.py``)."""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule

__all__ = ["SequentialModule", "PythonModule", "PythonLossModule"]


class SequentialModule(BaseModule):
    """Chain modules: each module's outputs feed the next one's data
    (reference ``SequentialModule``).  ``add(mod, take_labels=True)``
    marks the module that receives the iterator's labels (typically the
    loss head)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False

    def add(self, module, **kwargs):
        self._modules.append(module)
        self._metas.append(kwargs)
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        assert self.binded
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._modules[-1].output_shapes

    def get_params(self):
        assert self.binded and self.params_initialized
        arg_params, aux_params = {}, {}
        for mod in self._modules:
            arg, aux = mod.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False,
                    force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        assert self.binded
        for mod in self._modules:
            mod.init_params(initializer=initializer, arg_params=arg_params,
                            aux_params=aux_params,
                            allow_missing=True if arg_params is not None
                            else allow_missing,
                            force_init=force_init, allow_extra=True)
        self.params_initialized = True

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if not self._modules:
            raise MXNetError("SequentialModule has no modules; call add()")
        assert shared_module is None
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        cur_shapes = data_shapes
        n = len(self._modules)
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, i == n - 1)
            mod.bind(cur_shapes,
                     label_shapes if take_labels else None,
                     for_training=for_training,
                     inputs_need_grad=inputs_need_grad or i > 0,
                     force_rebind=force_rebind, grad_req=grad_req)
            # next module's data shapes = this module's output shapes,
            # named by ITS data_names (auto wiring)
            if i + 1 < n:
                nxt = self._modules[i + 1]
                out_shapes = mod.output_shapes
                if len(out_shapes) != len(nxt.data_names):
                    raise MXNetError(
                        "cannot wire module %d (%d outputs) into module "
                        "%d (%d data inputs)" % (i, len(out_shapes),
                                                 i + 1,
                                                 len(nxt.data_names)))
                cur_shapes = [(name, shape) for name, shape in
                              zip(nxt.data_names, out_shapes)]
        self.binded = True

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        for mod in self._modules:
            mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                               optimizer_params=optimizer_params,
                               force_init=force_init)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        from ..io import DataBatch

        data = data_batch.data
        n = len(self._modules)
        for i, (mod, meta) in enumerate(zip(self._modules, self._metas)):
            take_labels = meta.get(self.META_TAKE_LABELS, i == n - 1)
            batch = DataBatch(
                data=data,
                label=data_batch.label if take_labels else None,
                pad=data_batch.pad, index=data_batch.index)
            mod.forward(batch, is_train=is_train)
            if i + 1 < n:
                data = mod.get_outputs()

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        grads = out_grads
        for i, mod in reversed(list(enumerate(self._modules))):
            mod.backward(out_grads=grads)
            if i > 0:
                grads = mod.get_input_grads()

    def update(self):
        assert self.optimizer_initialized
        for mod in self._modules:
            mod.update()

    def _epoch_end_sync(self):
        for mod in self._modules:
            mod._epoch_end_sync()

    def get_outputs(self, merge_multi_context=True):
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        for mod, meta in zip(self._modules, self._metas):
            if meta.get(self.META_TAKE_LABELS,
                        mod is self._modules[-1]):
                mod.update_metric(eval_metric, labels)

    def install_monitor(self, monitor):
        for mod in self._modules:
            mod.install_monitor(monitor)


class PythonModule(BaseModule):
    """A module whose compute is arbitrary Python (reference
    ``PythonModule``): subclasses override ``forward``/``backward``;
    parameterless by default."""

    def __init__(self, data_names, label_names, output_names,
                 logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    def get_params(self):
        return {}, {}

    def init_params(self, *args, **kwargs):
        self.params_initialized = True

    def init_optimizer(self, *args, **kwargs):
        self.optimizer_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_names:
            eval_metric.update(labels, self.get_outputs())

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.binded = True

    def _compute_output_shapes(self):
        raise NotImplementedError


class PythonLossModule(PythonModule):
    """Pass-through loss head in Python (reference ``PythonLossModule``):
    forward is identity; the gradient function is user-supplied."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, [name + "_output"],
                         logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        d = self._data_shapes[0]
        shape = d.shape if hasattr(d, "shape") else d[1]
        return [shape]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is not None:
            self._scores_grad = self._grad_func(self._scores, self._labels)
        else:
            raise MXNetError("PythonLossModule needs grad_func")

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]

    def install_monitor(self, monitor):
        pass
