"""Monitor — per-node tensor statistics during forward.

Reference: ``python/mxnet/monitor.py:33`` over the executor's
``monitor_callback`` (``ExecuteMonCallback`` fires per node output).

TPU note: the compiled forward is ONE XLA program with no per-node
boundary, so an installed monitor switches the executor into the eager
node-by-node interpretation of the same graph (``Executor`` monitor
mode — also the framework's NaiveEngine-style synchronous debug mode,
reference ``MXNET_ENGINE_TYPE=NaiveEngine`` / SURVEY.md §5 "race
detection").  Slow by design; a debugging tool, exactly like the
reference's.
"""
from __future__ import annotations

import re

from .base import MXNetError

__all__ = ["Monitor", "STAT_FUNCS"]


def _mean_abs(x):
    import jax.numpy as jnp

    return jnp.abs(x).mean()


def _nan_count(x):
    """Count of non-finite (NaN/Inf) elements — the debugging companion
    of the run-health sentinel: ``Monitor(1, stat_func='nan_count')``
    names WHICH node first went bad, where the in-step flag only says
    that one did."""
    import jax.numpy as jnp

    if not jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.zeros((), "int32")
    return (~jnp.isfinite(x)).sum().astype("int32")


# built-in stat funcs, selectable by name: Monitor(1, 'nan_count')
STAT_FUNCS = {"mean_abs": _mean_abs, "nan_count": _nan_count}


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            stat_func = _mean_abs
        elif isinstance(stat_func, str):
            if stat_func not in STAT_FUNCS:
                raise MXNetError(
                    "unknown stat_func %r (built-ins: %s; or pass a "
                    "callable)" % (stat_func, sorted(STAT_FUNCS)))
            stat_func = STAT_FUNCS[stat_func]
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, arr):
        """Executor callback: collect stats for matching node outputs."""
        if not self.activated or not self.re_pattern.match(name):
            return
        data = arr._data if hasattr(arr, "_data") else arr
        self.queue.append((self.step, name, self.stat_func(data)))

    def install(self, exe):
        """Install on an executor (reference ``Monitor.install`` →
        ``set_monitor_callback``)."""
        exe.set_monitor_callback(self.stat_helper, monitor_all=True)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; return [(step, name, stat)] with stats
        realized on host — ONE batched ``jax.device_get`` for the whole
        queue instead of a blocking round trip per node (a monitored
        net has hundreds of nodes; per-value realization serialized a
        device ping for each)."""
        import numpy as np

        if not self.activated:
            return []
        self.activated = False
        queue, self.queue = self.queue, []
        if not queue:
            return []
        try:
            import jax

            values = jax.device_get([v for _, _, v in queue])
        except Exception:  # host-side stat funcs (plain numpy) pass through
            values = [np.asarray(v) for _, _, v in queue]
        res = []
        for (step, name, _), v in zip(queue, values):
            v = np.asarray(v)
            res.append((step, name,
                        v.reshape(-1) if v.ndim else v[()]))
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            print("Batch: %7d %30s %s" % (step, name, value))
