"""Monitor — per-node tensor statistics during forward.

Reference: ``python/mxnet/monitor.py:33`` over the executor's
``monitor_callback`` (``ExecuteMonCallback`` fires per node output).

TPU note: the compiled forward is ONE XLA program with no per-node
boundary, so an installed monitor switches the executor into the eager
node-by-node interpretation of the same graph (``Executor`` monitor
mode — also the framework's NaiveEngine-style synchronous debug mode,
reference ``MXNET_ENGINE_TYPE=NaiveEngine`` / SURVEY.md §5 "race
detection").  Slow by design; a debugging tool, exactly like the
reference's.
"""
from __future__ import annotations

import re

from .base import MXNetError

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                import jax.numpy as jnp

                return jnp.abs(x).mean()
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_pattern = re.compile(pattern)
        self.sort = sort

    def stat_helper(self, name, arr):
        """Executor callback: collect stats for matching node outputs."""
        if not self.activated or not self.re_pattern.match(name):
            return
        data = arr._data if hasattr(arr, "_data") else arr
        self.queue.append((self.step, name, self.stat_func(data)))

    def install(self, exe):
        """Install on an executor (reference ``Monitor.install`` →
        ``set_monitor_callback``)."""
        exe.set_monitor_callback(self.stat_helper, monitor_all=True)
        self.exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval has elapsed."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; return [(step, name, stat)] with stats
        realized on host."""
        import numpy as np

        if not self.activated:
            return []
        self.activated = False
        res = []
        for step, name, value in self.queue:
            v = np.asarray(value)
            res.append((step, name,
                        v.reshape(-1) if v.ndim else v[()]))
        self.queue = []
        if self.sort:
            res.sort(key=lambda x: x[1])
        return res

    def toc_print(self):
        for step, name, value in self.toc():
            print("Batch: %7d %30s %s" % (step, name, value))
