"""Automatic symbol naming (reference ``python/mxnet/name.py``).

``NameManager`` assigns sequential names (``convolution0``, ``convolution1``
…) to anonymously-created symbols; ``Prefix`` prepends a scope prefix.  Both
are context managers and nest, exactly like the reference's
``NameManager.current`` stack — this is what makes two separately-built
networks get disjoint parameter names.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix", "current"]

_local = threading.local()


def current():
    """The innermost active NameManager (a default one if none entered)."""
    stack = getattr(_local, "stack", None)
    if not stack:
        _local.stack = [NameManager()]
        stack = _local.stack
    return stack[-1]


class NameManager:
    """Sequential auto-namer; ``with NameManager():`` scopes the counters so
    names restart from 0 inside the block (reference ``name.py:20-73``)."""

    def __init__(self):
        self._counter = {}

    def get(self, name, hint):
        if name is not None:
            return name
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    def __enter__(self):
        if not hasattr(_local, "stack"):
            _local.stack = [NameManager()]
        _local.stack.append(self)
        return self

    def __exit__(self, *exc):
        _local.stack.pop()


class Prefix(NameManager):
    """NameManager that prepends ``prefix`` to every generated name
    (reference ``name.py:76-97``)::

        with mx.name.Prefix("resnet_"):
            net = build()   # parameters named resnet_convolution0_weight …
    """

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return self._prefix + super().get(name, hint)
