"""The ``mx.nd`` namespace.

Like the reference, every operator function here is **generated from the
registry** at import time (reference: ``_init_ndarray_module`` builds one
Python function per registered op via the C ABI op list,
``python/mxnet/ndarray/op.py:174-209``).  ``mx.nd.relu``, ``mx.nd.dot``,
``mx.nd.Convolution`` … all dispatch through
:func:`mxnet_tpu.ndarray.ndarray.imperative_invoke`.
"""
from __future__ import annotations

import sys as _sys

import numpy as _np

from ..ops import registry as _registry
from .ndarray import (NDArray, imperative_invoke, array, empty, zeros, ones,
                      full, arange, moveaxis, concat, save, load, waitall,
                      onehot_encode)

_INIT_OPS = {"_zeros", "zeros", "_ones", "ones", "_full", "full", "_arange",
             "arange", "_eye", "eye"}  # handled by the creation helpers above
_RESERVED = {"array", "empty", "save", "load", "concat", "moveaxis",
             "waitall", "onehot_encode",
             # creation helpers take (shape, ctx, dtype) signatures
             "zeros", "ones", "full", "arange", "eye"}


def _make_op_func(name, op):
    def op_func(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)  # symbol-compat no-op
        ctx = kwargs.pop("ctx", None)
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif isinstance(a, _np.ndarray):
                inputs.append(array(a, ctx))
            elif isinstance(a, (list, tuple)) and not inputs:
                inputs.append(array(a, ctx))
            else:
                raise TypeError(
                    "%s: positional args must be NDArray, got %r" % (name, a))
        # NDArray keyword arguments are tensor inputs in the reference call
        # style (nd.FullyConnected(data=x, weight=w, ...)) — order them by
        # the op's declared argument names, not into attrs
        named = {k: v for k, v in kwargs.items()
                 if isinstance(v, (NDArray, _np.ndarray))}
        if named:
            from ..ops.op_names import expected_inputs

            for k in named:
                kwargs.pop(k)
            attrs_only = {k: v for k, v in kwargs.items()}
            arg_names, aux_names = expected_inputs(name, attrs_only)
            ordered = []
            for an in list(arg_names) + list(aux_names):
                if an in named:
                    v = named.pop(an)
                    ordered.append(v if isinstance(v, NDArray)
                                   else array(v, ctx))
                elif inputs:
                    ordered.append(inputs.pop(0))
            if named:
                raise TypeError("%s: unexpected tensor kwargs %s"
                                % (name, sorted(named)))
            inputs = ordered + inputs
        res = imperative_invoke(name, inputs, kwargs, out=out)
        if ctx is not None and not inputs:
            res = [r.as_in_context(ctx) for r in res]
        return res[0] if len(res) == 1 else res

    op_func.__name__ = name
    op_func.__qualname__ = name
    op_func.__doc__ = op.describe()
    return op_func


def _init_module():
    mod = _sys.modules[__name__]
    for name in _registry.list_ops():
        if name in _RESERVED:
            continue
        func = _make_op_func(name, _registry.get(name))
        setattr(mod, name, func)


_init_module()


from . import sparse  # noqa: E402  (storage types; reference nd.sparse)
from .sparse import BaseSparseNDArray, CSRNDArray, RowSparseNDArray  # noqa
from .cached_op import CachedOp  # noqa: E402  (reference nd.CachedOp)


def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kwargs):
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        return sparse.dot(lhs, rhs, transpose_a=transpose_a,
                          transpose_b=transpose_b)
    return imperative_invoke("dot", [lhs, rhs], {
        "transpose_a": transpose_a, "transpose_b": transpose_b})[0]


def cast_storage(arr, stype):
    """Convert an array's storage type (reference ``nd.cast_storage``)."""
    return sparse.cast_storage(arr, stype)


def split(data, num_outputs, axis=1, squeeze_axis=False, **kwargs):
    return imperative_invoke("SliceChannel", [data], {
        "num_outputs": num_outputs, "axis": axis,
        "squeeze_axis": squeeze_axis})


def stack(*data, axis=0):
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = tuple(data[0])
    return imperative_invoke("stack", list(data), {"axis": axis})[0]


def add_n(*args):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return imperative_invoke("add_n", list(args), {})[0]


elemwise_sum = add_n
ElementWiseSum = add_n


# -- host image ops (reference src/io/image_io.cc registers _cvimdecode /
# _cvimread / _cvimresize / _cvcopyMakeBorder as CPU-only ops; decode is
# host work by nature, so here they are host functions returning NDArrays
# rather than jitted registry entries) -------------------------------------

def _cvimdecode(buf, flag=1, to_rgb=1, **kwargs):
    from .. import image as _image

    return array(_image.imdecode(buf, to_rgb=to_rgb, flag=flag))


def _cvimread(filename, flag=1, **kwargs):
    from .. import image as _image

    return array(_image.imread(filename, flag=flag))


def _cvimresize(src, w, h, interp=2, **kwargs):
    from .. import image as _image

    return array(_image.imresize(_np.asarray(src.asnumpy() if isinstance(
        src, NDArray) else src), int(w), int(h), int(interp)))


def _cvcopyMakeBorder(src, top, bot, left, right, fill_value=0, **kwargs):
    from .. import image as _image

    return array(_image.copyMakeBorder(_np.asarray(
        src.asnumpy() if isinstance(src, NDArray) else src),
        int(top), int(bot), int(left), int(right), fill_value))


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0,
             channels=3, mean=None):
    """Decode with the reference's pre-1.0 ``nd.imdecode`` signature
    (``python/mxnet/ndarray.py``): optional crop rectangle
    ``(x0, y0, x1, y1)``, channel count, and mean subtraction."""
    from .. import image as _image

    arr = _image.imdecode(str_img, flag=1 if channels == 3 else 0)
    x0, y0, x1, y1 = clip_rect
    if (x0, y0, x1, y1) != (0, 0, 0, 0):
        arr = arr[y0:y1, x0:x1]
    arr = arr.astype(_np.float32)
    if mean is not None:
        arr = arr - (mean.asnumpy() if isinstance(mean, NDArray) else
                     _np.asarray(mean))
    res = array(arr)
    if out is not None:
        out[:] = res
        return out
    return res
