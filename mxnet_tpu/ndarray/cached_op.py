"""CachedOp — bind a Symbol once, invoke imperatively many times
(reference ``src/c_api/c_api_ndarray.cc:611-660`` ``MXCreateCachedOp`` /
``MXInvokeCachedOp``; Python ``mxnet.ndarray.CachedOp``).

TPU-native stance: the reference replays the graph through its
imperative engine per call; here the whole graph jit-compiles into ONE
XLA program per (shapes, dtypes, train-mode) key — the same inversion as
``Executor`` — and subsequent calls with the same signature are a single
dispatch.  Under ``autograd.record()`` the invocation lands on the tape
as ONE entry whose replay is the traced graph function, so
``autograd.backward`` differentiates through it exactly.

The per-signature jit cache is LRU-bounded by
``MXNET_CACHED_OP_CACHE_SIZE`` (default 32) and registered with the
process-wide recompile registry (``mxnet_tpu.compile_cache``): a
CachedOp fed drifting shapes warns past ``MXNET_RECOMPILE_WARN``
distinct signatures instead of silently recompiling forever.
"""
from __future__ import annotations

from collections import OrderedDict

from ..base import MXNetError, get_env

__all__ = ["CachedOp"]


class _GraphOp:
    """Synthetic registry-op view of a traced symbol graph: what the
    autograd tape needs to replay a CachedOp invocation as one entry."""

    needs_rng = True
    uses_train_mode = False
    mutable_inputs = ()

    def __init__(self, name, fn, arg_names):
        self.name = name
        self._fn = fn
        self._arg_names = arg_names

    def compute(self, attrs, rng, *ins):
        args = dict(zip(self._arg_names, ins))
        outs, _aux = self._fn(args, {}, rng)
        return outs if len(outs) > 1 else outs[0]


class CachedOp:
    """``CachedOp(sym)(*inputs)``: inputs follow ``list_arguments()``
    order, then ``list_auxiliary_states()`` order (the reference's
    ``ListInputs(kAll)`` flattening, grouped args-then-aux here).  Aux
    state updates (BatchNorm moving stats) write back into the passed
    aux NDArrays, mirroring the reference's mutable-input contract."""

    def __init__(self, sym):
        from ..compile_cache import ensure_initialized, registry

        ensure_initialized()
        self._sym = sym
        self._arg_names = list(sym.list_arguments())
        self._aux_names = list(sym.list_auxiliary_states())
        # LRU-bounded: one jit per (train-mode, shapes, dtypes) signature
        self._jit_cache = OrderedDict()
        self._jit_cache_size = max(
            1, get_env("MXNET_CACHED_OP_CACHE_SIZE", 32, int))
        self._recompile_guard = registry.guard(
            "CachedOp(%s)" % (getattr(sym, "name", None) or "graph"))
        self._trace_cache = {}

    @property
    def num_inputs(self):
        return len(self._arg_names) + len(self._aux_names)

    def _traced(self, is_train):
        from ..executor import _trace_fn

        if is_train not in self._trace_cache:
            self._trace_cache[is_train] = _trace_fn(
                self._sym, is_train=is_train)[0]
        return self._trace_cache[is_train]

    def __call__(self, *args):
        import jax

        from .. import autograd
        from .. import random as _random
        from .ndarray import NDArray

        expect = self.num_inputs
        if len(args) != expect:
            raise MXNetError(
                "CachedOp expects %d inputs (%d arguments + %d aux "
                "states), got %d" % (expect, len(self._arg_names),
                                     len(self._aux_names), len(args)))
        nds = [a if isinstance(a, NDArray) else NDArray(a) for a in args]
        arg_nds = nds[:len(self._arg_names)]
        aux_nds = nds[len(self._arg_names):]
        is_train = autograd.is_training()
        recording = autograd.is_recording()
        rng = _random.next_key()

        if recording:
            if self._aux_names:
                raise MXNetError(
                    "CachedOp under autograd.record() does not support "
                    "aux-state symbols (%s) — BatchNorm moving-stat "
                    "mutation has no gradient meaning on the tape; run "
                    "outside record() or use use_global_stats"
                    % self._aux_names)
            fn = self._traced(is_train)
            gop = _GraphOp("cached_op", fn, self._arg_names)
            bufs = [x._data for x in arg_nds]
            outs = gop.compute(None, rng, *bufs)
            if not isinstance(outs, tuple):
                outs = (outs,)
            out_nds = [NDArray(o, arg_nds[0].context if arg_nds else None)
                       for o in outs]
            autograd._record(gop, None, arg_nds, [rng] + bufs, out_nds,
                             list(outs), rng)
            return out_nds if len(out_nds) > 1 else out_nds[0]

        key = (is_train,) + tuple(
            (tuple(x.shape), str(x.dtype)) for x in nds)
        names = self._arg_names + self._aux_names
        sig = ((".train", (is_train,)),) + tuple(
            (names[i], (tuple(x.shape), str(x.dtype), False))
            for i, x in enumerate(nds))
        if key not in self._jit_cache:
            fn = self._traced(is_train)

            def run(arg_bufs, aux_bufs, k):
                args_d = dict(zip(self._arg_names, arg_bufs))
                aux_d = dict(zip(self._aux_names, aux_bufs))
                return fn(args_d, aux_d, k)

            # force=True: a rebuild after LRU eviction re-traces even
            # though the guard has seen this signature before
            self._recompile_guard.observe(sig, force=True)
            self._jit_cache[key] = jax.jit(run)
            while len(self._jit_cache) > self._jit_cache_size:
                self._jit_cache.popitem(last=False)
        else:
            self._jit_cache.move_to_end(key)
            self._recompile_guard.observe(sig)
        outs, new_aux = self._jit_cache[key](
            [x._data for x in arg_nds], [x._data for x in aux_nds], rng)
        # reference FMutateInputs contract: aux inputs are updated
        for name, nd in zip(self._aux_names, aux_nds):
            if name in new_aux:
                nd._set_data(new_aux[name])
        ctx = arg_nds[0].context if arg_nds else None
        out_nds = [NDArray(o, ctx) for o in outs]
        return out_nds if len(out_nds) > 1 else out_nds[0]
