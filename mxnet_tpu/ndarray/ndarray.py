"""NDArray — the imperative value type, backed by XLA.

TPU-native replacement for the reference's ``NDArray``
(``include/mxnet/ndarray.h:93``, ``src/ndarray/ndarray.cc``, Python
``python/mxnet/ndarray/ndarray.py``).

Design mapping (SURVEY.md §7 items 1-3):

* the reference's ``Chunk`` (storage handle + engine variable) becomes an
  immutable ``jax.Array`` reference that is **rebound** on mutation — a
  version chain instead of in-place writes.  JAX/XLA's async dispatch *is*
  the dependency engine: ops on the same buffer are ordered by data flow,
  and ``wait_to_read`` maps to ``jax.block_until_ready`` (reference
  ``WaitToRead``, ``ndarray.h:336``).
* every operator call goes through :func:`imperative_invoke` — the analogue
  of ``MXImperativeInvoke`` (``src/c_api/c_api_ndarray.cc:548``): gather
  input buffers, run the op's cached jitted executable, wrap outputs, write
  back functionally-threaded state (``mutable_inputs``), and record on the
  autograd tape when recording is active.
* ``context`` moves data with ``jax.device_put`` (reference ``CopyFromTo``
  with kCopyFromGPU/kCopyToGPU FnProperty, ``src/ndarray/ndarray.cc:499``).
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from .. import random as _random
from ..ops import registry as _registry

__all__ = ["NDArray", "imperative_invoke", "array", "empty", "zeros", "ones",
           "full", "arange", "moveaxis", "concat", "save", "load", "waitall",
           "onehot_encode"]


def _as_jax(value, dtype=None, ctx=None):
    import jax

    dev = (ctx or current_context()).jax_device
    arr = _np.asarray(value, dtype=dtype if dtype else None)
    if arr.dtype == _np.float64 and dtype is None:
        arr = arr.astype(_np.float32)
    return jax.device_put(arr, dev)


class NDArray:
    """A multidimensional array on a device context.

    Mirrors the reference Python ``NDArray`` API surface: shape/dtype/size,
    ``asnumpy``/``asscalar``, arithmetic operators, indexing/assignment,
    ``copyto``/``as_in_context``, ``wait_to_read``, ``astype``, ``reshape``,
    ``T`` …  The backing buffer is an immutable ``jax.Array``; "mutation"
    rebinds ``_data`` and bumps ``_version`` (engine write-ordering made
    explicit).
    """

    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req",
                 "_tape_marked", "__weakref__")

    def __init__(self, data, ctx=None):
        self._data = data
        self._ctx = ctx or current_context()
        self._version = 0
        self._grad = None
        self._grad_req = None
        self._tape_marked = False

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def size(self):
        s = 1
        for d in self.shape:
            s *= d
        return s

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def grad(self):
        """Gradient buffer attached by ``autograd.mark_variables`` /
        ``Parameter`` (reference: ``args_grad``)."""
        return self._grad

    @property
    def T(self):
        return transpose_nd(self)

    @property
    def handle(self):
        """The raw jax.Array (stands in for the C-ABI NDArrayHandle)."""
        return self._data

    stype = "default"

    def tostype(self, stype):
        """Convert storage type (reference ``NDArray.tostype``)."""
        if stype == "default":
            return self
        from .sparse import cast_storage

        return cast_storage(self, stype)

    def todense(self):
        return self

    # -- sync & host transfer ----------------------------------------------
    def wait_to_read(self):
        import jax

        jax.block_until_ready(self._data)

    wait_to_write = wait_to_read

    def asnumpy(self):
        self.wait_to_read()
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def astype(self, dtype):
        return imperative_invoke("Cast", [self], {"dtype": _np.dtype(dtype).name})[0]

    def copy(self):
        return imperative_invoke("_copy", [self], {})[0]

    def copyto(self, other):
        import jax

        if isinstance(other, NDArray):
            other._set_data(jax.device_put(self._data, other._ctx.jax_device))
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), other)
        raise MXNetError("copyto target must be NDArray or Context")

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def detach(self):
        from .. import autograd

        if autograd.is_recording():
            # route through BlockGrad so the tape records a stop_gradient —
            # sharing the raw buffer would let replay differentiate through
            # the "detached" value
            return imperative_invoke("BlockGrad", [self], {})[0]
        return NDArray(self._data, self._ctx)

    def clip(self, a_min, a_max):
        return imperative_invoke("clip", [self],
                                 {"a_min": a_min, "a_max": a_max})[0]

    # -- mutation (engine write semantics) ----------------------------------
    def _set_data(self, data):
        self._data = data
        self._version += 1

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        if isinstance(value, NDArray):
            value = value._data
        if key is Ellipsis or key == slice(None):
            import jax

            # materialize on THIS array's context device: jnp.* default to
            # the default platform, which silently migrates a cpu-context
            # param to the accelerator on multi-platform hosts
            dev = self._ctx.jax_device
            if _np.isscalar(value):
                self._set_data(jax.device_put(
                    jnp.full(self.shape, value, self.dtype), dev))
            else:
                arr = _as_jax(value, self.dtype, self._ctx) \
                    if not hasattr(value, "dtype") or isinstance(value, _np.ndarray) else value
                self._set_data(jax.device_put(
                    jnp.broadcast_to(arr, self.shape).astype(self.dtype), dev))
            return
        if isinstance(value, _np.ndarray):
            value = _as_jax(value, self.dtype, self._ctx)
        self._set_data(self._data.at[key].set(value))

    def __getitem__(self, key):
        from .. import autograd

        if autograd.is_recording():
            # route the common cases through registered ops so indexing is
            # on the tape (raw buffer indexing would silently cut gradients)
            if isinstance(key, int):
                k = key % self.shape[0] if self.shape else key
                out = imperative_invoke(
                    "slice_axis", [self],
                    {"axis": 0, "begin": k, "end": k + 1})[0]
                return imperative_invoke(
                    "Reshape", [out], {"shape": self.shape[1:] or (1,)})[0]
            if isinstance(key, slice) and key.step in (None, 1):
                b = 0 if key.start is None else key.start
                e = self.shape[0] if key.stop is None else key.stop
                return imperative_invoke(
                    "slice_axis", [self],
                    {"axis": 0, "begin": b, "end": e})[0]
        out = self._data[key]
        return NDArray(out, self._ctx)

    def reshape(self, shape, **kwargs):
        if isinstance(shape, int):
            shape = (shape,)
        return imperative_invoke("Reshape", [self],
                                 {"shape": tuple(shape), **kwargs})[0]

    def expand_dims(self, axis):
        return imperative_invoke("expand_dims", [self], {"axis": axis})[0]

    def flatten(self):
        return imperative_invoke("Flatten", [self], {})[0]

    def attach_grad(self, grad_req="write"):
        """Allocate gradient buffer and mark for autograd (Gluon-style;
        reference ``python/mxnet/ndarray/ndarray.py`` + autograd)."""
        from .. import autograd

        grad = zeros(self.shape, self._ctx, dtype=self.dtype)
        autograd.mark_variables([self], [grad], grad_req)

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    def __getattr__(self, name):
        # any registered op is available as a method with self as first
        # input (the reference generates these on the NDArray class from
        # the registry, python/mxnet/ndarray/op.py)
        if name.startswith("_") or not _registry.exists(name):
            raise AttributeError(
                "'NDArray' object has no attribute %r" % name)

        def method(*args, **kwargs):
            bad = [a for a in args if not isinstance(a, NDArray)]
            if bad:
                raise TypeError(
                    "NDArray.%s: pass scalar attributes as keywords "
                    "(got positional %r)" % (name, bad[0]))
            inputs = [self] + list(args)
            res = imperative_invoke(name, inputs, kwargs)
            return res[0] if len(res) == 1 else res

        method.__name__ = name
        return method

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        return self.shape[0] if self.shape else 0

    def __repr__(self):
        return "%s\n<NDArray %s @%s>" % (
            str(self.asnumpy()), "x".join(str(s) for s in self.shape), self._ctx)

    def __bool__(self):
        if self.size != 1:
            raise MXNetError("ambiguous truth value of multi-element NDArray")
        return bool(self.asscalar())

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # arithmetic — routed through the registry so autograd records them
    def _binary(self, other, op, scalar_op, rop=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if rop else (self, other)
            return imperative_invoke(op, [a, b], {})[0]
        if rop and scalar_op.startswith("_r"):
            return imperative_invoke(scalar_op, [self], {"scalar": float(other)})[0]
        return imperative_invoke(scalar_op, [self], {"scalar": float(other)})[0]

    def __add__(self, o): return self._binary(o, "elemwise_add", "_plus_scalar")
    def __radd__(self, o): return self._binary(o, "elemwise_add", "_plus_scalar")
    def __sub__(self, o): return self._binary(o, "elemwise_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binary(o, "elemwise_sub", "_rminus_scalar", rop=True)
    def __mul__(self, o): return self._binary(o, "elemwise_mul", "_mul_scalar")
    def __rmul__(self, o): return self._binary(o, "elemwise_mul", "_mul_scalar")
    def __truediv__(self, o): return self._binary(o, "elemwise_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binary(o, "elemwise_div", "_rdiv_scalar", rop=True)
    def __mod__(self, o): return self._binary(o, "elemwise_mod", "_mod_scalar")
    def __rmod__(self, o): return self._binary(o, "elemwise_mod", "_rmod_scalar", rop=True)
    def __pow__(self, o): return self._binary(o, "elemwise_power", "_power_scalar")
    def __rpow__(self, o): return self._binary(o, "elemwise_power", "_rpower_scalar", rop=True)
    def __neg__(self): return imperative_invoke("negative", [self], {})[0]
    def __abs__(self): return imperative_invoke("abs", [self], {})[0]
    def __eq__(self, o): return self._binary(o, "elemwise_equal", "_equal_scalar")
    def __ne__(self, o): return self._binary(o, "elemwise_not_equal", "_not_equal_scalar")
    def __gt__(self, o): return self._binary(o, "elemwise_greater", "_greater_scalar")
    def __ge__(self, o): return self._binary(o, "elemwise_greater_equal", "_greater_equal_scalar")
    def __lt__(self, o): return self._binary(o, "elemwise_lesser", "_lesser_scalar")
    def __le__(self, o): return self._binary(o, "elemwise_lesser_equal", "_lesser_equal_scalar")
    __hash__ = object.__hash__

    def __iadd__(self, o):
        out = self.__add__(o)
        self._set_data(out._data)
        return self

    def __isub__(self, o):
        out = self.__sub__(o)
        self._set_data(out._data)
        return self

    def __imul__(self, o):
        out = self.__mul__(o)
        self._set_data(out._data)
        return self

    def __itruediv__(self, o):
        out = self.__truediv__(o)
        self._set_data(out._data)
        return self


def transpose_nd(arr):
    return imperative_invoke("transpose", [arr], {})[0]


# ---------------------------------------------------------------------------
# the imperative invoke path (≈ MXImperativeInvoke / ImperativeInvokeImpl)
# ---------------------------------------------------------------------------

def imperative_invoke(op_name, inputs, attrs, out=None):
    """Run one registered op imperatively.

    Returns a list of output NDArrays.  Handles: rng key injection, train
    mode, functional write-back of ``mutable_inputs``, ``out=`` targets, and
    autograd tape recording (reference
    ``AutogradRuntime::RecordImperativeFCompute``, ``src/ndarray/autograd.cc:104``).
    """
    from .. import autograd

    op = _registry.get(op_name)
    attrs = dict(attrs)
    op.validate_attrs(attrs)

    if op.uses_train_mode and "__is_train__" not in attrs:
        attrs["__is_train__"] = autograd.is_training()

    in_arrays = [x._data if isinstance(x, NDArray) else _as_jax(x)
                 for x in inputs]
    if op.spans_mesh is not None and op.spans_mesh(attrs):
        # the compute holds a shard_map over the active mesh: inputs must
        # live replicated on ALL mesh devices, not committed to one
        from ..parallel import current_mesh

        mesh = current_mesh()
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(mesh, PartitionSpec())
            in_arrays = [jax.device_put(a, repl) for a in in_arrays]
    rng_key = None
    if op.needs_rng:
        rng_key = _random.next_key()
        in_arrays = [rng_key] + in_arrays

    frozen = _registry.FrozenAttrs(attrs)
    results = _registry.invoke(op, in_arrays, frozen)

    n_out = op.count_outputs(frozen)
    outputs = results[:n_out]
    updates = results[n_out:]

    ctx = inputs[0]._ctx if inputs and isinstance(inputs[0], NDArray) \
        else current_context()

    # functional state write-back (≈ FMutateInputs)
    for idx, new_val in zip(op.mutable_inputs, updates):
        tgt = inputs[idx]
        if isinstance(tgt, NDArray):
            tgt._set_data(new_val)

    if out is not None:
        out_list = out if isinstance(out, (list, tuple)) else [out]
        for tgt, val in zip(out_list, outputs):
            tgt._set_data(val)
        out_nd = list(out_list)
    else:
        out_nd = [NDArray(o, ctx) for o in outputs]

    if autograd.is_recording():
        autograd._record(op, frozen, inputs, in_arrays, out_nd, outputs,
                         rng_key)
    return out_nd


# ---------------------------------------------------------------------------
# creation / io helpers (reference ndarray.py module functions)
# ---------------------------------------------------------------------------

def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    return NDArray(_as_jax(source_array, dtype, ctx), ctx or current_context())


def empty(shape, ctx=None, dtype="float32"):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype="float32", **kwargs):
    import jax

    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or current_context()
    return NDArray(jax.device_put(
        _np.zeros(shape, dtype or "float32"), ctx.jax_device), ctx)


def ones(shape, ctx=None, dtype="float32", **kwargs):
    import jax

    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or current_context()
    return NDArray(jax.device_put(
        _np.ones(shape, dtype or "float32"), ctx.jax_device), ctx)


def full(shape, val, ctx=None, dtype="float32"):
    import jax

    if isinstance(shape, int):
        shape = (shape,)
    ctx = ctx or current_context()
    return NDArray(jax.device_put(
        _np.full(shape, val, dtype or "float32"), ctx.jax_device), ctx)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32"):
    out = _np.arange(start, stop, step).astype(dtype or "float32")
    if repeat > 1:
        out = _np.repeat(out, repeat)
    return array(out, ctx, dtype)


def moveaxis(tensor, source, destination):
    axes = list(range(tensor.ndim))
    axes.remove(source % tensor.ndim)
    axes.insert(destination % tensor.ndim, source % tensor.ndim)
    return imperative_invoke("transpose", [tensor], {"axes": tuple(axes)})[0]


def concat(*data, **kwargs):
    dim = kwargs.get("dim", 1)
    if len(data) == 1 and isinstance(data[0], (list, tuple)):
        data = data[0]
    return imperative_invoke("Concat", list(data), {"dim": dim})[0]


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = imperative_invoke("one_hot", [indices], {"depth": depth})[0]
    out._set_data(res._data)
    return out


def waitall():
    """Block until all pending computation completes (reference
    ``MXNDArrayWaitAll``).  XLA dispatch is async exactly like the
    engine; this is where deferred execution errors surface, so
    exceptions propagate to the caller (the reference engine's fatal
    handler contract, ``threaded_engine.h:347``)."""
    import jax

    barrier = getattr(jax, "effects_barrier", None)
    if barrier is not None:
        barrier()
    else:  # older jax: synchronize via a device round-trip
        jax.device_put(0.0).block_until_ready()


# -- save/load: the reference's binary NDArray dict format is replaced by
#    the portable .npz container (documented divergence; the *API* —
#    nd.save/nd.load round-tripping dicts or lists — is identical to
#    python/mxnet/ndarray/utils.py save/load).

def save(fname, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        _np.savez(fname, **{k: v.asnumpy() for k, v in data.items()})
    else:
        _np.savez(fname, **{"__list_%d" % i: v.asnumpy()
                            for i, v in enumerate(data)})


def load(fname):
    with _np.load(fname if fname.endswith(".npz") else fname + ".npz"
                  if not _is_file(fname) else fname, allow_pickle=False) as f:
        keys = list(f.keys())
        if keys and all(k.startswith("__list_") for k in keys):
            return [array(f[k]) for k in sorted(
                keys, key=lambda s: int(s.split("_")[-1]))]
        return {k: array(f[k]) for k in keys}


def _is_file(fname):
    import os

    return os.path.exists(fname)
