"""Sparse NDArray storage types: ``row_sparse`` and ``csr``.

Reference: ``include/mxnet/ndarray.h:82-86`` (storage types),
``python/mxnet/ndarray/sparse.py`` (``CSRNDArray``/``RowSparseNDArray``),
and the FComputeEx sparse op set (SURVEY.md Appendix A): dot(csr, dense),
sparse_retain, square_sum, cast_storage, elemwise add, sparse sgd/adam
updates, kvstore row-sparse push/pull
(``src/kvstore/kvstore_dist.h:346-385``).

TPU-first design: a sparse array is a set of **static-shape component
arrays** (values + indices [+ indptr]) — the ragged encoding the SURVEY
names hard part (a).  Component shapes are fixed per instance, so every
sparse kernel jit-compiles per (nnz, dense-shape) exactly like the
reference's per-shape executable cache; imperative code with varying nnz
pays a recompile per new nnz, the same trade BucketingModule makes per
bucket.  Ops that have no sparse implementation fall back to dense
(reference storage-fallback, ``src/common/utils.h`` SetupDefaultBlobs)
via ``tostype('default')``.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "cast_storage", "zeros",
           "dot", "retain", "square_sum", "elemwise_add", "add_n",
           "sgd_update", "sgd_mom_update", "adam_update"]


class BaseSparseNDArray(NDArray):
    """Common behavior for sparse storage types.

    ``_data`` holds the values component; extra slots carry the index
    structure.  Dense-only operations transparently fall back through
    ``tostype('default')`` (storage-fallback semantics).
    """

    __slots__ = ("_sp_shape", "_indices", "_indptr", "_true_nnz")

    stype = "undefined"

    @property
    def shape(self):
        return self._sp_shape

    @property
    def dtype(self):
        return _np.dtype(self._data.dtype)

    @property
    def data(self):
        """The values component (reference ``.data``).  Under nnz
        bucketing the public view is sliced back to the true nnz —
        padding never leaks to host-side consumers (numpy indexing has
        no out-of-bounds drop)."""
        return NDArray(self._data[:self._public_nnz()], self._ctx)

    @property
    def indices(self):
        return NDArray(self._indices[:self._public_nnz()], self._ctx)

    def _public_nnz(self):
        n = getattr(self, "_true_nnz", None)
        return int(self._data.shape[0]) if n is None else n

    def asnumpy(self):
        return _np.asarray(self._to_dense_jax())

    def todense(self):
        return self.tostype("default")

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return NDArray(self._to_dense_jax(), self._ctx)
        return cast_storage(self, stype)

    def copy(self):
        # fresh wrapper sharing the immutable component buffers (dense
        # NDArray.copy has the same sharing-safety: mutation rebinds).
        # Components are sliced to the TRUE nnz first: feeding padded
        # buffers back through the constructor would re-pad and reset
        # _true_nnz to the padded length (sentinels would leak into the
        # public views and index unions)
        n = self._public_nnz()
        if isinstance(self, RowSparseNDArray):
            return RowSparseNDArray(self._data[:n], self._indices[:n],
                                    self._sp_shape, self._ctx)
        return CSRNDArray(self._data[:n], self._indices[:n],
                          self._indptr, self._sp_shape, self._ctx)

    def copyto(self, other):
        if isinstance(other, BaseSparseNDArray):
            raise MXNetError("copyto between sparse arrays is not "
                             "supported; use tostype")
        return self.todense().copyto(other)

    def __repr__(self):
        return "<%s %s @%s, nnz-storage %s>" % (
            type(self).__name__, "x".join(map(str, self.shape)), self._ctx,
            self._data.shape)

    # dense fallback for registry op methods ONLY (reference storage
    # fallback: cast to dense, run the dense kernel); other attribute
    # probes (hasattr, pickle/numpy protocols) must fail fast without
    # densifying
    def __getattr__(self, name):
        from ..ops import registry as _reg

        if name.startswith("_") or not _reg.exists(name):
            raise AttributeError(
                "'%s' object has no attribute %r"
                % (type(self).__name__, name))
        return getattr(self.todense(), name)

    def _binary(self, other, op, scalar_op, rop=False):
        return self.todense()._binary(other, op, scalar_op, rop=rop)

    def _to_dense_jax(self):
        raise NotImplementedError


class RowSparseNDArray(BaseSparseNDArray):
    """First dim sparse: ``values[(nnz,) + shape[1:]]`` + sorted unique
    ``indices[(nnz,)]`` (reference ``kRowSparseStorage``)."""

    stype = "row_sparse"

    def __init__(self, data, indices, shape, ctx=None):
        import jax.numpy as jnp

        indices = indices.astype(jnp.int32) \
            if hasattr(indices, "astype") else jnp.asarray(indices, "int32")
        # nnz bucketing applies HERE — the one spot every producer
        # (constructors, retain, merges, kv pulls) goes through — so
        # the O(log max_nnz) executable bound holds past the first op
        self._true_nnz = int(data.shape[0])
        data, indices = _pad_rsp_components(data, indices, shape[0])
        super().__init__(data, ctx)
        self._indices = indices
        self._sp_shape = tuple(int(s) for s in shape)

    def _to_dense_jax(self):
        import jax.numpy as jnp

        out = jnp.zeros(self._sp_shape, self._data.dtype)
        if self._data.shape[0] == 0:
            return out
        return out.at[self._indices].set(self._data)

    def retain(self, row_ids):
        return retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """2-D compressed sparse row: ``data[(nnz,)]``, ``indices[(nnz,)]``,
    ``indptr[(m+1,)]`` (reference ``kCSRStorage``)."""

    stype = "csr"

    def __init__(self, data, indices, indptr, shape, ctx=None):
        import jax.numpy as jnp

        indices = jnp.asarray(indices).astype(jnp.int32)
        self._true_nnz = int(data.shape[0])
        data, indices = _pad_csr_components(jnp.asarray(data), indices)
        super().__init__(data, ctx)
        self._indices = indices
        self._indptr = jnp.asarray(indptr).astype(jnp.int32)
        self._sp_shape = tuple(int(s) for s in shape)
        if len(self._sp_shape) != 2:
            raise MXNetError("csr storage is 2-D only")

    @property
    def indptr(self):
        return NDArray(self._indptr, self._ctx)

    def _row_ids(self):
        """Row id per stored element, from indptr (static nnz)."""
        import jax.numpy as jnp

        nnz = self._data.shape[0]
        return (jnp.searchsorted(self._indptr, jnp.arange(nnz),
                                 side="right") - 1).astype(jnp.int32)

    def _to_dense_jax(self):
        import jax.numpy as jnp

        out = jnp.zeros(self._sp_shape, self._data.dtype)
        if self._data.shape[0] == 0:
            return out
        return out.at[self._row_ids(), self._indices].set(self._data)


# ---------------------------------------------------------------------------
# constructors (reference python/mxnet/ndarray/sparse.py)
# ---------------------------------------------------------------------------

def _nnz_bucket(n):
    """Next power-of-two bucket for nnz padding (min 16), active under
    ``MXNET_SPARSE_NNZ_BUCKETS=1``.

    Sparse kernels compile per component SHAPE (module docstring: the
    static-shape ragged encoding); imperative workloads with organic nnz
    variation pay a recompile per distinct nnz.  Bucketing pads nnz to
    powers of two so the executable count is O(log max_nnz) — the
    BucketingModule trick applied to sparsity.  Padding is inert by
    construction: row_sparse pads with SENTINEL row id ``num_rows``
    (out-of-range scatter indices drop under jit; gathers clamp but
    their results are dropped too) and csr pads values with zeros
    beyond ``indptr[-1]`` (value-linear kernels are unaffected).
    """
    from ..base import get_env

    if n == 0 or not get_env("MXNET_SPARSE_NNZ_BUCKETS", 0, int):
        return n
    b = 16
    while b < n:
        b *= 2
    return b


def _pad_csr_components(data, indices):
    """Zero-value tail beyond ``indptr[-1]``: value-linear kernels are
    unaffected; one executable per bucket."""
    import jax.numpy as jnp

    bucket = _nnz_bucket(int(data.shape[0]))
    pad = bucket - int(data.shape[0])
    if pad <= 0:
        return data, indices
    return (jnp.concatenate([data, jnp.zeros((pad,), data.dtype)]),
            jnp.concatenate([indices, jnp.zeros((pad,), jnp.int32)]))


def _pad_rsp_components(data, indices, num_rows):
    import jax.numpy as jnp

    bucket = _nnz_bucket(int(data.shape[0]))
    pad = bucket - int(data.shape[0])
    if pad <= 0:
        return data, indices
    zrows = jnp.zeros((pad,) + tuple(data.shape[1:]), data.dtype)
    sentinel = jnp.full((pad,), num_rows, "int32")
    return (jnp.concatenate([data, zrows]),
            jnp.concatenate([indices, sentinel]))


def row_sparse_array(arg, shape=None, ctx=None, dtype=None):
    """Build a RowSparseNDArray from (data, indices) or a dense source."""
    import jax.numpy as jnp

    ctx = ctx or current_context()
    if isinstance(arg, tuple) and len(arg) == 2:
        data, indices = arg
        data = _dense_array(data, ctx, dtype)._data
        indices = jnp.asarray(_np.asarray(indices), "int32") \
            if not isinstance(indices, NDArray) else \
            indices._data.astype("int32")
        if shape is None:
            raise MXNetError("shape required with (data, indices)")
        return RowSparseNDArray(data, indices, shape, ctx)
    if isinstance(arg, RowSparseNDArray):
        return arg
    dense = arg.asnumpy() if isinstance(arg, NDArray) else _np.asarray(
        arg, dtype=dtype or "float32")
    nz_rows = _np.where(_np.any(dense.reshape(dense.shape[0], -1) != 0,
                                axis=1))[0]
    return RowSparseNDArray(
        jnp.asarray(dense[nz_rows]), jnp.asarray(nz_rows, "int32"),
        dense.shape, ctx)


def csr_matrix(arg, shape=None, ctx=None, dtype=None):
    """Build a CSRNDArray from (data, indices, indptr) or a dense source."""
    import jax.numpy as jnp

    ctx = ctx or current_context()
    if isinstance(arg, tuple) and len(arg) == 3:
        data, indices, indptr = arg
        if shape is None:
            raise MXNetError("shape required with (data, indices, indptr)")
        return CSRNDArray(
            _dense_array(data, ctx, dtype)._data,
            _np.asarray(indices, "int32"), _np.asarray(indptr, "int32"),
            shape, ctx)
    if isinstance(arg, CSRNDArray):
        return arg
    dense = arg.asnumpy() if isinstance(arg, NDArray) else _np.asarray(
        arg, dtype=dtype or "float32")
    if dense.ndim != 2:
        raise MXNetError("csr_matrix needs a 2-D source")
    rows, cols = _np.nonzero(dense)
    indptr = _np.zeros(dense.shape[0] + 1, "int32")
    _np.add.at(indptr, rows + 1, 1)
    indptr = _np.cumsum(indptr).astype("int32")
    return CSRNDArray(jnp.asarray(dense[rows, cols]),
                      cols.astype("int32"), indptr, dense.shape, ctx)


def zeros(stype, shape, ctx=None, dtype="float32"):
    import jax.numpy as jnp

    ctx = ctx or current_context()
    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype),
            jnp.zeros((0,), "int32"), shape, ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype), jnp.zeros((0,), "int32"),
                          jnp.zeros(shape[0] + 1, "int32"), shape, ctx)
    from .ndarray import zeros as dzeros

    return dzeros(shape, ctx, dtype)


def cast_storage(arr, stype):
    """Convert between storage types (reference ``cast_storage`` op,
    ``src/operator/tensor/cast_storage-inl.h``)."""
    if stype == "default":
        return arr.todense() if isinstance(arr, BaseSparseNDArray) else arr
    if stype == "row_sparse":
        return row_sparse_array(
            arr.todense() if isinstance(arr, BaseSparseNDArray) else arr)
    if stype == "csr":
        return csr_matrix(
            arr.todense() if isinstance(arr, BaseSparseNDArray) else arr)
    raise MXNetError("unknown storage type %r" % stype)


# ---------------------------------------------------------------------------
# sparse kernels (reference FComputeEx set)
# ---------------------------------------------------------------------------

def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """dot with sparse support: csr x dense and csr^T x dense (reference
    ``src/operator/tensor/dot-inl.h``); dense falls through to nd.dot."""
    import jax
    import jax.numpy as jnp

    if isinstance(lhs, CSRNDArray):
        if transpose_b:
            raise MXNetError("dot(csr, dense, transpose_b) unsupported")
        if not isinstance(rhs, NDArray) or isinstance(rhs, BaseSparseNDArray):
            raise MXNetError("dot(csr, rhs): rhs must be dense")
        m, k = lhs.shape
        row_ids = lhs._row_ids()
        vals, cols, dense = lhs._data, lhs._indices, rhs._data
        if transpose_a:
            # out[k, n] = sum over stored (r, c, v): out[c] += v * dense[r]
            out = jax.ops.segment_sum(
                vals[:, None] * dense[row_ids], cols, num_segments=k)
        else:
            out = jax.ops.segment_sum(
                vals[:, None] * dense[cols], row_ids, num_segments=m)
        return NDArray(out, lhs.context)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs,
                                                        BaseSparseNDArray):
        raise MXNetError("unsupported sparse dot combination")
    from . import dot as dense_dot

    return dense_dot(lhs, rhs, transpose_a=transpose_a,
                     transpose_b=transpose_b)


def retain(rsp, row_ids):
    """Keep only the requested rows (reference ``_sparse_retain``)."""
    import jax.numpy as jnp

    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    rid = row_ids._data.astype("int32") if isinstance(row_ids, NDArray) \
        else jnp.asarray(_np.asarray(row_ids), "int32")
    # membership of each stored row in row_ids
    keep = (rsp._indices[:, None] == rid[None, :]).any(axis=1)
    keep_np = _np.asarray(keep)
    sel = _np.where(keep_np)[0]
    return RowSparseNDArray(rsp._data[sel], rsp._indices[sel], rsp.shape,
                            rsp.context)


def square_sum(rsp, axis=None, keepdims=False):
    """sum(x^2) over a row-sparse array without densifying (reference
    ``_square_sum``)."""
    import jax.numpy as jnp

    if not isinstance(rsp, RowSparseNDArray):
        raise MXNetError("square_sum expects a RowSparseNDArray")
    sq = jnp.square(rsp._data)
    if axis is None:
        return NDArray(sq.sum(), rsp.context)
    if axis in (1, -1) and len(rsp.shape) == 2:
        out = jnp.zeros(rsp.shape[0], rsp._data.dtype)
        out = out.at[rsp._indices].set(sq.sum(axis=1))
        if keepdims:
            out = out[:, None]
        return NDArray(out, rsp.context)
    return NDArray(jnp.square(rsp._to_dense_jax()).sum(
        axis=axis, keepdims=keepdims), rsp.context)


def _merge_rsp(arrays):
    """Sum row-sparse arrays into one with sorted unique indices."""
    import jax.numpy as jnp

    shape = arrays[0].shape
    # merge the TRUE components: bucketing's sentinel rows must not
    # enter the index union (the constructor re-pads the result)
    idx = _np.concatenate(
        [_np.asarray(a._indices[:a._public_nnz()]) for a in arrays])
    uniq, inv = _np.unique(idx, return_inverse=True)
    vals = jnp.concatenate(
        [a._data[:a._public_nnz()] for a in arrays], axis=0)
    import jax

    summed = jax.ops.segment_sum(vals, jnp.asarray(inv, "int32"),
                                 num_segments=len(uniq))
    return RowSparseNDArray(summed, jnp.asarray(uniq, "int32"), shape,
                            arrays[0].context)


def elemwise_add(lhs, rhs):
    """rsp + rsp stays sparse (reference FComputeEx elemwise_add)."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs,
                                                        RowSparseNDArray):
        if lhs.shape != rhs.shape:
            raise MXNetError("shape mismatch %s vs %s"
                             % (lhs.shape, rhs.shape))
        return _merge_rsp([lhs, rhs])
    l = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    r = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return l + r


def add_n(*arrays):
    if len(arrays) == 1 and isinstance(arrays[0], (list, tuple)):
        arrays = tuple(arrays[0])
    if all(isinstance(a, RowSparseNDArray) for a in arrays):
        return _merge_rsp(list(arrays))
    out = arrays[0].todense() if isinstance(arrays[0], BaseSparseNDArray) \
        else arrays[0].copy()
    for a in arrays[1:]:
        out = elemwise_add(out, a)
    return out


# ---------------------------------------------------------------------------
# sparse optimizer updates (reference sparse-aware sgd/adam,
# src/operator/optimizer_op.cc "lazy update": only rows present in the
# gradient are touched — weight decay included)
# ---------------------------------------------------------------------------

def _prep(grad_vals, rescale, clip):
    import jax.numpy as jnp

    g = grad_vals * rescale
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def sgd_update(weight, grad, lr, wd=0.0, rescale_grad=1.0,
               clip_gradient=None, out=None):
    if not isinstance(grad, RowSparseNDArray):
        raise MXNetError("sparse.sgd_update expects a row_sparse grad")
    idx = grad._indices
    g = _prep(grad._data, rescale_grad, clip_gradient)
    w = weight._data
    rows = w[idx]
    new_rows = rows - lr * (g + wd * rows)
    new_w = w.at[idx].set(new_rows)
    tgt = out if out is not None else weight
    tgt._set_data(new_w)
    return tgt


def sgd_mom_update(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=None, out=None):
    if not isinstance(grad, RowSparseNDArray):
        raise MXNetError("sparse.sgd_mom_update expects a row_sparse grad")
    idx = grad._indices
    g = _prep(grad._data, rescale_grad, clip_gradient)
    w, m = weight._data, mom._data
    rows_w, rows_m = w[idx], m[idx]
    new_m = momentum * rows_m - lr * (g + wd * rows_w)
    mom._set_data(m.at[idx].set(new_m))
    new_w = w.at[idx].add(new_m)
    tgt = out if out is not None else weight
    tgt._set_data(new_w)
    return tgt


def adam_update(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=None,
                out=None):
    import jax.numpy as jnp

    if not isinstance(grad, RowSparseNDArray):
        raise MXNetError("sparse.adam_update expects a row_sparse grad")
    idx = grad._indices
    g = _prep(grad._data, rescale_grad, clip_gradient)
    w = weight._data
    g = g + wd * w[idx]
    new_mean_rows = beta1 * mean._data[idx] + (1 - beta1) * g
    new_var_rows = beta2 * var._data[idx] + (1 - beta2) * jnp.square(g)
    mean._set_data(mean._data.at[idx].set(new_mean_rows))
    var._set_data(var._data.at[idx].set(new_var_rows))
    new_w = w.at[idx].add(-lr * new_mean_rows /
                          (jnp.sqrt(new_var_rows) + epsilon))
    tgt = out if out is not None else weight
    tgt._set_data(new_w)
    return tgt
