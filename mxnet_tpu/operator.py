"""Custom operators defined in Python.

Reference: ``python/mxnet/operator.py`` + ``src/operator/custom/custom.cc``
(SURVEY.md §2.1 "Custom-op bridge"): user subclasses ``CustomOp`` (compute)
and ``CustomOpProp`` (metadata), registers the prop under a name, and
invokes ``mx.nd.Custom(..., op_type=name)`` / ``mx.sym.Custom(...)``.  In
the reference the callbacks run on the engine's ``kAsync`` path.

TPU-native form: the ``Custom`` registry op lowers to
``jax.pure_callback`` — XLA calls back onto the host mid-graph — wrapped
in ``jax.custom_vjp`` so the user's ``backward`` supplies the gradient.
It works imperatively, inside Symbol graphs, under the split Module path,
AND inside the fused train step (the callback compiles into the XLA
program; each step still pays one host round-trip per custom op, so keep
them off the hot path for peak throughput).

Divergences (documented):
* one ``CustomOp`` instance is created per callback invocation, so ops
  must be stateless between calls (the reference creates one per bound
  executor);
* auxiliary states are not supported;
* ``ctx`` passed to ``create_operator`` is the host CPU context.
"""
from __future__ import annotations

import functools

import numpy as np

from .base import MXNetError

__all__ = ["CustomOp", "CustomOpProp", "register", "get_prop_class"]

_CUSTOM_PROPS = {}


class CustomOp:
    """Base class for the compute part (reference ``CustomOp``)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad request."""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req %r" % req)


class CustomOpProp:
    """Base class for the metadata part (reference ``CustomOpProp``)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],) * len(self.list_outputs()), ()

    def infer_type(self, in_type):
        return in_type, (in_type[0],) * len(self.list_outputs()), ()

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        raise NotImplementedError


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type`` (reference
    ``mx.operator.register``)."""
    def _do(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _CUSTOM_PROPS[reg_name] = prop_cls
        return prop_cls

    return _do


def get_prop_class(op_type):
    try:
        return _CUSTOM_PROPS[op_type]
    except KeyError:
        raise MXNetError(
            "custom op %r is not registered (known: %s)"
            % (op_type, sorted(_CUSTOM_PROPS))) from None


def _make_prop(attrs):
    """Instantiate the prop with the user's string kwargs (the reference
    passes all attrs as strings to the prop constructor)."""
    kwargs = {k: v for k, v in attrs.items()
              if k not in ("op_type",) and not k.startswith("__")}
    return get_prop_class(attrs["op_type"])(**kwargs)


def _custom_num_outputs(attrs):
    return len(_make_prop(attrs).list_outputs())


def _custom_compute(attrs, *inputs):
    """The Custom registry op (reference ``custom.cc:36``), two tiers:

    1. **Device path (default)**: the user's ``forward``/``backward``
       are CALLED DURING TRACING with NDArray shims over the traced
       values — custom ops written with ``mx.nd`` operations (which ARE
       jax computations) compile straight into the surrounding XLA
       program and run on the accelerator, no host round-trip.  This is
       the TPU-native answer to the reference's ``FnProperty::kAsync``
       callback scheduling.
    2. **Host-callback fallback**: ops that materialize numpy
       (``.asnumpy()``) cannot trace; they raise a concretization error
       and fall back to ``jax.pure_callback`` + ``custom_vjp`` — which
       only runs on backends with host-callback support (NOT the axon
       TPU tunnel).  ``MXNET_CUSTOM_OP_CALLBACK=1`` forces this tier.
    """
    import jax

    if "op_type" not in attrs:
        raise MXNetError("Custom needs an op_type attr")
    prop = _make_prop(attrs)
    if prop.list_auxiliary_states():
        raise MXNetError("Custom ops with auxiliary states are not "
                         "supported on the TPU build")
    is_train = bool(attrs.get("__is_train__", False))
    n_in = len(inputs)
    in_shapes = [tuple(x.shape) for x in inputs]
    in_dtypes = [np.dtype(x.dtype).name for x in inputs]
    shape_res = prop.infer_shape([list(s) for s in in_shapes])
    out_shapes = [tuple(s) for s in shape_res[1]]
    type_res = prop.infer_type(list(in_dtypes))
    out_dtypes = list(type_res[1])
    out_avals = [jax.ShapeDtypeStruct(s, np.dtype(d))
                 for s, d in zip(out_shapes, out_dtypes)]
    in_avals = [jax.ShapeDtypeStruct(s, np.dtype(d))
                for s, d in zip(in_shapes, in_dtypes)]

    from .ndarray import array, zeros

    def _new_op():
        from .context import cpu

        return prop.create_operator(cpu(), [list(s) for s in in_shapes],
                                    list(in_dtypes))

    # ---- tier 1: trace the user code into the XLA program -------------
    from .base import get_env

    if not get_env("MXNET_CUSTOM_OP_CALLBACK", 0, int):
        import jax.numpy as jnp

        from .ndarray import NDArray

        from . import autograd as _ag

        def traced_forward(*xs):
            op = _new_op()
            in_data = [NDArray(jnp.asarray(x)) for x in xs]
            out_data = [NDArray(jnp.zeros(s, np.dtype(d)))
                        for s, d in zip(out_shapes, out_dtypes)]
            # the op's internals run INSIDE this trace; recording them
            # on the imperative tape would leak tracers (the Custom
            # node itself is what the tape sees)
            with _ag.pause():
                op.forward(is_train=is_train,
                           req=["write"] * len(out_data),
                           in_data=in_data, out_data=out_data, aux=[])
            return tuple(o._data.astype(a.dtype)
                         for o, a in zip(out_data, out_avals))

        def traced_backward(cts, xs, outs):
            op = _new_op()
            in_grad = [NDArray(jnp.zeros(s, np.dtype(d)))
                       for s, d in zip(in_shapes, in_dtypes)]
            with _ag.pause():
                op.backward(
                    req=["write"] * n_in,
                    out_grad=[NDArray(jnp.asarray(g)) for g in cts],
                    in_data=[NDArray(jnp.asarray(x)) for x in xs],
                    out_data=[NDArray(jnp.asarray(o)) for o in outs],
                    in_grad=in_grad, aux=[])
            return tuple(g._data.astype(a.dtype)
                         for g, a in zip(in_grad, in_avals))

        device_ok = True
        try:
            # probe abstractly FIRST: a half-traced user forward must
            # not leak partial effects into the real trace
            jax.eval_shape(traced_forward, *in_avals)
        except Exception:  # noqa: BLE001 — any probe failure: host tier
            device_ok = False
        if device_ok:
            try:
                jax.eval_shape(traced_backward, out_avals, in_avals,
                               out_avals)
            except jax.errors.ConcretizationTypeError:
                # host-bound backward (.asnumpy() etc. — covers the
                # TracerArrayConversion subclass): the whole op takes
                # the callback tier so gradients stay available
                device_ok = False
            except Exception:  # noqa: BLE001
                # user error (e.g. forward-only op: backward raises
                # NotImplementedError) — keep the device tier; the
                # error surfaces if/when gradients are requested,
                # matching the reference contract
                pass
        if device_ok:
            @jax.custom_vjp
            def run_traced(*xs):
                return traced_forward(*xs)

            def traced_fwd_rule(*xs):
                outs = traced_forward(*xs)
                return outs, (xs, outs)

            def traced_bwd_rule(res, cts):
                xs, outs = res
                return traced_backward(tuple(cts), xs, outs)

            run_traced.defvjp(traced_fwd_rule, traced_bwd_rule)
            return run_traced(*inputs)

    def host_forward(*np_in):
        op = _new_op()
        in_data = [array(np.asarray(x)) for x in np_in]
        out_data = [zeros(s) for s in out_shapes]
        op.forward(is_train=is_train, req=["write"] * len(out_data),
                   in_data=in_data, out_data=out_data, aux=[])
        return tuple(np.asarray(o.asnumpy(), dtype=a.dtype)
                     for o, a in zip(out_data, out_avals))

    def host_backward(*np_args):
        ograds = [np.asarray(x) for x in np_args[:len(out_shapes)]]
        ins = [np.asarray(x) for x in
               np_args[len(out_shapes):len(out_shapes) + n_in]]
        outs = [np.asarray(x) for x in np_args[len(out_shapes) + n_in:]]
        op = _new_op()
        in_grad = [zeros(s) for s in in_shapes]
        op.backward(req=["write"] * n_in,
                    out_grad=[array(g) for g in ograds],
                    in_data=[array(x) for x in ins],
                    out_data=[array(x) for x in outs],
                    in_grad=in_grad, aux=[])
        return tuple(np.asarray(g.asnumpy(), dtype=a.dtype)
                     for g, a in zip(in_grad, in_avals))

    @jax.custom_vjp
    def run(*xs):
        return jax.pure_callback(host_forward, tuple(out_avals), *xs,
                                 vmap_method="sequential")

    def run_fwd(*xs):
        outs = run(*xs)
        return outs, (xs, outs)

    def run_bwd(res, cts):
        xs, outs = res
        grads = jax.pure_callback(host_backward, tuple(in_avals),
                                  *(tuple(cts) + tuple(xs) + tuple(outs)),
                                  vmap_method="sequential")
        return tuple(grads)

    run.defvjp(run_fwd, run_bwd)
    return run(*inputs)


def _register_custom_op():
    from .ops.registry import register as reg_op

    reg_op("Custom", num_outputs=_custom_num_outputs,
           uses_train_mode=True)(_custom_compute)


_register_custom_op()
