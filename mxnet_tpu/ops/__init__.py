"""Operator registry and all built-in operator definitions.

Importing this package registers every op (mirrors the reference's static
registration at library load; SURVEY.md Appendix A is the catalog).
"""
from . import registry
from .registry import OpDef, register, get, list_ops, invoke, FrozenAttrs

# register all built-in op families
from . import attention     # noqa: F401  (kernel library, no op names)
from . import ssm_ops       # noqa: F401  (kernel library, no op names)
from . import math_ops      # noqa: F401
from . import matrix_ops    # noqa: F401
from . import nn_ops        # noqa: F401
from . import random_ops    # noqa: F401
from . import optimizer_ops # noqa: F401
from . import rnn_ops       # noqa: F401
from . import contrib_ops   # noqa: F401

# attach the dmlc::Parameter-style per-op parameter declarations
from . import op_params     # noqa: E402
op_params.attach_specs(get)

__all__ = ["OpDef", "register", "get", "list_ops", "invoke", "FrozenAttrs",
           "registry"]
