"""Blockwise (flash-style) dot-product attention.

The reference (0.11, pre-transformer) has nothing to port here; this
module is the TPU-first kernel behind ``MultiHeadAttention`` and the
per-hop inner kernel of ring attention (``parallel/sequence.py``).

Why it exists: the materialized-scores path builds an ``(n, h, T, T)``
fp32 tensor that XLA's fusion heuristics will not cross ("Operator
Fusion in XLA", arXiv 2301.13062) — at the bench shape (8L-d2048-T1024)
it is the single largest live buffer in the train step and caps both
sequence length and MFU.  The flash path tiles the key/value sequence
into blocks and keeps online-softmax statistics (running max ``m`` and
denominator ``l``) in fp32, so peak attention memory is O(T·block)
instead of O(T²), with a ``jax.custom_vjp`` backward that *recomputes*
each block's probabilities from the saved logsumexp instead of storing
them (Dao et al., FlashAttention, 2022 — public technique).

Three implementations, selected by ``MXNET_ATTN_IMPL``:

* ``reference`` — the original materialized path (exact softmax over
  the full score matrix).  Ground truth for tests.
* ``flash`` — the pure-``lax`` blockwise kernel below.  Runs on every
  backend, so the CPU tier-1 rig exercises the same code path that
  ships on TPU.
* ``auto`` (default) — on TPU, try the Pallas fused flash kernel
  (``jax.experimental.pallas.ops.tpu.flash_attention``) and fall back
  to the ``lax`` blockwise kernel when the shape/backend does not
  qualify; elsewhere, the ``lax`` blockwise kernel.

The per-block accumulation (:func:`attend_block` /
:func:`online_block_merge`) is shared with ring attention: each ring
hop is exactly one K/V-block visit with positions recovered from the
hop index, so sequence parallelism and the single-chip kernel stay one
implementation.

Gradient contract: the custom VJP is linear in the incoming cotangent
(``d(q,k,v)`` scale with ``g``), so the dynamic loss scale riding the
loss-head cotangent (PR 3) flows through unchanged — same semantics the
materialized path gets from autodiff.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError, get_env

__all__ = ["attention_impl", "attention_block_size", "dot_product_attention",
           "flash_attention", "reference_attention", "attend_block",
           "online_block_merge", "finalize_attention", "decode_attention"]

_IMPLS = ("auto", "flash", "reference")


def attention_impl():
    """Resolve ``MXNET_ATTN_IMPL`` (``auto`` | ``flash`` | ``reference``).

    Read at trace time: jitted programs bake in whichever implementation
    was active when they were traced (the registry's imperative-invoke
    cache keys on attrs/shapes, not env) — tests that need to force a
    path per-call should pass the ``attn_impl`` op attr instead.
    """
    impl = get_env("MXNET_ATTN_IMPL", "auto").strip().lower()
    if impl not in _IMPLS:
        raise MXNetError("MXNET_ATTN_IMPL=%r not in %s" % (impl, _IMPLS))
    return impl


def attention_block_size():
    """K/V block length for the blockwise kernel (``MXNET_ATTN_BLOCK``)."""
    block = get_env("MXNET_ATTN_BLOCK", 128)
    if block < 1:
        raise MXNetError("MXNET_ATTN_BLOCK must be >= 1, got %d" % block)
    return block


# ---------------------------------------------------------------------------
# shared online-softmax inner kernel (also the ring-attention hop kernel)
# ---------------------------------------------------------------------------

def _qk_scores(q32, kb32, mi=False):
    """(..., Tq, D) x (..., Tk, D) -> (..., Tq, Tk) score matmul.

    ``mi=True`` selects the M-invariant broadcast-multiply-reduce form:
    each output element reduces over D in an order independent of Tq, so
    a single-query decode step produces bit-identical scores to the
    matching row of a full-context forward (the serving bit-exactness
    contract — XLA's gemm packs/accumulates differently per M, which is
    ~1 ulp of drift the einsum form cannot avoid).  Costs extra bandwidth
    (the product tensor materializes), so it is opt-in.
    """
    if mi:
        return jnp.sum(q32[..., :, None, :] * kb32[..., None, :, :],
                       axis=-1)
    return jnp.einsum("...qd,...kd->...qk", q32, kb32)


def _pv_accum(p, vb32, mi=False):
    """(..., Tq, Tk) x (..., Tk, D) -> (..., Tq, D) probability-value
    matmul; ``mi`` as in :func:`_qk_scores`."""
    if mi:
        return jnp.sum(p[..., :, :, None] * vb32[..., None, :, :],
                       axis=-2)
    return jnp.einsum("...qk,...kd->...qd", p, vb32)


def online_block_merge(acc, m, l, scores, v, mi=False):
    """One flash-attention accumulation step.

    acc: (..., Tq, D) weighted-value accumulator; m: (..., Tq, 1) running
    max; l: (..., Tq, 1) running denominator; scores: (..., Tq, Tk) this
    block's logits (fp32, masked entries at ``-inf``); v: (..., Tk, D).
    Returns updated (acc, m, l).
    """
    block_max = jnp.max(scores, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, block_max)
    # guard against all--inf rows (fully masked block): exp(-inf - -inf)
    new_m_safe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    correction = jnp.exp(m - new_m_safe)
    correction = jnp.where(jnp.isfinite(m), correction, 0.0)
    p = jnp.exp(scores - new_m_safe)
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    new_l = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    new_acc = acc * correction + _pv_accum(p, v, mi=mi)
    return new_acc, new_m, new_l


def attend_block(q32, kb, vb, acc, m, l, q_pos=None, k_pos=None,
                 causal=False, kv_valid=None, mi=False, window=0):
    """Visit one K/V block: score, mask, merge into the running stats.

    ``q32`` is the full (pre-scaled, fp32) query; ``kb``/``vb`` one key/
    value block.  ``q_pos``/``k_pos`` are absolute positions (1-D int
    arrays) used for causal masking — ring attention recovers ``k_pos``
    from the hop index, the blockwise kernel from the block start.
    ``kv_valid`` masks padded keys in the (ragged) last block; any
    broadcastable mask shape works (the paged decode kernel passes a
    per-batch-element (..., 1, Tk) validity mask).  ``mi`` selects the
    M-invariant matmuls (see :func:`_qk_scores`).  ``window > 0`` adds a
    sliding-window lower bound: a key is visible only when
    ``q_pos - k_pos < window`` — a causal horizon that also *starts*
    late.  Fully windowed-out blocks are exact no-ops in the merge, so
    windowing preserves the M-invariant accumulation contract.
    """
    scores = _qk_scores(q32, kb.astype(jnp.float32), mi=mi)
    mask = None
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
    if window:
        wmask = k_pos[None, :] > q_pos[:, None] - window
        mask = wmask if mask is None else mask & wmask
    if kv_valid is not None:
        mask = kv_valid if mask is None else mask & kv_valid
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    return online_block_merge(acc, m, l, scores,
                              vb.astype(jnp.float32), mi=mi)


def finalize_attention(acc, l):
    """Normalize the accumulator by the running denominator."""
    return acc / jnp.maximum(l, 1e-20)


# ---------------------------------------------------------------------------
# reference (materialized) path
# ---------------------------------------------------------------------------

def reference_attention(q, k, v, causal=True, scale=None, window=0):
    """Exact softmax attention over the full (..., Tq, Tk) score matrix.

    The pre-flash ``_multi_head_attention`` body, kept verbatim as the
    numeric ground truth: scores in fp32, O(T²) peak memory.
    ``window > 0`` restricts row ``i`` to keys ``j`` with
    ``i - j < window`` (sliding-window attention).
    """
    t, d = q.shape[-2], q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32)
    scores = scores * scale
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((t, k.shape[-2]), bool))
    if window:
        row = jnp.arange(t)[:, None]
        col = jnp.arange(k.shape[-2])[None, :]
        wmask = col > row - window
        mask = wmask if mask is None else mask & wmask
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("...qk,...kd->...qd", probs, v)


# ---------------------------------------------------------------------------
# blockwise flash kernel (pure lax, custom VJP)
# ---------------------------------------------------------------------------

def _kv_blocks(x, t_pad, block):
    """(..., T, D) -> (nblk, ..., block, D) scan-ready block stack."""
    pad = [(0, 0)] * (x.ndim - 2) + [(0, t_pad - x.shape[-2]), (0, 0)]
    x = jnp.pad(x, pad)
    x = x.reshape(x.shape[:-2] + (t_pad // block, block, x.shape[-1]))
    return jnp.moveaxis(x, -3, 0)


def _flash_forward(q, k, v, causal, scale, block, mi=False, window=0):
    """Tiled forward: scan over K/V blocks carrying (acc, m, l) in fp32.

    Returns ``(out, lse)`` where ``lse = m + log l`` is the per-query
    logsumexp the backward recomputes probabilities from.  Peak live
    memory is O(T·block) — the (T, T) score matrix never exists.
    """
    t, d = q.shape[-2], q.shape[-1]
    nblk = -(-t // block)
    t_pad = nblk * block
    kb = _kv_blocks(k, t_pad, block)
    vb = _kv_blocks(v, t_pad, block)
    starts = jnp.arange(nblk) * block
    q32 = q.astype(jnp.float32) * scale
    q_pos = jnp.arange(t)

    acc0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:-1] + (1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)

    def body(carry, xs):
        acc, m, l = carry
        kblk, vblk, start = xs
        k_pos = start + jnp.arange(block)
        kv_valid = k_pos < t if t_pad != t else None
        acc, m, l = attend_block(q32, kblk, vblk, acc, m, l,
                                 q_pos=q_pos, k_pos=k_pos, causal=causal,
                                 kv_valid=kv_valid, mi=mi, window=window)
        return (acc, m, l), None

    (acc, m, l), _ = lax.scan(body, (acc0, m0, l0), (kb, vb, starts))
    out = finalize_attention(acc, l).astype(q.dtype)
    # l > 0 always (row q attends to at least key 0 under causal; all
    # keys when not), so the log is finite
    lse = jnp.where(jnp.isfinite(m), m, 0.0) + jnp.log(jnp.maximum(l, 1e-38))
    return out, lse


def _flash_backward(q, k, v, out, lse, g, causal, scale, block, window=0):
    """Recompute-based backward: one more scan over K/V blocks.

    Each block's probabilities are rebuilt from ``lse`` (never stored),
    then ``ds = p * (dp - delta)`` with ``delta = Σ dO·O`` gives the
    score gradient.  dq accumulates across blocks (carry); dk/dv are
    per-block (stacked ys).  Linear in ``g`` by construction.
    """
    t = q.shape[-2]
    nblk = -(-t // block)
    t_pad = nblk * block
    kb = _kv_blocks(k, t_pad, block)
    vb = _kv_blocks(v, t_pad, block)
    starts = jnp.arange(nblk) * block
    q32 = q.astype(jnp.float32) * scale
    q_pos = jnp.arange(t)
    do = g.astype(jnp.float32)
    delta = jnp.sum(do * out.astype(jnp.float32), axis=-1, keepdims=True)

    def body(dq, xs):
        kblk, vblk, start = xs
        kb32 = kblk.astype(jnp.float32)
        vb32 = vblk.astype(jnp.float32)
        scores = jnp.einsum("...qd,...kd->...qk", q32, kb32)
        k_pos = start + jnp.arange(block)
        mask = q_pos[:, None] >= k_pos[None, :] if causal else None
        if window:
            wmask = k_pos[None, :] > q_pos[:, None] - window
            mask = wmask if mask is None else mask & wmask
        if t_pad != t:
            valid = k_pos < t
            mask = valid if mask is None else mask & valid
        if mask is not None:
            scores = jnp.where(mask, scores, -jnp.inf)
        p = jnp.exp(scores - lse)  # masked -> exp(-inf) == 0 exactly
        dv_blk = jnp.einsum("...qk,...qd->...kd", p, do)
        dp = jnp.einsum("...qd,...kd->...qk", do, vb32)
        ds = p * (dp - delta)
        dq = dq + jnp.einsum("...qk,...kd->...qd", ds, kb32)
        dk_blk = jnp.einsum("...qk,...qd->...kd", ds, q32)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dk_blk, dv_blk) = lax.scan(body, dq0, (kb, vb, starts))

    def unblocks(blk, like):
        x = jnp.moveaxis(blk, 0, -3)
        x = x.reshape(x.shape[:-3] + (t_pad, x.shape[-1]))
        return x[..., :t, :].astype(like.dtype)

    # scores = (q*scale)·k: d/dq carries the scale factor explicitly,
    # d/dk already has it through q32
    dq = (dq * scale).astype(q.dtype)
    return dq, unblocks(dk_blk, k), unblocks(dv_blk, v)


@functools.lru_cache(maxsize=64)
def _flash_fn(causal, scale, block, mi=False, window=0):
    """Per-(causal, scale, block, mi, window) custom-VJP closure.

    ``custom_vjp`` needs the static config out of the traced signature;
    the cache keeps function identity stable so jit does not re-trace
    per call.  ``mi`` only changes the forward matmul form (serving
    bit-exactness); the recompute backward keeps the einsum form —
    gradients carry no M-invariance contract.  ``window`` masks
    identically in forward and backward (a windowed-out key gets exactly
    zero probability and zero gradient).
    """

    @jax.custom_vjp
    def attn(q, k, v):
        out, _ = _flash_forward(q, k, v, causal, scale, block, mi=mi,
                                window=window)
        return out

    def fwd(q, k, v):
        out, lse = _flash_forward(q, k, v, causal, scale, block, mi=mi,
                                  window=window)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, out, lse = res
        return _flash_backward(q, k, v, out, lse, g, causal, scale, block,
                               window=window)

    attn.defvjp(fwd, bwd)
    return attn


def flash_attention(q, k, v, causal=True, scale=None, block=None,
                    mi=False, window=0):
    """Blockwise online-softmax attention, O(T·block) peak memory.

    q/k/v: (..., T, D) with identical leading dims (batch, heads are
    free).  Ragged T is handled by padding the last K/V block and
    masking the padded keys to ``-inf``.  Differentiable via a
    recompute-based ``custom_vjp`` (no stored probabilities).  ``mi``
    selects M-invariant forward matmuls so per-row outputs do not depend
    on how many query rows share the call (see :func:`_qk_scores`).
    ``window > 0`` limits each query to the most recent ``window`` keys
    (sliding-window attention; see :func:`attend_block`).
    """
    d = q.shape[-1]
    t = k.shape[-2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if block is None:
        # default only: clamp to T so short sequences do not pay padding.
        # An explicit block is honored verbatim — serving bit-exactness
        # needs the accumulation width fixed across different T.
        block = min(attention_block_size(), max(t, 1))
    return _flash_fn(bool(causal), float(scale), int(block),
                     bool(mi), int(window))(q, k, v)


# ---------------------------------------------------------------------------
# single-query paged decode kernel (serving)
# ---------------------------------------------------------------------------

def decode_attention(q, k_ctx, v_ctx, lengths, scale=None, block=None,
                     mi=False, k_scale=None, v_scale=None, window=0,
                     k_positions=None):
    """One autoregressive decode step of attention over a paged KV
    context: the O(1)-per-token serving counterpart of
    :func:`flash_attention`, built from the same :func:`attend_block`
    online-softmax primitive so the two paths cannot drift numerically.

    q: (S, H, Q, D) — ``Q`` queries per batch slot (1 for the decode
    step, ``K+1`` for the speculative verify step); k_ctx/v_ctx:
    (S, H, Tcap, D) — the slot's gathered KV pages, where ``Tcap`` is the
    fixed page capacity and rows at positions >= the valid length are
    stale/garbage; lengths: (S,) int — valid context length per slot
    (INCLUDING the current token, whose KV the caller appends before
    attending) — or (S, Q) int for a per-query-row valid length (the
    verify step: row ``j`` at absolute position ``L + j`` sees exactly
    ``L + j + 1`` keys, which is the causal mask expressed as raggedness).
    ``Tcap`` must be a multiple of ``block`` (the page size, for the
    paged cache).  Fully-masked blocks are exact no-ops in the online
    merge (correction 1, p 0), so visiting all ``Tcap/block`` blocks
    with the validity mask reproduces the reference forward's merge
    sequence bit-for-bit when ``mi=True``.

    ``k_scale``/``v_scale``: optional (S, Tcap) float32 per-position
    scales of a quantized KV context (``quantize.kv_quantize_rows``
    rows).  Dequantization happens HERE, per block inside the scan —
    an elementwise convert + multiply feeding the score/value matmuls
    directly, so XLA fuses it into the attention kernel and the f32
    context never materializes at (S, H, Tcap, D).

    ``window > 0`` adds the sliding-window lower bound: a context row is
    visible only when its position ``p`` satisfies
    ``valid_len - 1 - window < p <= valid_len - 1``.  ``k_positions``
    (optional, (S, Tcap) int32) gives each context row an explicit
    absolute position — the windowed-layer ring gather rotates a slot's
    ring pages into ascending-position order and labels each row, so
    rows that wrapped (or were never written) carry positions outside
    the window (or < 0) and mask out exactly.  Because the gathered
    blocks are page-aligned at the same absolute boundaries the
    reference forward uses, the online merge visits visible blocks in
    the same order with the same masks — windowed decode stays
    bit-exact against the windowed reference under ``mi=True``.
    """
    d = q.shape[-1]
    t_cap = k_ctx.shape[-2]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    if block is None:
        block = attention_block_size()
    block = min(block, max(t_cap, 1))
    if t_cap % block:
        raise MXNetError(
            "decode_attention: context capacity %d not a multiple of "
            "block %d" % (t_cap, block))
    nblk = t_cap // block
    kb = _kv_blocks(k_ctx, t_cap, block)
    vb = _kv_blocks(v_ctx, t_cap, block)

    def _scale_blocks(s):
        # (S, Tcap) -> (nblk, S, 1, block, 1): broadcast-ready against
        # the (nblk, S, H, block, D) code blocks
        s = s.reshape(s.shape[0], nblk, block)
        return jnp.moveaxis(s, 1, 0)[:, :, None, :, None]

    ksb = _scale_blocks(k_scale) if k_scale is not None else None
    vsb = _scale_blocks(v_scale) if v_scale is not None else None

    def _pos_blocks(p):
        # (S, Tcap) -> (nblk, S, 1, 1, block): broadcast-ready against
        # the (S, H, Q, block) mask
        p = p.reshape(p.shape[0], nblk, block)
        return jnp.moveaxis(p, 1, 0)[:, :, None, None, :]

    kpb = _pos_blocks(k_positions) if k_positions is not None else None
    starts = jnp.arange(nblk) * block
    q32 = q.astype(jnp.float32) * scale
    acc0 = jnp.zeros(q.shape[:-1] + (v_ctx.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:-1] + (1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)
    if lengths.ndim == 2:
        if lengths.shape != (q.shape[0], q.shape[-2]):
            raise MXNetError(
                "decode_attention: per-row lengths %r do not match query "
                "rows %r" % (lengths.shape, (q.shape[0], q.shape[-2])))
        # (S, 1, Q, 1) so each query row carries its own validity horizon
        valid_len = lengths[:, None, :, None]
    else:
        # (S, 1, 1, 1) so the mask broadcasts against (S, H, Q, block)
        valid_len = lengths.reshape(lengths.shape + (1,) * (q.ndim - 1))

    def body(carry, xs):
        acc, m, l = carry
        kblk, vblk, start, ks, vs, kp = xs
        if ks is not None:  # in-kernel dequant of quantized pages
            kblk = kblk.astype(jnp.float32) * ks
            vblk = vblk.astype(jnp.float32) * vs
        if kp is not None:
            # explicit per-row absolute positions (ring gather): rows
            # that wrapped or were never written carry positions outside
            # [0, valid_len) and mask out exactly
            pos = valid_len - 1  # query row's absolute position
            kv_valid = (kp >= 0) & (kp <= pos)
            if window:
                kv_valid = kv_valid & (kp > pos - window)
        else:
            k_pos = start + jnp.arange(block)
            kv_valid = k_pos < valid_len
            if window:
                kv_valid = kv_valid & (k_pos >= valid_len - window)
        acc, m, l = attend_block(q32, kblk, vblk, acc, m, l,
                                 kv_valid=kv_valid, mi=mi)
        return (acc, m, l), None

    slots = [kb, vb, starts, ksb, vsb, kpb]
    present = [x is not None for x in slots]
    packed = tuple(x for x in slots if x is not None)

    def step(carry, xs):
        it = iter(xs)
        return body(carry, tuple(next(it) if p else None for p in present))

    (acc, _, l), _ = lax.scan(step, (acc0, m0, l0), packed)
    return finalize_attention(acc, l).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas fused kernel (TPU) + dispatcher
# ---------------------------------------------------------------------------

def _pallas_attention(q, k, v, causal, scale):
    """TPU fused flash kernel (Mosaic).  Raises when unavailable or the
    shape does not meet the kernel's block constraints — callers fall
    back to the ``lax`` blockwise path."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as pl_flash)

    if q.ndim != 4:
        raise MXNetError("pallas flash kernel needs (n, h, T, d) inputs")
    return pl_flash(q, k, v, causal=causal, sm_scale=scale)


def dot_product_attention(q, k, v, causal=True, scale=None, impl=None,
                          block=None, window=0):
    """Dispatch attention to the implementation ``MXNET_ATTN_IMPL`` (or
    the explicit ``impl`` argument) selects.

    ``auto`` tries the Pallas fused kernel when tracing for TPU and
    falls back to the portable ``lax`` blockwise kernel — which is also
    what ``flash`` forces, so the CPU tier-1 rig and the TPU fallback
    run identical code.  ``window > 0`` (sliding-window attention) is
    not expressible in the Pallas kernel's mask, so it always takes the
    blockwise path.
    """
    impl = (impl or attention_impl()).strip().lower()
    if impl not in _IMPLS:
        raise MXNetError("attention impl %r not in %s" % (impl, _IMPLS))
    if impl == "reference":
        return reference_attention(q, k, v, causal=causal, scale=scale,
                                   window=window)
    if impl == "auto" and jax.default_backend() == "tpu" and not window:
        if scale is None:
            scale = 1.0 / (q.shape[-1] ** 0.5)
        try:
            return _pallas_attention(q, k, v, causal, scale)
        except MXNetError:  # typed contract violation, not a kernel gap
            raise
        except Exception:  # unsupported shape/kernel -> portable path
            pass
    return flash_attention(q, k, v, causal=causal, scale=scale,
                           block=block, window=window)
