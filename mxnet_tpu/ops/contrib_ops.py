"""Contrib operators — detection / research ops.

Reference: ``src/operator/contrib/`` (~12k LoC of CUDA/C++; SURVEY.md
§2.1 "Operators — contrib"): the SSD family (MultiBoxPrior/Target/
Detection), the R-CNN family (Proposal, PSROIPooling,
DeformableConvolution), CTCLoss, FFT, quantization.

TPU-first formulations: everything is static-shape.  NMS and proposal
selection keep FIXED candidate counts (top-k + masked suppression loops
via ``lax.fori_loop`` — invalid slots carry -1/0 like the reference's
pad semantics) instead of the reference's dynamic-length CUDA kernels;
CTC is the log-domain forward recursion as one ``lax.scan`` with the
gradient from autodiff; deformable conv gathers bilinear samples and
contracts on the MXU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


def _tuple_attr(attrs, name, default):
    v = attrs.get(name, default)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


# ---------------------------------------------------------------------------
# SSD family
# ---------------------------------------------------------------------------

@register("_contrib_MultiBoxPrior", aliases=("MultiBoxPrior",))
def _multibox_prior(attrs, data):
    """Anchor generation (reference ``multibox_prior.cc``): one anchor
    per (size, ratio) combo per cell, count = len(sizes)+len(ratios)-1,
    output (1, H*W*A, 4) corner-form normalized boxes."""
    sizes = _tuple_attr(attrs, "sizes", (1.0,))
    ratios = _tuple_attr(attrs, "ratios", (1.0,))
    clip = bool(attrs.get("clip", False))
    steps = _tuple_attr(attrs, "steps", (-1.0, -1.0))
    offsets = _tuple_attr(attrs, "offsets", (0.5, 0.5))
    h, w = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if len(steps) > 1 and steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    # anchor (w, h) list: all sizes with ratio[0], then ratios[1:] with
    # size[0] (the reference's combination rule)
    whs = [(s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0]))
           for s in sizes]
    whs += [(sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r))
            for r in ratios[1:]]
    aw = jnp.asarray([x[0] for x in whs], jnp.float32)
    ah = jnp.asarray([x[1] for x in whs], jnp.float32)
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")     # (H, W)
    cyg = cyg[:, :, None]
    cxg = cxg[:, :, None]
    xmin = cxg - aw / 2.0
    ymin = cyg - ah / 2.0
    xmax = cxg + aw / 2.0
    ymax = cyg + ah / 2.0
    out = jnp.stack([xmin, ymin, xmax, ymax], axis=-1)  # (H, W, A, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out.reshape(1, -1, 4)


def _iou_matrix(a, b):
    """(N,4) corner boxes x (M,4) -> (N,M) IoU."""
    ax1, ay1, ax2, ay2 = a[:, 0:1], a[:, 1:2], a[:, 2:3], a[:, 3:4]
    bx1, by1, bx2, by2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register("_contrib_MultiBoxTarget", aliases=("MultiBoxTarget",),
          num_outputs=3)
def _multibox_target(attrs, anchor, label, cls_pred):
    """Anchor->GT matching (reference ``multibox_target.cc``).

    anchor (1, N, 4); label (B, M, 5) rows [cls, xmin, ymin, xmax, ymax]
    padded with cls=-1; cls_pred is unused for matching (the reference
    uses it only for negative mining, which is subsumed by the loss-side
    weighting here).  Outputs: loc_target (B, N*4), loc_mask (B, N*4),
    cls_target (B, N) — cls_target is gt class + 1, 0 = background.
    """
    overlap = float(attrs.get("overlap_threshold", 0.5))
    variances = _tuple_attr(attrs, "variances", (0.1, 0.1, 0.2, 0.2))
    anchors = anchor[0]                                   # (N, 4)

    def one(lbl):
        valid = lbl[:, 0] >= 0                            # (M,)
        ious = _iou_matrix(anchors, lbl[:, 1:5])          # (N, M)
        ious = jnp.where(valid[None, :], ious, -1.0)
        best_gt = jnp.argmax(ious, axis=1)                # (N,)
        best_iou = jnp.max(ious, axis=1)
        matched = best_iou >= overlap
        # force-match: each VALID gt claims its best anchor; padded rows
        # scatter out of bounds and are dropped (they would all land on
        # anchor 0 otherwise, clobbering real matches)
        n_anchors = anchors.shape[0]
        best_anchor = jnp.argmax(ious, axis=0)            # (M,)
        safe_anchor = jnp.where(valid, best_anchor, n_anchors)
        forced = jnp.zeros(n_anchors, bool).at[safe_anchor].set(
            True, mode="drop")
        gt_for_forced = jnp.zeros(n_anchors, jnp.int32).at[
            safe_anchor].set(jnp.arange(lbl.shape[0], dtype=jnp.int32),
                             mode="drop")
        gt_idx = jnp.where(forced, gt_for_forced, best_gt)
        pos = matched | forced

        gt = lbl[gt_idx]                                  # (N, 5)
        # center-form offsets scaled by variances (reference encoding)
        aw = anchors[:, 2] - anchors[:, 0]
        ahh = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
        gh = jnp.maximum(gt[:, 4] - gt[:, 2], 1e-8)
        gcx = (gt[:, 1] + gt[:, 3]) / 2
        gcy = (gt[:, 2] + gt[:, 4]) / 2
        tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
        ty = (gcy - acy) / jnp.maximum(ahh, 1e-8) / variances[1]
        tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / variances[2]
        th = jnp.log(gh / jnp.maximum(ahh, 1e-8)) / variances[3]
        loc_t = jnp.stack([tx, ty, tw, th], axis=-1)       # (N, 4)
        mask = pos[:, None].astype(jnp.float32) * jnp.ones((1, 4))
        cls_t = jnp.where(pos, gt[:, 0].astype(jnp.float32) + 1.0, 0.0)
        return (loc_t * mask).reshape(-1), mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(one)(label)
    return loc_t, loc_m, cls_t


def _decode_boxes(anchors, deltas, variances):
    """Invert the center-form encoding: (N,4) anchors + deltas -> corners."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = deltas[:, 0] * variances[0] * aw + acx
    cy = deltas[:, 1] * variances[1] * ah + acy
    w = jnp.exp(deltas[:, 2] * variances[2]) * aw
    h = jnp.exp(deltas[:, 3] * variances[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


def _nms_mask(boxes, scores, iou_thr, topk, cls_id=None):
    """Static-shape greedy NMS: returns keep mask.  ``topk`` rounds of
    select-max + suppress (the reference's nms_topk cap).  With
    ``cls_id`` given, suppression applies only within the same class
    (the reference's ``force_suppress=False`` default)."""
    n = boxes.shape[0]
    ious = _iou_matrix(boxes, boxes)

    def body(_, state):
        alive, keep = state
        masked = jnp.where(alive, scores, -jnp.inf)
        best = jnp.argmax(masked)
        any_alive = masked[best] > -jnp.inf
        keep = keep.at[best].set(keep[best] | any_alive)
        suppress = ious[best] > iou_thr
        if cls_id is not None:
            suppress = suppress & (cls_id == cls_id[best])
        alive = alive & ~suppress & (jnp.arange(n) != best)
        return alive, keep

    alive0 = jnp.ones(n, bool)
    keep0 = jnp.zeros(n, bool)
    _, keep = lax.fori_loop(0, min(topk, n), body, (alive0, keep0))
    return keep


@register("_contrib_MultiBoxDetection", aliases=("MultiBoxDetection",))
def _multibox_detection(attrs, cls_prob, loc_pred, anchor):
    """Decode + per-class NMS (reference ``multibox_detection.cc``).

    cls_prob (B, C, N) incl. background class 0; loc_pred (B, N*4);
    anchor (1, N, 4).  Output (B, N, 6): [cls_id, score, xmin, ymin,
    xmax, ymax], suppressed/invalid rows get cls_id -1.
    """
    thr = float(attrs.get("threshold", 0.01))
    nms_thr = float(attrs.get("nms_threshold", 0.5))
    topk = int(attrs.get("nms_topk", -1))
    clip = bool(attrs.get("clip", True))
    force_suppress = bool(attrs.get("force_suppress", False))
    variances = _tuple_attr(attrs, "variances", (0.1, 0.1, 0.2, 0.2))
    anchors = anchor[0]
    n = anchors.shape[0]
    if topk <= 0:
        topk = n

    def one(probs, deltas):
        boxes = _decode_boxes(anchors, deltas.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        score = jnp.max(probs[1:], axis=0)          # best non-background
        cls_id = jnp.argmax(probs[1:], axis=0).astype(jnp.float32)
        keep = _nms_mask(boxes, score, nms_thr, topk,
                         cls_id=None if force_suppress else cls_id)
        ok = keep & (score > thr)
        cls_out = jnp.where(ok, cls_id, -1.0)
        return jnp.concatenate([cls_out[:, None], score[:, None], boxes],
                               axis=-1)

    return jax.vmap(one)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# R-CNN family
# ---------------------------------------------------------------------------

@register("_contrib_Proposal", aliases=("Proposal", "MultiProposal",
                                        "_contrib_MultiProposal"))
def _proposal(attrs, cls_prob, bbox_pred, im_info):
    """RPN proposal generation (reference ``proposal.cc``): decode
    per-anchor deltas, clip to image, score by objectness, fixed-count
    top-k + NMS.  Output (B, rpn_post_nms_top_n, 5) rows
    [batch_idx, x1, y1, x2, y2] (invalid rows repeat the best box, like
    the reference's padding)."""
    scales = _tuple_attr(attrs, "scales", (4.0, 8.0, 16.0, 32.0))
    ratios = _tuple_attr(attrs, "ratios", (0.5, 1.0, 2.0))
    stride = float(attrs.get("feature_stride", 16))
    pre_n = int(attrs.get("rpn_pre_nms_top_n", 6000))
    post_n = int(attrs.get("rpn_post_nms_top_n", 300))
    nms_thr = float(attrs.get("threshold", 0.7))
    min_size = float(attrs.get("rpn_min_size", 16))

    b, a2, h, w = cls_prob.shape
    num_anchors = a2 // 2
    # base anchors at each cell (corner form, image coords)
    base = []
    for r in ratios:
        for s in scales:
            ww = stride * s * jnp.sqrt(1.0 / r)
            hh = stride * s * jnp.sqrt(r)
            base.append((-ww / 2, -hh / 2, ww / 2, hh / 2))
    base = jnp.asarray(base, jnp.float32)        # (A, 4)
    sy = jnp.arange(h, dtype=jnp.float32) * stride
    sx = jnp.arange(w, dtype=jnp.float32) * stride
    cyg, cxg = jnp.meshgrid(sy, sx, indexing="ij")
    shift = jnp.stack([cxg, cyg, cxg, cyg], axis=-1)  # (H, W, 4)
    anchors = (shift[:, :, None, :] + base[None, None, :, :]
               ).reshape(-1, 4)                       # (H*W*A, 4)

    def one(scores_map, deltas_map, info):
        # scores: foreground half of cls_prob, layout (A, H, W)
        fg = scores_map[num_anchors:].transpose(1, 2, 0).reshape(-1)
        deltas = deltas_map.reshape(num_anchors, 4, h, w) \
            .transpose(2, 3, 0, 1).reshape(-1, 4)
        boxes = _decode_boxes_rcnn(anchors, deltas)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=-1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        ms = min_size * info[2]
        valid = (ws >= ms) & (hs >= ms)
        fg = jnp.where(valid, fg, -jnp.inf)
        k = min(pre_n, fg.shape[0])
        top_scores, top_idx = lax.top_k(fg, k)
        top_boxes = boxes[top_idx]
        keep = _nms_mask(top_boxes, top_scores, nms_thr, post_n)
        score_keep = jnp.where(keep, top_scores, -jnp.inf)
        kk = min(post_n, k)
        _, sel = lax.top_k(score_keep, kk)
        out_boxes = top_boxes[sel]
        if kk < post_n:
            out_boxes = jnp.concatenate(
                [out_boxes, jnp.broadcast_to(out_boxes[:1],
                                             (post_n - kk, 4))])
        return out_boxes

    outs = jax.vmap(one)(cls_prob, bbox_pred, im_info)   # (B, post, 4)
    bidx = jnp.broadcast_to(
        jnp.arange(b, dtype=jnp.float32)[:, None, None], (b, post_n, 1))
    return jnp.concatenate([bidx, outs], axis=-1)


def _decode_boxes_rcnn(anchors, deltas):
    """R-CNN style decoding (pixel coords, +1 widths)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw * 0.5
    acy = anchors[:, 1] + ah * 0.5
    cx = deltas[:, 0] * aw + acx
    cy = deltas[:, 1] * ah + acy
    w = jnp.exp(deltas[:, 2]) * aw
    h = jnp.exp(deltas[:, 3]) * ah
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5, cy + h * 0.5], axis=-1)


@register("_contrib_PSROIPooling", aliases=("PSROIPooling",))
def _psroi_pooling(attrs, data, rois):
    """Position-sensitive ROI pooling (reference ``psroi_pooling.cc``):
    channel block (i,j) of the output grid average-pools its own group
    of input channels inside subcell (i,j) of the ROI."""
    spatial_scale = float(attrs["spatial_scale"])
    output_dim = int(attrs["output_dim"])
    pooled = int(attrs.get("pooled_size", attrs.get("group_size", 7)))
    group = int(attrs.get("group_size", pooled))
    n, c, h, w = data.shape
    if c != output_dim * group * group:
        raise MXNetError("PSROIPooling: data channels %d != output_dim*"
                         "group_size^2 = %d" % (c, output_dim * group *
                                                group))
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale
        y1 = roi[2] * spatial_scale
        x2 = roi[3] * spatial_scale
        y2 = roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw = rw / pooled
        bh = rh / pooled
        img = data[bidx].reshape(output_dim, group * group, h, w)

        def cell(iy, ix):
            cy0 = y1 + iy * bh
            cy1 = y1 + (iy + 1) * bh
            cx0 = x1 + ix * bw
            cx1 = x1 + (ix + 1) * bw
            m = ((ys[:, None] >= jnp.floor(cy0)) &
                 (ys[:, None] < jnp.maximum(jnp.ceil(cy1),
                                            jnp.floor(cy0) + 1)) &
                 (xs[None, :] >= jnp.floor(cx0)) &
                 (xs[None, :] < jnp.maximum(jnp.ceil(cx1),
                                            jnp.floor(cx0) + 1)))
            mf = m.astype(jnp.float32)
            denom = jnp.maximum(mf.sum(), 1.0)
            # output cell -> channel group via floor scaling (reference
            # psroi_pooling: gh = floor(ph*group/pooled)), NOT modulo
            gidx = (iy * group // pooled) * group + (ix * group // pooled)
            plane = img[:, gidx]                 # (output_dim, h, w)
            return (plane * mf).sum(axis=(1, 2)) / denom

        iy, ix = jnp.meshgrid(jnp.arange(pooled), jnp.arange(pooled),
                              indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(iy, ix)  # (p, p, output_dim)
        return jnp.moveaxis(cells, -1, 0)          # (output_dim, p, p)

    return jax.vmap(one_roi)(rois)


@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution",))
def _deformable_conv(attrs, data, offset, weight, *bias):
    """Deformable convolution v1 (reference ``deformable_convolution.cc``):
    each kernel tap samples the input at a learned fractional offset via
    bilinear interpolation; the contraction is a plain MXU matmul over
    the gathered patches."""
    kernel = tuple(int(k) for k in attrs["kernel"])
    kh, kw = kernel
    stride = tuple(int(s) for s in attrs.get("stride", (1, 1)))
    pad = tuple(int(p) for p in attrs.get("pad", (0, 0)))
    dilate = tuple(int(d) for d in attrs.get("dilate", (1, 1)))
    groups = int(attrs.get("num_group", 1))
    dgroups = int(attrs.get("num_deformable_group", 1))
    if groups != 1 or dgroups != 1:
        raise MXNetError("DeformableConvolution: only num_group=1, "
                         "num_deformable_group=1 are supported")
    n, c, h, w = data.shape
    out_h = (h + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    out_w = (w + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1

    oy = jnp.arange(out_h) * stride[0] - pad[0]
    ox = jnp.arange(out_w) * stride[1] - pad[1]
    ky = jnp.arange(kh) * dilate[0]
    kx = jnp.arange(kw) * dilate[1]
    # base sample positions (kh, kw, out_h, out_w)
    py = ky[:, None, None, None] + oy[None, None, :, None] + \
        jnp.zeros((1, kw, 1, out_w))
    px = kx[None, :, None, None] + ox[None, None, None, :] + \
        jnp.zeros((kh, 1, out_h, 1))

    def bilinear(img, y, x):
        """img (c, h, w); y/x sample grids (...,) -> (c, ...)."""
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)
        wy = y - y0
        wx = x - x0

        def tap(yy, xx):
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = img[:, yi, xi]
            ok = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            return jnp.where(ok, v, 0.0)

        return (tap(y0, x0) * (1 - wy) * (1 - wx) +
                tap(y0, x0 + 1) * (1 - wy) * wx +
                tap(y0 + 1, x0) * wy * (1 - wx) +
                tap(y0 + 1, x0 + 1) * wy * wx)

    def one(img, off):
        off = off.reshape(kh, kw, 2, out_h, out_w)
        sy = py + off[:, :, 0]
        sx = px + off[:, :, 1]
        patches = bilinear(img, sy, sx)      # (c, kh, kw, oh, ow)
        return patches

    patches = jax.vmap(one)(data, offset)     # (n, c, kh, kw, oh, ow)
    out = jnp.einsum("nckhyx,ockh->noyx",
                     patches.reshape(n, c, kh, kw, out_h, out_w),
                     weight.reshape(weight.shape[0], c, kh, kw))
    if bias:
        out = out + bias[0].reshape(1, -1, 1, 1)
    return out


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------

@register("_contrib_CTCLoss", aliases=("CTCLoss", "ctc_loss"))
def _ctc_loss(attrs, data, label):
    """Connectionist temporal classification loss (reference
    ``ctc_loss.cc`` over the bundled warpctc kernels).

    ``data`` (T, N, C) un-normalized activations (softmax applied
    internally, warpctc contract); ``label`` (N, L) with class ids in
    [1, C-1], 0-padded; blank is class 0 (``blank_label='first'``).
    Output: per-sequence loss (N,).  Gradient comes from autodiff of the
    log-domain forward recursion (one ``lax.scan`` over time).
    """
    t_len, n, c = data.shape
    l_max = label.shape[1]
    log_probs = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    labels = label.astype(jnp.int32)
    lab_len = jnp.sum((labels != 0).astype(jnp.int32), axis=1)

    # extended label sequence: blank, l1, blank, l2, ... blank (2L+1)
    s = 2 * l_max + 1
    ext = jnp.zeros((n, s), jnp.int32)
    ext = ext.at[:, 1::2].set(labels)

    neg_inf = jnp.float32(-1e30)
    # alpha recursion in log domain
    def step(alpha, lp):
        # lp: (N, C) log prob at time t
        emit = jnp.take_along_axis(lp, ext, axis=1)     # (N, S)
        shift1 = jnp.concatenate(
            [jnp.full((n, 1), neg_inf), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((n, 2), neg_inf), alpha[:, :-2]], axis=1)
        # skip allowed only between different non-blank labels
        prev2 = jnp.concatenate(
            [jnp.zeros((n, 2), jnp.int32), ext[:, :-2]], axis=1)
        can_skip = (ext != 0) & (ext != prev2)
        best = jnp.logaddexp(alpha, shift1)
        best = jnp.where(can_skip, jnp.logaddexp(best, shift2), best)
        new_alpha = best + emit
        return new_alpha, None

    alpha0 = jnp.full((n, s), neg_inf)
    alpha0 = alpha0.at[:, 0].set(log_probs[0, :, 0])
    first_lab = ext[:, 1]
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(log_probs[0], first_lab[:, None],
                            axis=1)[:, 0])
    alpha, _ = lax.scan(step, alpha0, log_probs[1:])

    # total prob ends at the last blank or last label position
    end1 = 2 * lab_len          # last blank
    end2 = 2 * lab_len - 1      # last label
    a_end1 = jnp.take_along_axis(alpha, end1[:, None], axis=1)[:, 0]
    a_end2 = jnp.take_along_axis(
        alpha, jnp.maximum(end2, 0)[:, None], axis=1)[:, 0]
    log_p = jnp.where(lab_len > 0, jnp.logaddexp(a_end1, a_end2), a_end1)
    return -log_p


# ---------------------------------------------------------------------------
# fft / quantization / count_sketch
# ---------------------------------------------------------------------------

@register("_contrib_fft", aliases=("fft",))
def _fft(attrs, data):
    """1-D FFT over the last axis (reference ``fft.cc`` via cuFFT);
    complex output packed as interleaved [re, im] like the reference."""
    out = jnp.fft.fft(data.astype(jnp.complex64), axis=-1)
    packed = jnp.stack([out.real, out.imag], axis=-1)
    return packed.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(jnp.float32)


@register("_contrib_ifft", aliases=("ifft",))
def _ifft(attrs, data):
    half = data.shape[-1] // 2
    unpacked = data.reshape(data.shape[:-1] + (half, 2))
    comp = unpacked[..., 0] + 1j * unpacked[..., 1]
    return jnp.fft.ifft(comp, axis=-1).real.astype(jnp.float32) * half


@register("_contrib_quantize", aliases=("quantize",), num_outputs=3)
def _quantize(attrs, data, min_range, max_range):
    """Affine quantize to uint8 (reference ``quantize.cc``)."""
    qmin, qmax = 0.0, 255.0
    scale = (qmax - qmin) / jnp.maximum(max_range - min_range, 1e-8)
    q = jnp.clip(jnp.round((data - min_range) * scale + qmin), qmin, qmax)
    return q.astype(jnp.uint8), min_range, max_range


@register("_contrib_dequantize", aliases=("dequantize",))
def _dequantize(attrs, data, min_range, max_range):
    scale = jnp.maximum(max_range - min_range, 1e-8) / 255.0
    return data.astype(jnp.float32) * scale + min_range


@register("_contrib_count_sketch", aliases=("count_sketch",))
def _count_sketch(attrs, data, h, s):
    """Count sketch projection (reference ``count_sketch.cc``): hash each
    input dim into out_dim buckets with sign flips."""
    out_dim = int(attrs["out_dim"])
    idx = h.astype(jnp.int32).reshape(-1) % out_dim
    sign = s.astype(data.dtype).reshape(-1)
    contrib = data * sign[None, :]
    out = jnp.zeros(data.shape[:-1] + (out_dim,), data.dtype)
    return out.at[..., idx].add(contrib)


@register("Correlation")
def _correlation(attrs, data1, data2):
    """Patch cross-correlation (reference ``src/operator/correlation.cc``,
    the FlowNet op): for each displacement within max_displacement,
    correlate kernel_size x kernel_size patches of data1 with shifted
    patches of data2, normalized by patch volume.  Output
    (N, D*D, H, W) with D = 2*floor(max_displacement/stride2)+1."""
    k = int(attrs.get("kernel_size", 1))
    max_d = int(attrs.get("max_displacement", 1))
    stride1 = int(attrs.get("stride1", 1))
    stride2 = int(attrs.get("stride2", 1))
    pad = int(attrs.get("pad_size", 0))
    is_multiply = bool(attrs.get("is_multiply", True))
    n, c, h, w = data1.shape
    d1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    d2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    grid = max_d // stride2
    disps = [(dy * stride2, dx * stride2)
             for dy in range(-grid, grid + 1)
             for dx in range(-grid, grid + 1)]
    bound = max_d + k // 2
    # per-displacement: elementwise product (or abs-diff) averaged over
    # channels, then averaged over the kernel window
    window = (1, 1, k, k)
    import numpy as _onp

    maps = []
    for dy, dx in disps:
        shifted = jnp.roll(d2, shift=(-dy, -dx), axis=(2, 3))
        if is_multiply:
            prod = (d1 * shifted).mean(axis=1, keepdims=True)
        else:
            prod = jnp.abs(d1 - shifted).mean(axis=1, keepdims=True)
        summed = lax.reduce_window(
            prod, _onp.array(0, prod.dtype), lax.add, window,
            (1, 1, 1, 1),
            ((0, 0), (0, 0), (k // 2, k // 2), (k // 2, k // 2)))
        maps.append(summed / (k * k))
    out = jnp.concatenate(maps, axis=1)
    # crop the padded border so displaced reads never leave the map
    lo = bound
    out = out[:, :, lo:lo + h + 2 * pad - 2 * bound:stride1,
              lo:lo + w + 2 * pad - 2 * bound:stride1]
    return out


@register("_contrib_DeformablePSROIPooling",
          aliases=("DeformablePSROIPooling",))
def _deformable_psroi_pooling(attrs, data, rois, *trans):
    """Deformable PSROI pooling (reference
    ``deformable_psroi_pooling.cc``): PSROIPooling whose (iy, ix) cell
    samples at a learned normalized offset.  ``trans`` (N_roi, 2*g*g,
    ...) gives per-cell (dy, dx) in units of the ROI size; absent or
    ``no_trans`` -> plain position-sensitive pooling on a sample grid."""
    spatial_scale = float(attrs["spatial_scale"])
    output_dim = int(attrs["output_dim"])
    pooled = int(attrs.get("pooled_size", attrs.get("group_size", 7)))
    group = int(attrs.get("group_size", pooled))
    sample = int(attrs.get("sample_per_part", 2))
    trans_std = float(attrs.get("trans_std", 0.1))
    no_trans = bool(attrs.get("no_trans", False)) or not trans
    n, c, h, w = data.shape
    if c != output_dim * group * group:
        raise MXNetError("DeformablePSROIPooling: data channels %d != "
                         "output_dim*group_size^2" % c)

    def bilinear(img, y, x):
        y0 = jnp.floor(y); x0 = jnp.floor(x)
        wy = y - y0; wx = x - x0

        def tap(yy, xx):
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            ok = (yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1)
            return jnp.where(ok, img[yi, xi], 0.0)

        return (tap(y0, x0) * (1 - wy) * (1 - wx) +
                tap(y0, x0 + 1) * (1 - wy) * wx +
                tap(y0 + 1, x0) * wy * (1 - wx) +
                tap(y0 + 1, x0 + 1) * wy * wx)

    def one_roi(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale
        y1 = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale - x1, 0.1)
        rh = jnp.maximum(roi[4] * spatial_scale - y1, 0.1)
        img = data[bidx].reshape(output_dim, group * group, h, w)

        def cell(iy, ix):
            gh = iy * group // pooled
            gw = ix * group // pooled
            gidx = gh * group + gw
            if no_trans:
                off_y = 0.0
                off_x = 0.0
            else:
                off_y = tr[(gh * group + gw) * 2] * trans_std * rh
                off_x = tr[(gh * group + gw) * 2 + 1] * trans_std * rw
            bh = rh / pooled
            bw = rw / pooled
            ss = jnp.arange(sample, dtype=jnp.float32) + 0.5
            ys = y1 + iy * bh + off_y + ss[:, None] * (bh / sample)
            xs = x1 + ix * bw + off_x + ss[None, :] * (bw / sample)
            ys = jnp.broadcast_to(ys, (sample, sample))
            xs = jnp.broadcast_to(xs, (sample, sample))
            plane = img[:, gidx]

            def per_dim(pl):
                return jax.vmap(jax.vmap(lambda y, x: bilinear(pl, y, x)))(
                    ys, xs).mean()

            return jax.vmap(per_dim)(plane)

        iy, ix = jnp.meshgrid(jnp.arange(pooled), jnp.arange(pooled),
                              indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(iy, ix)
        return jnp.moveaxis(cells, -1, 0)

    if no_trans:
        tr_arr = jnp.zeros((rois.shape[0], 2 * group * group),
                           jnp.float32)
    else:
        tr_arr = trans[0].reshape(rois.shape[0], -1)
    return jax.vmap(one_roi)(rois, tr_arr)


@register("khatri_rao", aliases=("_contrib_khatri_rao", "krprod"))
def _khatri_rao(attrs, *mats):
    """Column-wise Khatri-Rao product (reference
    ``src/operator/contrib/krprod.cc``): for matrices with shapes
    ``(r_i, k)`` the result has shape ``(prod r_i, k)`` where each column
    is the Kronecker product of the corresponding columns.  On TPU this is
    a broadcast-multiply-reshape — one fused XLA kernel, no gather."""
    out = mats[0]
    for m in mats[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(
            out.shape[0] * m.shape[0], m.shape[1])
    return out
