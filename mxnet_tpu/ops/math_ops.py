"""Elementwise unary/binary/scalar/broadcast/logic + reduce operators.

Covers the reference's ``src/operator/tensor/elemwise_unary_op.cc``,
``elemwise_binary_op*.cc``, ``elemwise_binary_scalar_op*.cc``,
``broadcast_reduce_op*.cc`` and the ~80 ``mshadow_op.h`` scalar functors
(SURVEY.md Appendix A).  Each op is a one-line pure JAX function: XLA fuses
chains of these into single kernels, which replaces both mshadow expression
templates and the reference's hand-registered CUDA kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "ceil": jnp.ceil, "floor": jnp.floor, "rint": jnp.rint,
    "round": jnp.round, "trunc": jnp.trunc, "fix": jnp.trunc,
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log1p": jnp.log1p,
    "log2": jnp.log2, "log10": jnp.log10,
    "sqrt": jnp.sqrt, "cbrt": jnp.cbrt, "square": jnp.square,
    "negative": jnp.negative, "sign": jnp.sign,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "erf": jax.scipy.special.erf, "erfinv": jax.scipy.special.erfinv,
    "rsqrt": lax.rsqrt, "rcbrt": lambda x: x ** (-1.0 / 3),
    "reciprocal": jnp.reciprocal,
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}

for _name, _fn in _UNARY.items():
    register(_name, (lambda fn: lambda attrs, x: fn(x))(_fn))

@register("_copy", aliases=("identity",))
def _copy(attrs, x):
    return x


@register("BlockGrad", aliases=("stop_gradient", "block_grad"))
def _block_grad(attrs, x):
    """Reference ``BlockGrad`` (``src/operator/tensor/elemwise_unary_op.cc``):
    identity forward, zero gradient — exactly ``lax.stop_gradient``."""
    return lax.stop_gradient(x)


@register("make_loss", aliases=("MakeLoss",))
def _make_loss(attrs, x):
    """Reference ``MakeLoss`` (``src/operator/make_loss.cc``): marks a head
    whose backward seeds grad_scale instead of a head gradient."""
    scale = float(attrs.get("grad_scale", 1.0))

    @jax.custom_vjp
    def f(v):
        return v

    def fwd(v):
        return v, v.shape

    def bwd(shape, g):
        # grad_scale times the head cotangent (ones under the reference
        # seeding; the fused step's loss scale rides it)
        return (jnp.full(shape, scale, dtype=g.dtype) * g,)

    f.defvjp(fwd, bwd)
    return f(x)


@register("Cast", aliases=("cast",))
def _cast(attrs, x):
    return x.astype(jnp.dtype(attrs["dtype"]))


@register("clip")
def _clip(attrs, x):
    return jnp.clip(x, float(attrs["a_min"]), float(attrs["a_max"]))


@register("smooth_l1")
def _smooth_l1(attrs, x):
    sigma = float(attrs.get("scalar", 1.0))
    s2 = sigma * sigma
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * x * x,
                     jnp.abs(x) - 0.5 / s2)


# ---------------------------------------------------------------------------
# binary (elemwise_* and broadcast_* both map to jnp broadcasting ops — a
# strict superset of the reference's same-shape elemwise requirement)
# ---------------------------------------------------------------------------

def _logic(fn):
    return lambda a, b: fn(a, b).astype(jnp.result_type(a, b))


_BINARY = {
    "add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
    "div": jnp.divide, "mod": jnp.mod, "power": jnp.power,
    "maximum": jnp.maximum, "minimum": jnp.minimum, "hypot": jnp.hypot,
    "equal": _logic(jnp.equal), "not_equal": _logic(jnp.not_equal),
    "greater": _logic(jnp.greater), "greater_equal": _logic(jnp.greater_equal),
    "lesser": _logic(jnp.less), "lesser_equal": _logic(jnp.less_equal),
    "logical_and": _logic(lambda a, b: (a != 0) & (b != 0)),
    "logical_or": _logic(lambda a, b: (a != 0) | (b != 0)),
    "logical_xor": _logic(lambda a, b: (a != 0) ^ (b != 0)),
    "arctan2": jnp.arctan2,
}

for _name, _fn in _BINARY.items():
    _compute = (lambda fn: lambda attrs, a, b: fn(a, b))(_fn)
    register("elemwise_%s" % _name, _compute,
             aliases=("_%s" % _name, "broadcast_%s" % _name))

register("_grad_add", lambda attrs, a, b: a + b)
register("_minus", lambda attrs, a, b: a - b, aliases=("elemwise_minus",))
register("broadcast_minus", lambda attrs, a, b: a - b)
register("broadcast_plus", lambda attrs, a, b: a + b)


@register("add_n", aliases=("ElementWiseSum", "_sum", "elemwise_sum"))
def _add_n(attrs, *xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


# scalar variants (reference elemwise_binary_scalar_op*.cc)
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.mod(x, s),
    "_rmod_scalar": lambda x, s: jnp.mod(s, x),
    "_power_scalar": lambda x, s: jnp.power(x, s),
    "_rpower_scalar": lambda x, s: jnp.power(s, x),
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
}

for _name, _fn in _SCALAR.items():
    register(_name, (lambda fn: lambda attrs, x: fn(x, float(attrs["scalar"])))(_fn))


# ---------------------------------------------------------------------------
# reductions (reference broadcast_reduce_op_value.cc)
# ---------------------------------------------------------------------------

def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(fn):
    def compute(attrs, x):
        axis = _norm_axis(attrs.get("axis"), x.ndim, attrs.get("exclude", False))
        return fn(x, axis=axis, keepdims=bool(attrs.get("keepdims", False)))
    return compute


for _name, _fn in [("sum", jnp.sum), ("mean", jnp.mean), ("prod", jnp.prod),
                   ("nansum", jnp.nansum), ("nanprod", jnp.nanprod),
                   ("max", jnp.max), ("min", jnp.min)]:
    register(_name, _reduce(_fn), aliases=("%s_axis" % _name,))


@register("norm")
def _norm(attrs, x):
    ord_ = int(attrs.get("ord", 2))
    axis = _norm_axis(attrs.get("axis"), x.ndim)
    keepdims = bool(attrs.get("keepdims", False))
    if ord_ == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims))


def _arg_reduce(fn):
    def compute(attrs, x):
        axis = attrs.get("axis")
        keepdims = bool(attrs.get("keepdims", False))
        if axis is None:
            out = fn(x.reshape(-1), axis=0)
            return out.astype(jnp.float32)
        out = fn(x, axis=int(axis))
        if keepdims:
            out = jnp.expand_dims(out, int(axis))
        return out.astype(jnp.float32)
    return compute


register("argmax", _arg_reduce(jnp.argmax))
register("argmin", _arg_reduce(jnp.argmin))


@register("argmax_channel")
def _argmax_channel(attrs, x):
    return jnp.argmax(x, axis=1).astype(jnp.float32)


# ---------------------------------------------------------------------------
# broadcast structure ops
# ---------------------------------------------------------------------------

@register("broadcast_to")
def _broadcast_to(attrs, x):
    shape = tuple(int(s) if int(s) != 0 else x.shape[i]
                  for i, s in enumerate(attrs["shape"]))
    return jnp.broadcast_to(x, shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def _broadcast_axis(attrs, x):
    axes = attrs["axis"]
    sizes = attrs["size"]
    if isinstance(axes, int):
        axes, sizes = (axes,), (sizes,)
    shape = list(x.shape)
    for a, s in zip(axes, sizes):
        shape[int(a)] = int(s)
    return jnp.broadcast_to(x, tuple(shape))
