"""Tensor structure / indexing / linalg operators.

Covers the reference's ``src/operator/tensor/matrix_op.cc`` (Reshape with
special codes, transpose, slice family, repeat/tile/reverse/stack, dot,
batch_dot, take/one_hot/pick), ``indexing_op.cc`` (Embedding, take),
``ordering_op.cc`` (sort/argsort/topk), ``init_op.cc`` (_zeros/_ones/_arange)
and ``la_op.cc`` linalg (SURVEY.md Appendix A).

All matmuls go through ``lax.dot_general`` with ``preferred_element_type``
so the MXU gets large fp32-accumulated contractions even for bf16 inputs.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..base import MXNetError
from .registry import register


# ---------------------------------------------------------------------------
# reshape & friends
# ---------------------------------------------------------------------------

def _infer_reshape(shape, target):
    """Implements the reference's Reshape special codes 0, -1, -2, -3, -4
    (``src/operator/tensor/matrix_op.cc`` Reshape doc)."""
    out, src_i, i = [], 0, 0
    target = list(target)
    while i < len(target):
        t = int(target[i])
        if t == 0:
            out.append(shape[src_i]); src_i += 1
        elif t == -1:
            out.append(-1); src_i += 1
        elif t == -2:
            out.extend(shape[src_i:]); src_i = len(shape)
        elif t == -3:
            out.append(shape[src_i] * shape[src_i + 1]); src_i += 2
        elif t == -4:
            a, b = int(target[i + 1]), int(target[i + 2])
            dim = shape[src_i]
            if a == -1:
                a = dim // b
            if b == -1:
                b = dim // a
            out.extend([a, b]); src_i += 1; i += 2
        else:
            out.append(t); src_i += 1
        i += 1
    return tuple(out)


@register("Reshape", aliases=("reshape",))
def _reshape(attrs, x):
    shape = attrs["shape"]
    if attrs.get("reverse", False):
        rshape = _infer_reshape(x.shape[::-1], list(shape)[::-1])
        return jnp.reshape(x, rshape[::-1])
    return jnp.reshape(x, _infer_reshape(x.shape, shape))


@register("Flatten", aliases=("flatten",))
def _flatten(attrs, x):
    return jnp.reshape(x, (x.shape[0], -1))


@register("transpose")
def _transpose(attrs, x):
    axes = attrs.get("axes") or None
    return jnp.transpose(x, axes)


@register("expand_dims")
def _expand_dims(attrs, x):
    return jnp.expand_dims(x, int(attrs["axis"]))


@register("squeeze")
def _squeeze(attrs, x):
    axis = attrs.get("axis")
    return jnp.squeeze(x, axis if axis is None else tuple(
        a if isinstance(a, int) else int(a)
        for a in (axis if isinstance(axis, (tuple, list)) else (axis,))))


@register("SwapAxis", aliases=("swapaxes",))
def _swapaxes(attrs, x):
    return jnp.swapaxes(x, int(attrs["dim1"]), int(attrs["dim2"]))


@register("slice", aliases=("crop",))
def _slice(attrs, x):
    return x[_slice_tuple(attrs, x.ndim)]


def _slice_tuple(attrs, ndim):
    begin, end = attrs["begin"], attrs["end"]
    step = attrs.get("step") or (None,) * len(begin)
    idx = tuple(
        slice(None if b is None else int(b),
              None if e is None else int(e),
              None if s in (None, 0) else int(s))
        for b, e, s in zip(begin, end, step))
    return idx + (slice(None),) * (ndim - len(idx))


@register("_slice_assign", aliases=("_crop_assign",))
def _slice_assign(attrs, lhs, rhs):
    """Write ``rhs`` into the slice region of ``lhs`` and return the result
    (reference ``_slice_assign``/``_crop_assign``,
    ``src/operator/tensor/matrix_op.cc``).  Functional on TPU: XLA turns the
    ``.at[].set`` into an in-place dynamic-update-slice when the input
    buffer is donated, so no copy survives in the compiled program."""
    return lhs.at[_slice_tuple(attrs, lhs.ndim)].set(rhs)


@register("_slice_assign_scalar", aliases=("_crop_assign_scalar",))
def _slice_assign_scalar(attrs, lhs):
    return lhs.at[_slice_tuple(attrs, lhs.ndim)].set(
        jnp.asarray(float(attrs.get("scalar", 0.0)), lhs.dtype))


@register("_CrossDeviceCopy")
def _cross_device_copy(attrs, x):
    """Device-boundary marker (reference ``src/operator/cross_device_copy.cc``,
    inserted by the PlaceDevice pass at ``graph_executor.cc:395``).  Under
    SPMD there is no device boundary inside a program — placement is
    expressed as sharding, so this is an identity XLA can elide; kept so
    legacy ``group2ctx`` graphs load and bind."""
    return x


@register("slice_axis")
def _slice_axis(attrs, x):
    axis = int(attrs["axis"]) % x.ndim
    begin = int(attrs["begin"])
    end = attrs.get("end")
    end = x.shape[axis] if end is None else int(end)
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like")
def _slice_like(attrs, x, like):
    axes = attrs.get("axes") or tuple(range(like.ndim))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[int(a)] = slice(0, like.shape[int(a)])
    return x[tuple(idx)]


@register("repeat")
def _repeat(attrs, x):
    return jnp.repeat(x, int(attrs["repeats"]), axis=attrs.get("axis"))


@register("tile")
def _tile(attrs, x):
    return jnp.tile(x, tuple(int(r) for r in attrs["reps"]))


@register("reverse", aliases=("flip",))
def _reverse(attrs, x):
    axis = attrs["axis"]
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(x, tuple(int(a) for a in axis))


@register("stack")
def _stack(attrs, *xs):
    return jnp.stack(xs, axis=int(attrs.get("axis", 0)))


@register("Concat", aliases=("concat", "concatenate"))
def _concat(attrs, *xs):
    return jnp.concatenate(xs, axis=int(attrs.get("dim", 1)))


def _split_outputs(attrs):
    return int(attrs["num_outputs"])


@register("SliceChannel", aliases=("split",), num_outputs=_split_outputs)
def _split(attrs, x):
    num = int(attrs["num_outputs"])
    axis = int(attrs.get("axis", 1))
    parts = jnp.split(x, num, axis=axis)
    if attrs.get("squeeze_axis", False):
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("Pad", aliases=("pad",))
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = tuple((int(pw[2 * i]), int(pw[2 * i + 1])) for i in range(len(pw) // 2))
    mode = attrs.get("mode", "constant")
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=float(attrs.get("constant_value", 0)))
    return jnp.pad(x, pairs, mode={"edge": "edge", "reflect": "reflect"}[mode])


# ---------------------------------------------------------------------------
# indexing
# ---------------------------------------------------------------------------

@register("take")
def _take(attrs, a, indices):
    axis = int(attrs.get("axis", 0))
    mode = attrs.get("mode", "clip")
    return jnp.take(a, indices.astype(jnp.int32), axis=axis,
                    mode={"clip": "clip", "wrap": "wrap"}.get(mode, "clip"))


@register("batch_take")
def _batch_take(attrs, a, indices):
    return a[jnp.arange(a.shape[0]), indices.astype(jnp.int32)]


@register("Embedding")
def _embedding(attrs, data, weight):
    """Reference ``src/operator/tensor/indexing_op.cc`` Embedding: table
    lookup.  ``jnp.take`` lowers to an XLA gather; the backward is a scatter
    that XLA turns into efficient sorted-segment-sum on TPU."""
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("one_hot")
def _one_hot(attrs, indices):
    depth = int(attrs["depth"])
    on = float(attrs.get("on_value", 1.0))
    off = float(attrs.get("off_value", 0.0))
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    hot = (indices.astype(jnp.int32)[..., None] ==
           jnp.arange(depth, dtype=jnp.int32))
    return jnp.where(hot, on, off).astype(dtype)


@register("pick", aliases=("choose_element_0index",))
def _pick(attrs, x, index):
    axis = attrs.get("axis", 1)
    axis = x.ndim - 1 if axis is None else int(axis)
    idx = jnp.clip(index.astype(jnp.int32), 0, x.shape[axis] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(idx, axis), axis=axis)
    if bool(attrs.get("keepdims", False)):
        return picked
    return jnp.squeeze(picked, axis=axis)


@register("where")
def _where(attrs, cond, a, b):
    if cond.ndim < a.ndim:  # row-wise condition, reference where semantics
        cond = cond.reshape(cond.shape + (1,) * (a.ndim - cond.ndim))
    return jnp.where(cond != 0, a, b)


@register("ones_like")
def _ones_like(attrs, x):
    return jnp.ones_like(x)


@register("zeros_like")
def _zeros_like(attrs, x):
    return jnp.zeros_like(x)


@register("_identity_with_attr_like_rhs")
def _ident_like(attrs, lhs, rhs):
    return lhs


# ---------------------------------------------------------------------------
# init ops (no tensor inputs)
# ---------------------------------------------------------------------------

@register("_zeros", aliases=("zeros",))
def _zeros(attrs):
    return jnp.zeros(tuple(attrs["shape"]), jnp.dtype(attrs.get("dtype", "float32")))


@register("_ones", aliases=("ones",))
def _ones(attrs):
    return jnp.ones(tuple(attrs["shape"]), jnp.dtype(attrs.get("dtype", "float32")))


@register("_full", aliases=("full",))
def _full(attrs):
    return jnp.full(tuple(attrs["shape"]), float(attrs["value"]),
                    jnp.dtype(attrs.get("dtype", "float32")))


@register("_arange", aliases=("arange",))
def _arange(attrs):
    start = float(attrs.get("start", 0))
    stop = attrs.get("stop")
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    dtype = jnp.dtype(attrs.get("dtype", "float32"))
    out = np.arange(start, stop if stop is None else float(stop), step)
    if repeat > 1:
        out = np.repeat(out, repeat)
    return jnp.asarray(out, dtype)


@register("_eye", aliases=("eye",))
def _eye(attrs):
    n = int(attrs["N"])
    m = int(attrs.get("M", 0)) or n
    return jnp.eye(n, m, int(attrs.get("k", 0)),
                   dtype=jnp.dtype(attrs.get("dtype", "float32")))


# ---------------------------------------------------------------------------
# ordering (reference ordering_op.cc) — static shapes keep XLA happy;
# topk's k is an attr (static), so results are fixed-shape.
# ---------------------------------------------------------------------------

@register("sort")
def _sort(attrs, x):
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis)
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis) if not attrs.get("is_ascend", True) else out


@register("argsort")
def _argsort(attrs, x):
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis)
    out = jnp.argsort(x, axis=axis)
    if not attrs.get("is_ascend", True):
        out = jnp.flip(out, axis)
    return out.astype(jnp.dtype(attrs.get("dtype", "float32")))


def _topk_outputs(attrs):
    return 2 if attrs.get("ret_typ", "indices") == "both" else 1


@register("topk", num_outputs=_topk_outputs)
def _topk(attrs, x):
    axis = attrs.get("axis", -1)
    axis = x.ndim - 1 if axis is None else int(axis)
    k = int(attrs.get("k", 1))
    ret = attrs.get("ret_typ", "indices")
    largest = bool(attrs.get("is_ascend", False)) is False
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.float32)
    if ret == "value":
        return vals
    if ret == "both":
        return vals, idx
    if ret == "mask":
        raise MXNetError("topk ret_typ='mask' not supported yet")
    return idx


# ---------------------------------------------------------------------------
# linalg / dot — the MXU path
# ---------------------------------------------------------------------------

@register("dot")
def _dot(attrs, a, b):
    ta, tb = bool(attrs.get("transpose_a", False)), bool(attrs.get("transpose_b", False))
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b)
    am = a.T if ta else a
    bm = b.T if tb else b
    # collapse leading dims (reference dot treats >2D as matrix over
    # flattened leading/trailing dims)
    if am.ndim > 2:
        am = am.reshape(-1, am.shape[-1])
    if bm.ndim > 2:
        bm = bm.reshape(bm.shape[0], -1)
    from ..quantize import fp8_apply_dot

    out = fp8_apply_dot(am, bm, label=attrs.get("__node_name__"), w_dim=0)
    if out is None:
        out = lax.dot_general(
            am, bm, (((am.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.promote_types(a.dtype, jnp.float32)
            if a.dtype == jnp.bfloat16 else None,
        )
    return out.astype(a.dtype)


@register("batch_dot")
def _batch_dot(attrs, a, b):
    ta, tb = bool(attrs.get("transpose_a", False)), bool(attrs.get("transpose_b", False))
    if ta:
        a = jnp.swapaxes(a, -1, -2)
    if tb:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@register("linalg_gemm")
def _linalg_gemm(attrs, a, b, c):
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    ta, tb = bool(attrs.get("transpose_a", False)), bool(attrs.get("transpose_b", False))
    am = jnp.swapaxes(a, -1, -2) if ta else a
    bm = jnp.swapaxes(b, -1, -2) if tb else b
    return alpha * jnp.matmul(am, bm) + beta * c


@register("linalg_gemm2")
def _linalg_gemm2(attrs, a, b):
    alpha = float(attrs.get("alpha", 1.0))
    ta, tb = bool(attrs.get("transpose_a", False)), bool(attrs.get("transpose_b", False))
    am = jnp.swapaxes(a, -1, -2) if ta else a
    bm = jnp.swapaxes(b, -1, -2) if tb else b
    return alpha * jnp.matmul(am, bm)


@register("linalg_potrf")
def _potrf(attrs, a):
    return jnp.linalg.cholesky(a)


@register("linalg_potri")
def _potri(attrs, a):
    # inverse from cholesky factor: (A A^T)^-1 given L
    eye = jnp.broadcast_to(jnp.eye(a.shape[-1], dtype=a.dtype), a.shape)
    linv = lax.linalg.triangular_solve(a, eye, lower=True, left_side=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("linalg_trmm")
def _trmm(attrs, a, b):
    alpha = float(attrs.get("alpha", 1.0))
    transpose = bool(attrs.get("transpose", False))
    rightside = bool(attrs.get("rightside", False))
    am = jnp.swapaxes(a, -1, -2) if transpose else a
    return alpha * (jnp.matmul(b, am) if rightside else jnp.matmul(am, b))


@register("linalg_trsm")
def _trsm(attrs, a, b):
    alpha = float(attrs.get("alpha", 1.0))
    transpose = bool(attrs.get("transpose", False))
    rightside = bool(attrs.get("rightside", False))
    return alpha * lax.linalg.triangular_solve(
        a, b, left_side=not rightside, lower=True,
        transpose_a=transpose)


@register("linalg_sumlogdiag")
def _sumlogdiag(attrs, a):
    diag = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("linalg_syrk")
def _syrk(attrs, a):
    alpha = float(attrs.get("alpha", 1.0))
    transpose = bool(attrs.get("transpose", False))
    at = jnp.swapaxes(a, -1, -2)
    return alpha * (jnp.matmul(at, a) if transpose else jnp.matmul(a, at))
