"""Neural-network layer operators.

Covers the reference's legacy layer ops (``MXNET_REGISTER_OP_PROPERTY`` —
Convolution, FullyConnected, Pooling, BatchNorm, Activation, Dropout,
SoftmaxOutput, LRN, LeakyReLU, UpSampling, InstanceNorm, L2Normalization,
SequenceMask/Last/Reverse, … — SURVEY.md §2.1 "Operators — neural net").

TPU-first notes:
* Convolutions use ``lax.conv_general_dilated``; data stays in the MXNet
  NCHW calling convention and XLA's TPU layout assignment picks the
  physical layout — no hand transposes.
* Losses with fused backwards in the reference (SoftmaxOutput, the
  regression outputs) keep their exact gradient contract via
  ``jax.custom_vjp``: backward emits ``(p - label) * grad_scale`` times
  the head cotangent (ones under the reference's ``Executor.backward``
  seeding, so results match ``src/operator/softmax_output-inl.h`` /
  ``regression_output-inl.h`` exactly; a dynamic loss scale from the
  run-health sentinel rides the cotangent into the backward chain).
* Stateful normalization (BatchNorm moving stats) threads state functionally:
  the op returns updated stats and the invoke layer rebinds the aux
  NDArrays — replacing the reference's in-place aux mutation.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register


def _pair(v, n=2):
    if v is None:
        return (1,) * n
    if isinstance(v, (int, float)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    return t if t else (1,) * n


# ---------------------------------------------------------------------------
# FullyConnected — a plain MXU matmul
# ---------------------------------------------------------------------------

@register("FullyConnected", aliases=("fully_connected",))
def _fully_connected(attrs, data, weight, *bias):
    """Reference ``src/operator/fully_connected.cc``: Y = X W^T + b."""
    if bool(attrs.get("flatten", True)) and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    from ..quantize import fp8_apply_dot

    out = fp8_apply_dot(data, weight, label=attrs.get("__node_name__"),
                        w_dim=1)
    if out is None:
        # bf16 inputs produce bf16 outputs; the MXU accumulates in fp32
        # internally, and an explicit preferred_element_type=f32 would
        # break the conv/dot transpose rules (f32 cotangent vs bf16
        # operand)
        out = lax.dot_general(
            data, weight, (((data.ndim - 1,), (1,)), ((), ())))
    if bias:
        out = out + bias[0]
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution / Pooling
# ---------------------------------------------------------------------------

def _conv_dims(kernel_ndim, layout=None):
    # Dimension numbers for 1/2/3 spatial dims.  Default is the MXNet
    # NCHW family; channels-last layouts (NWC/NHWC/NDHWC — the TPU-native
    # choice: C rides the 128-lane dim, so BN reductions are
    # lane-parallel and convs skip relayouts) use OHWI-style weights,
    # matching the reference's cuDNN-NHWC convention (weight (O, *k, I),
    # ``src/operator/convolution-inl.h`` layout param).
    if layout in (None, "NCW", "NCHW", "NCDHW"):
        spec = {1: ("NCH", "OIH", "NCH"),
                2: ("NCHW", "OIHW", "NCHW"),
                3: ("NCDHW", "OIDHW", "NCDHW")}[kernel_ndim]
    elif layout in ("NWC", "NHWC", "NDHWC"):
        spec = {1: ("NHC", "OHI", "NHC"),
                2: ("NHWC", "OHWI", "NHWC"),
                3: ("NDHWC", "ODHWI", "NDHWC")}[kernel_ndim]
    else:
        raise MXNetError("unsupported conv layout %r" % layout)
    return lax.conv_dimension_numbers((0,) * (kernel_ndim + 2),
                                      (0,) * (kernel_ndim + 2), spec)


def _channels_last(layout):
    return layout in ("NWC", "NHWC", "NDHWC")


@register("Convolution", aliases=("conv", "Convolution_v1"))
def _convolution(attrs, data, weight, *bias):
    """Reference ``src/operator/convolution-inl.h``: grouped ND convolution,
    weight (O, I/g, *kernel) for NC-first layouts, (O, *kernel, I/g) for
    channels-last."""
    kernel = _pair(attrs["kernel"], len(attrs["kernel"]))
    nd = len(kernel)
    layout = attrs.get("layout")
    stride = _pair(attrs.get("stride"), nd)
    pad = _pair(attrs.get("pad", (0,) * nd), nd)
    dilate = _pair(attrs.get("dilate"), nd)
    groups = int(attrs.get("num_group", 1))
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=tuple((p, p) for p in pad),
        rhs_dilation=dilate,
        dimension_numbers=_conv_dims(nd, layout),
        feature_group_count=groups,
    )
    if bias:
        b = bias[0] if _channels_last(layout) else \
            bias[0].reshape((1, -1) + (1,) * nd)
        out = out + b
    return out


@register("Deconvolution")
def _deconvolution(attrs, data, weight, *bias):
    """Reference ``src/operator/deconvolution-inl.h``: transposed conv.
    Implemented as the gradient-of-conv form via lhs dilation."""
    kernel = _pair(attrs["kernel"], len(attrs["kernel"]))
    nd = len(kernel)
    stride = _pair(attrs.get("stride"), nd)
    pad = _pair(attrs.get("pad", (0,) * nd), nd)
    adj = _pair(attrs.get("adj", (0,) * nd), nd)
    groups = int(attrs.get("num_group", 1))
    # transposed conv = conv with lhs_dilation=stride, flipped spatial kernel,
    # swapped I/O on the weight, padding k-1-p
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    if groups == 1:
        w = jnp.swapaxes(w, 0, 1)
    else:
        o, i = weight.shape[0], weight.shape[1]
        w = w.reshape((groups, o // groups, i) + w.shape[2:])
        w = jnp.swapaxes(w, 1, 2).reshape((groups * i, o // groups) + w.shape[3:])
    padding = tuple((kernel[d] - 1 - pad[d], kernel[d] - 1 - pad[d] + adj[d])
                    for d in range(nd))
    out = lax.conv_general_dilated(
        data, w,
        window_strides=(1,) * nd,
        padding=padding,
        lhs_dilation=stride,
        dimension_numbers=_conv_dims(nd),
        feature_group_count=groups,
    ).astype(data.dtype)
    if bias:
        out = out + bias[0].reshape((1, -1) + (1,) * nd)
    return out


@register("Pooling", aliases=("Pooling_v1",))
def _pooling(attrs, data):
    """Reference ``src/operator/pooling-inl.h``: max/avg/sum pooling with
    global_pool and 'valid'/'full' conventions."""
    pool_type = attrs.get("pool_type", "max")
    layout = attrs.get("layout")
    ch_last = _channels_last(layout)
    nd = data.ndim - 2
    sp0 = 1 if ch_last else 2  # first spatial axis
    if bool(attrs.get("global_pool", False)):
        axes = tuple(range(sp0, sp0 + nd))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _pair(attrs["kernel"], len(attrs["kernel"]))
    nd = len(kernel)
    stride = _pair(attrs.get("stride"), nd)
    pad = _pair(attrs.get("pad", (0,) * nd), nd)
    window = (1,) + kernel + (1,) if ch_last else (1, 1) + kernel
    strides = (1,) + stride + (1,) if ch_last else (1, 1) + stride
    # 'full' (ceil) convention pads the high edge so partial windows count
    # (reference pooling-inl.h pooling_convention)
    extra = [0] * nd
    if attrs.get("pooling_convention", "valid") == "full":
        for d in range(nd):
            size = data.shape[sp0 + d] + 2 * pad[d] - kernel[d]
            rem = size % stride[d]
            if rem:
                extra[d] = stride[d] - rem
    sp_padding = tuple((p, p + e) for p, e in zip(pad, extra))
    padding = ((0, 0),) + sp_padding + ((0, 0),) if ch_last \
        else ((0, 0), (0, 0)) + sp_padding
    # init values must be CONCRETE (numpy) scalars: a jnp array created
    # under a jit trace is a tracer constant, which breaks reduce_window's
    # linearization rule (jit(grad(maxpool)) fails with "Linearization
    # failed to produce known values")
    import numpy as _onp

    if pool_type == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = _onp.array(-_onp.inf, data.dtype)
        else:
            init = _onp.array(_onp.iinfo(data.dtype).min, data.dtype)
        return lax.reduce_window(data, init, lax.max,
                                 window, strides, padding)
    summed = lax.reduce_window(data, _onp.array(0, data.dtype), lax.add,
                               window, strides, padding)
    if pool_type == "sum":
        return summed
    if pool_type == "avg":
        # count_include_pad=True matches the reference default
        denom = 1
        for k in kernel:
            denom *= k
        return summed / jnp.asarray(denom, data.dtype)
    raise MXNetError("unknown pool_type %r" % pool_type)


@register("UpSampling")
def _upsampling(attrs, *inputs):
    """Reference ``src/operator/upsampling.cc``: nearest / bilinear scale-up."""
    scale = int(attrs["scale"])
    sample_type = attrs.get("sample_type", "nearest")
    data = inputs[0]
    n, c, h, w = data.shape
    if sample_type == "nearest":
        out = jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
        return out
    return jax.image.resize(data, (n, c, h * scale, w * scale), method="bilinear")


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

@register("Activation")
def _activation(attrs, x):
    act = attrs.get("act_type", "relu")
    if act == "relu":
        return jnp.maximum(x, 0)
    if act == "sigmoid":
        return jax.nn.sigmoid(x)
    if act == "tanh":
        return jnp.tanh(x)
    if act == "softrelu":
        return jax.nn.softplus(x)
    if act == "softsign":
        return jax.nn.soft_sign(x)
    if act == "gelu":
        # post-0.11 addition for the transformer family (tanh approx,
        # the TPU-friendly form)
        return jax.nn.gelu(x)
    raise MXNetError("unknown act_type %r" % act)


@register("LeakyReLU", needs_rng=True, uses_train_mode=True)
def _leaky_relu(attrs, rng, x, *gamma):
    act = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    if act == "leaky":
        return jnp.where(x > 0, x, slope * x)
    if act == "elu":
        return jnp.where(x > 0, x, slope * jnp.expm1(x))
    if act == "prelu":
        g = gamma[0].reshape((1, -1) + (1,) * (x.ndim - 2))
        return jnp.where(x > 0, x, g * x)
    if act == "rrelu":
        lo, hi = float(attrs.get("lower_bound", 0.125)), float(attrs.get("upper_bound", 0.334))
        if attrs.get("__is_train__", False):
            s = jax.random.uniform(rng, x.shape, x.dtype, lo, hi)
            return jnp.where(x > 0, x, s * x)
        return jnp.where(x > 0, x, ((lo + hi) / 2) * x)
    raise MXNetError("unknown LeakyReLU act_type %r" % act)


@register("softmax")
def _softmax(attrs, x):
    t = float(attrs.get("temperature") or 1.0)
    return jax.nn.softmax(x / t, axis=int(attrs.get("axis", -1)))


@register("log_softmax")
def _log_softmax(attrs, x):
    t = float(attrs.get("temperature") or 1.0)
    return jax.nn.log_softmax(x / t, axis=int(attrs.get("axis", -1)))


@register("SoftmaxActivation")
def _softmax_activation(attrs, x):
    if attrs.get("mode", "instance") == "channel":
        return jax.nn.softmax(x, axis=1)
    return jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)


# ---------------------------------------------------------------------------
# fused losses (custom gradient contract, like the reference)
# ---------------------------------------------------------------------------

def _fused_loss(forward_out, grad_fn):
    """Build output whose vjp wrt inputs is grad_fn(...), ignoring head grads
    — the reference's loss-layer contract (grad seeded by the op itself)."""
    return forward_out, grad_fn


@register("SoftmaxOutput", aliases=("Softmax",))
def _softmax_output(attrs, data, label):
    """Reference ``src/operator/softmax_output-inl.h``.  Forward = softmax;
    backward(data) = (softmax - onehot(label)) * grad_scale, with
    use_ignore/ignore_label and multi_output support; head grad ignored."""
    grad_scale = float(attrs.get("grad_scale", 1.0))
    ignore_label = float(attrs.get("ignore_label", -1))
    use_ignore = bool(attrs.get("use_ignore", False))
    multi_output = bool(attrs.get("multi_output", False))
    normalization = attrs.get("normalization", "null")

    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(d, l):
        return jax.nn.softmax(d, axis=axis)

    def fwd(d, l):
        p = jax.nn.softmax(d, axis=axis)
        return p, (p, l)

    def bwd(res, g):
        p, l = res
        li = l.astype(jnp.int32)
        if multi_output:
            onehot = jax.nn.one_hot(li, p.shape[1], axis=1, dtype=p.dtype)
        else:
            onehot = jax.nn.one_hot(li, p.shape[-1], dtype=p.dtype)
        grad = p - onehot
        if use_ignore:
            mask = (l != ignore_label).astype(p.dtype)
            mask = jnp.expand_dims(mask, axis=1 if multi_output else -1)
            grad = grad * mask
        # under the DDP grad-overlap shard_map the op sees only the
        # local batch shard; widen batch/valid normalization to the
        # global batch or the psum of per-replica gradients over-counts
        # by the replica factor
        from ..parallel import overlap as _ov

        scale = grad_scale
        if normalization == "batch":
            scale = scale / (p.shape[0] * _ov.ddp_batch_factor())
        elif normalization == "valid" and use_ignore:
            valid = jnp.maximum(_ov.ddp_psum(jnp.sum(l != ignore_label)),
                                1)
            grad = grad / valid.astype(p.dtype)
        grad = grad * scale
        # ride the head cotangent: the reference seeds ones (identical
        # result); the fused step's dynamic loss scale arrives here as a
        # constant cotangent and scales the whole downstream backward
        grad = grad * g.astype(grad.dtype)
        return grad, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


def _regression_output(transform, grad):
    def compute(attrs, data, label):
        grad_scale = float(attrs.get("grad_scale", 1.0))

        @jax.custom_vjp
        def f(d, l):
            return transform(d)

        def fwd(d, l):
            return transform(d), (d, l)

        def bwd(res, g):
            d, l = res
            num = 1
            for s in d.shape[1:]:
                num *= s
            gd = grad(transform(d), l.reshape(d.shape)) * (grad_scale / num)
            gd = gd * g.astype(gd.dtype)  # ones-seeded: identity
            return gd, jnp.zeros_like(l)

        f.defvjp(fwd, bwd)
        return f(data, label)
    return compute


register("LinearRegressionOutput",
         _regression_output(lambda d: d, lambda p, l: p - l))
register("MAERegressionOutput",
         _regression_output(lambda d: d, lambda p, l: jnp.sign(p - l)))
register("LogisticRegressionOutput",
         _regression_output(jax.nn.sigmoid, lambda p, l: p - l))


@register("SVMOutput")
def _svm_output(attrs, data, label):
    margin = float(attrs.get("margin", 1.0))
    reg = float(attrs.get("regularization_coefficient", 1.0))
    use_linear = bool(attrs.get("use_linear", False))

    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        li = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(li, d.shape[-1], dtype=d.dtype)
        # hinge: for wrong classes, +1 if margin violated; correct class -1
        score_correct = jnp.sum(d * onehot, axis=-1, keepdims=True)
        if use_linear:
            viol = ((d - score_correct + margin) > 0).astype(d.dtype) * (1 - onehot)
            gd = reg * (viol - onehot * jnp.sum(viol, axis=-1, keepdims=True))
        else:
            m = jnp.maximum(0., d - score_correct + margin) * (1 - onehot)
            gd = reg * 2 * (m - onehot * jnp.sum(m, axis=-1, keepdims=True))
        gd = gd * g.astype(gd.dtype)  # ones-seeded: identity
        return gd, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("softmax_cross_entropy")
def _softmax_xent(attrs, data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    li = label.astype(jnp.int32)
    return -jnp.sum(jnp.take_along_axis(logp, li[:, None], axis=-1))


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------

@register("BatchNorm", aliases=("BatchNorm_v1",), uses_train_mode=True,
          mutable_inputs=(3, 4))
def _batch_norm(attrs, data, gamma, beta, moving_mean, moving_var):
    """Reference ``src/operator/batch_norm-inl.h``.  Inputs: data, gamma,
    beta, aux moving_mean, moving_var; returns (out, new_mean, new_var).
    ``fix_gamma`` pins gamma to 1 (reference default!), axis=1 (channel)."""
    eps = float(attrs.get("eps", 1e-3))
    momentum = float(attrs.get("momentum", 0.9))
    fix_gamma = bool(attrs.get("fix_gamma", True))
    use_global = bool(attrs.get("use_global_stats", False))
    axis = int(attrs.get("axis", 1))
    is_train = bool(attrs.get("__is_train__", False)) and not use_global

    reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1 for i in range(data.ndim))

    if is_train:
        from ..parallel import overlap as _ov
        from .pallas_bn import pallas_bn_enabled

        # under the DDP grad-overlap shard_map, the batch statistics
        # must be the GLOBAL batch's (sync-BN): pmean the local moments
        # so the normalization — and the gradient flowing back through
        # it — matches the GSPMD global-batch computation exactly
        sync = _ov.ddp_batch_factor() > 1
        if axis == 1 and not sync and pallas_bn_enabled(data):
            # opt-in custom-kernel path (hand-written vjp + pallas sums)
            out, mean, var = _bn_train(eps, axis, fix_gamma)(
                data, gamma, beta)
        else:
            # default: jnp formulation, gradients via autodiff — measured
            # FASTER end-to-end than the hand-written vjp on ResNet-50/
            # v5e (XLA fuses the stat reductions with their consumers
            # better than the custom bwd's explicit passes)
            g = jnp.ones_like(gamma) if fix_gamma else gamma
            if data.dtype == jnp.bfloat16 or sync:
                mean = jnp.mean(data, axis=reduce_axes, dtype=jnp.float32)
                mean_sq = jnp.mean(jnp.square(data.astype(jnp.float32)),
                                   axis=reduce_axes)
                mean = _ov.ddp_pmean(mean)
                mean_sq = _ov.ddp_pmean(mean_sq)
                var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            else:
                data32 = data.astype(jnp.float32)
                mean = jnp.mean(data32, axis=reduce_axes)
                var = jnp.var(data32, axis=reduce_axes)
            g32 = g.astype(jnp.float32).reshape(bshape)
            inv = lax.rsqrt(var + eps).reshape(bshape)
            scale = (inv * g32).astype(data.dtype)
            shift = (beta.astype(jnp.float32).reshape(bshape) -
                     mean.reshape(bshape) * inv * g32).astype(data.dtype)
            out = data * scale + shift
        # keep the aux-state dtype stable: cast the fp32 batch stats to the
        # moving buffers' dtype before blending, else bf16 aux would drift
        # to fp32 after one step (retraces + checkpoint dtype mismatch)
        new_mean = momentum * moving_mean + (1 - momentum) * \
            lax.stop_gradient(mean).astype(moving_mean.dtype)
        new_var = momentum * moving_var + (1 - momentum) * \
            lax.stop_gradient(var).astype(moving_var.dtype)
        return out, new_mean, new_var

    g = jnp.ones_like(gamma) if fix_gamma else gamma
    mean32 = moving_mean.astype(jnp.float32)
    var32 = moving_var.astype(jnp.float32)
    g32 = g.astype(jnp.float32).reshape(bshape)
    inv = lax.rsqrt(var32 + eps).reshape(bshape)
    scale = (inv * g32).astype(data.dtype)
    shift = (beta.astype(jnp.float32).reshape(bshape) -
             mean32.reshape(bshape) * inv * g32).astype(data.dtype)
    out = data * scale + shift
    return out, moving_mean, moving_var


@functools.lru_cache(maxsize=None)
def _bn_train(eps, axis, fix_gamma):
    """Training-mode BN with a hand-written backward.

    Autodiff through the fp32-accumulated statistics path materializes
    fp32 activation-sized cotangents (double-width HBM traffic on the
    bf16 bench path — measured ~20% of step bytes on ResNet-50).  The
    closed-form BN gradient keeps every activation-sized tensor in the
    data dtype and accumulates the two reductions in fp32:

        dx = (g·inv) · (dxhat − E[dxhat] − xhat·E[dxhat·xhat])

    (biased-variance form, matching the forward's jnp.var).  Cotangents
    for the mean/var outputs are ignored: the only consumer is the
    moving-stat blend behind ``lax.stop_gradient``.
    """
    import jax

    def stats(data, reduce_axes):
        from . import pallas_bn

        if axis == 1 and pallas_bn.pallas_bn_enabled(data):
            s1, s2 = pallas_bn.bn_stats(data)
            count = data.size // data.shape[axis]
            mean = s1 / count
            var = jnp.maximum(s2 / count - jnp.square(mean), 0.0)
            return mean, var
        # fp32-accumulated moments without materializing an fp32 copy of
        # the activations; E[x^2]-E[x]^2 cancellation is bounded by input
        # precision for bf16, and the fp32 path keeps the two-pass form
        if data.dtype == jnp.bfloat16:
            mean = jnp.mean(data, axis=reduce_axes, dtype=jnp.float32)
            mean_sq = jnp.mean(jnp.square(data.astype(jnp.float32)),
                               axis=reduce_axes)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
        else:
            data32 = data.astype(jnp.float32)
            mean = jnp.mean(data32, axis=reduce_axes)
            var = jnp.var(data32, axis=reduce_axes)
        return mean, var

    @jax.custom_vjp
    def bn(data, gamma, beta):
        return bn_fwd(data, gamma, beta)[0]

    def bn_fwd(data, gamma, beta):
        reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
        bshape = tuple(data.shape[axis] if i == axis else 1
                       for i in range(data.ndim))
        mean, var = stats(data, reduce_axes)
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        g32 = g.astype(jnp.float32)
        inv = lax.rsqrt(var + eps)
        scale = (inv * g32).reshape(bshape).astype(data.dtype)
        shift = ((beta.astype(jnp.float32) - mean * inv * g32)
                 .reshape(bshape)).astype(data.dtype)
        out = data * scale + shift
        return (out, mean, var), (data, gamma, mean, inv)

    def bn_bwd(res, cts):
        from . import pallas_bn

        data, gamma, mean, inv = res
        dy = cts[0]  # d(mean)/d(var) cotangents are zero (stop_gradient)
        dt = data.dtype
        reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
        bshape = tuple(data.shape[axis] if i == axis else 1
                       for i in range(data.ndim))
        n = 1
        for i in reduce_axes:
            n *= data.shape[i]
        g32 = jnp.ones_like(gamma).astype(jnp.float32) if fix_gamma \
            else gamma.astype(jnp.float32)
        inv_b = inv.reshape(bshape).astype(dt)
        mean_b = mean.reshape(bshape).astype(dt)
        xhat = (data - mean_b) * inv_b
        if axis == 1 and pallas_bn.pallas_bn_enabled(data, streams=2):
            # one streamed pass over (dy, x) for both channel sums; dx is
            # a single fused elementwise pass (dxhat = g*dy folds into
            # per-channel constants)
            s_dy, s_dyxhat = pallas_bn.bn_grad_sums(dy, data, mean, inv)
            gi_b = (g32 * inv).reshape(bshape).astype(dt)
            e_dy = (s_dy / n).reshape(bshape).astype(dt)
            e_dyxhat = (s_dyxhat / n).reshape(bshape).astype(dt)
            dx = gi_b * (dy - e_dy - xhat * e_dyxhat)
            dbeta = s_dy.astype(gamma.dtype)
            dgamma = jnp.zeros_like(gamma) if fix_gamma \
                else s_dyxhat.astype(gamma.dtype)
            return dx, dgamma, dbeta
        dxhat = dy * g32.reshape(bshape).astype(dt)
        e_dxhat = (jnp.sum(dxhat, axis=reduce_axes, dtype=jnp.float32)
                   / n).reshape(bshape)
        e_dxhat_xhat = (jnp.sum(dxhat * xhat, axis=reduce_axes,
                                dtype=jnp.float32) / n).reshape(bshape)
        dx = inv_b * (dxhat - e_dxhat.astype(dt)
                      - xhat * e_dxhat_xhat.astype(dt))
        dbeta = jnp.sum(dy, axis=reduce_axes,
                        dtype=jnp.float32).astype(gamma.dtype)
        if fix_gamma:
            dgamma = jnp.zeros_like(gamma)
        else:
            dgamma = jnp.sum(dy * xhat, axis=reduce_axes,
                             dtype=jnp.float32).astype(gamma.dtype)
        return dx, dgamma, dbeta

    def bn_fwd_full(data, gamma, beta):
        return bn_fwd(data, gamma, beta)

    bn.defvjp(bn_fwd_full, bn_bwd)
    return bn


@register("InstanceNorm")
def _instance_norm(attrs, data, gamma, beta):
    eps = float(attrs.get("eps", 1e-3))
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return ((data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape)
            + beta.reshape(bshape))


@register("LayerNorm")
def _layer_norm(attrs, data, gamma, beta):
    """Not in the 0.11 reference but required by the transformer model
    family this framework adds; axis=-1."""
    eps = float(attrs.get("eps", 1e-5))
    axis = int(attrs.get("axis", -1))
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


@register("L2Normalization")
def _l2_normalization(attrs, data):
    eps = float(attrs.get("eps", 1e-10))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register("LRN")
def _lrn(attrs, data):
    """Reference ``src/operator/lrn.cc`` cross-channel local response norm."""
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    knorm = float(attrs.get("knorm", 2.0))
    nsize = int(attrs["nsize"])
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(padded[:, i:i + data.shape[1]] for i in range(nsize))
    return data / jnp.power(knorm + alpha * windows / nsize, beta)


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------

@register("Dropout", needs_rng=True, uses_train_mode=True)
def _dropout(attrs, rng, x):
    """Reference ``src/operator/dropout-inl.h``: inverted dropout, scaled at
    train time, identity at inference."""
    p = float(attrs.get("p", 0.5))
    mode = attrs.get("mode", "training")
    is_train = bool(attrs.get("__is_train__", False))
    if p <= 0 or (not is_train and mode != "always"):
        return x
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0).astype(x.dtype)


# ---------------------------------------------------------------------------
# sequence ops
# ---------------------------------------------------------------------------

def _seq_len_mask(seq_len, maxlen, dtype):
    return (jnp.arange(maxlen)[:, None] <
            seq_len.astype(jnp.int32)[None, :]).astype(dtype)


@register("SequenceMask")
def _sequence_mask(attrs, data, *seq_len):
    """Reference ``src/operator/sequence_mask.cc``: (T, B, ...) time-major."""
    if not bool(attrs.get("use_sequence_length", False)) or not seq_len:
        return data
    value = float(attrs.get("value", 0.0))
    mask = _seq_len_mask(seq_len[0], data.shape[0], data.dtype)
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return data * mask + value * (1 - mask)


@register("SequenceLast")
def _sequence_last(attrs, data, *seq_len):
    if bool(attrs.get("use_sequence_length", False)) and seq_len:
        idx = seq_len[0].astype(jnp.int32) - 1
        return data[idx, jnp.arange(data.shape[1])]
    return data[-1]


@register("SequenceReverse")
def _sequence_reverse(attrs, data, *seq_len):
    if bool(attrs.get("use_sequence_length", False)) and seq_len:
        T = data.shape[0]
        sl = seq_len[0].astype(jnp.int32)
        t = jnp.arange(T)[:, None]
        idx = jnp.where(t < sl[None, :], sl[None, :] - 1 - t, t)
        return jnp.take_along_axis(
            data, idx.reshape(idx.shape + (1,) * (data.ndim - 2)), axis=0)
    return jnp.flip(data, 0)


# ---------------------------------------------------------------------------
# spatial ops
# ---------------------------------------------------------------------------

@register("Crop")
def _crop(attrs, *inputs):
    data = inputs[0]
    if len(inputs) == 2:
        h, w = inputs[1].shape[2], inputs[1].shape[3]
    else:
        h, w = (int(v) for v in attrs["h_w"])
    if bool(attrs.get("center_crop", False)):
        y0 = (data.shape[2] - h) // 2
        x0 = (data.shape[3] - w) // 2
    else:
        offset = attrs.get("offset", (0, 0))
        y0, x0 = int(offset[0]), int(offset[1])
    return data[:, :, y0:y0 + h, x0:x0 + w]


@register("GridGenerator")
def _grid_generator(attrs, data):
    """Reference ``src/operator/grid_generator.cc``: affine → sampling grid."""
    h, w = (int(v) for v in attrs["target_shape"])
    if attrs.get("transform_type", "affine") == "affine":
        n = data.shape[0]
        theta = data.reshape(n, 2, 3)
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
        gx, gy = jnp.meshgrid(xs, ys)
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
        out = jnp.einsum("nij,jk->nik", theta, grid)
        return out.reshape(n, 2, h, w)
    return data  # warp type passes flow through


@register("BilinearSampler")
def _bilinear_sampler(attrs, data, grid):
    """Reference ``src/operator/bilinear_sampler.cc``: sample data at grid
    coords in [-1, 1] (x, y channels)."""
    n, c, h, w = data.shape
    gx = (grid[:, 0] + 1) * (w - 1) / 2
    gy = (grid[:, 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = gx - x0; wx0 = 1 - wx1
    wy1 = gy - y0; wy0 = 1 - wy1

    def gather(yy, xx):
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
        vals = data[jnp.arange(n)[:, None, None], :, yc, xc]  # (n,oh,ow,c)
        return jnp.where(valid[..., None], vals, 0)

    out = (gather(y0, x0) * (wy0 * wx0)[..., None]
           + gather(y0, x1) * (wy0 * wx1)[..., None]
           + gather(y1, x0) * (wy1 * wx0)[..., None]
           + gather(y1, x1) * (wy1 * wx1)[..., None])
    return jnp.moveaxis(out, -1, 1)


@register("SpatialTransformer")
def _spatial_transformer(attrs, data, loc):
    h, w = (int(v) for v in attrs["target_shape"])
    grid = _grid_generator(
        {"target_shape": (h, w), "transform_type": "affine"}, loc)
    return _bilinear_sampler({}, data, grid)


@register("ROIPooling")
def _roi_pooling(attrs, data, rois):
    """Reference ``src/operator/roi_pooling.cc``: max-pool each ROI to a
    fixed grid.  rois: (R, 5) = [batch_idx, x1, y1, x2, y2]."""
    ph, pw = (int(v) for v in attrs["pooled_size"])
    scale = float(attrs["spatial_scale"])
    n, c, h, w = data.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[b]  # (c, h, w)
        ys = jnp.arange(h); xs = jnp.arange(w)

        def cell(iy, ix):
            cy0 = y1 + (iy * rh) // ph
            cy1 = y1 + ((iy + 1) * rh + ph - 1) // ph
            cx0 = x1 + (ix * rw) // pw
            cx1 = x1 + ((ix + 1) * rw + pw - 1) // pw
            m = ((ys[:, None] >= cy0) & (ys[:, None] < jnp.maximum(cy1, cy0 + 1)) &
                 (xs[None, :] >= cx0) & (xs[None, :] < jnp.maximum(cx1, cx0 + 1)))
            masked = jnp.where(m[None], img, -jnp.inf)
            return jnp.max(masked, axis=(1, 2))

        iy, ix = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(iy, ix)  # (ph, pw, c)
        return jnp.moveaxis(cells, -1, 0)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------

@register("IdentityAttachKLSparseReg")
def _identity_kl(attrs, x):
    return x


# The "Custom" op (Python-defined ops over host callbacks) registers from
# mxnet_tpu/operator.py — reference src/operator/custom/custom.cc.


@register("_contrib_MultiHeadAttention", aliases=("MultiHeadAttention",),
          spans_mesh=lambda attrs: bool(attrs.get("seq_parallel", False)))
def _multi_head_attention(attrs, data, in_weight, in_bias, out_weight,
                          out_bias):
    """Fused causal multi-head self-attention.  Not in the 0.11 reference
    (attention post-dates it) — added for the transformer model family,
    shaped so every FLOP lands on the MXU: one (3C, C) input projection,
    einsum score/value matmuls batched over (batch, heads), one (C, C)
    output projection.  Softmax statistics run in fp32 regardless of the
    compute dtype (bf16-safe).  The score/value contraction dispatches
    through ``ops/attention.py`` — blockwise flash-style kernel with
    O(T·block) peak memory by default, the materialized reference path
    under ``MXNET_ATTN_IMPL=reference`` or the ``attn_impl`` attr.
    Sequence-parallel execution of the same contraction lives in
    ``parallel/sequence.py`` (ring attention, same per-block kernel).
    """
    from ..quantize import fp8_apply_dot

    num_heads = int(attrs["num_heads"])
    causal = bool(attrs.get("causal", True))
    n, t, c = data.shape
    d = c // num_heads
    qkv = fp8_apply_dot(data, in_weight, label=attrs.get("__node_name__"),
                        w_dim=1)
    if qkv is None:
        qkv = jnp.einsum("ntc,fc->ntf", data, in_weight)
    qkv = qkv + in_bias
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(x):
        return x.reshape(n, t, num_heads, d).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    if bool(attrs.get("seq_parallel", False)):
        if int(attrs.get("window", 0)):
            raise MXNetError(
                "MultiHeadAttention: window attr is not supported with "
                "seq_parallel=True (ring attention has no sliding-window "
                "mask)")
        # long-context path: shard T over the active mesh's 'seq' axis
        # and run ring attention (K/V rotate over ICI, O(T_local^2/ring)
        # peak memory per chip) — parallel/sequence.py
        from ..parallel import current_mesh
        from ..parallel.sequence import sequence_parallel_attention

        mesh = current_mesh()
        if mesh is None or "seq" not in mesh.shape:
            raise MXNetError(
                "MultiHeadAttention(seq_parallel=True) needs an active "
                "mesh with a 'seq' axis (parallel.mesh_scope)")
        ctx = sequence_parallel_attention(q, k, v, causal=causal,
                                          mesh=mesh)
    else:
        from .attention import dot_product_attention

        block = int(attrs["attn_block"]) if "attn_block" in attrs else None
        window = int(attrs.get("window", 0))
        ctx = dot_product_attention(q, k, v, causal=causal,
                                    impl=attrs.get("attn_impl") or None,
                                    block=block, window=window)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(n, t, c)
    proj = fp8_apply_dot(ctx, out_weight, label=attrs.get("__node_name__"),
                         w_dim=1)
    if proj is None:
        proj = jnp.einsum("ntc,oc->nto", ctx, out_weight)
    return proj + out_bias


@register("_contrib_MoE", aliases=("MoE",), num_outputs=2,
          spans_mesh=lambda attrs: bool(attrs.get("expert_parallel",
                                                  False)))
def _moe(attrs, data, gate_weight, w1_weight, w2_weight):
    """Top-k routed mixture-of-experts feed-forward (two outputs:
    ``out`` shaped like ``data`` and the scalar load-balancing aux
    loss).  Not in the 0.11 reference (MoE post-dates it; SURVEY.md
    §2.3 mandates expert parallelism as a fresh first-class design).
    Tokens route to their ``top_k`` experts under a capacity bound;
    with ``expert_parallel=True`` tokens shard over the active mesh's
    'expert' axis and dispatch/return ride two ``all_to_all`` hops on
    ICI (``parallel/expert.py``).  Add the aux output (scaled) to the
    objective via ``MakeLoss`` to keep experts load-balanced.
    """
    from ..parallel.expert import routed_moe_ffn

    top_k = int(attrs.get("top_k", 2))
    cf = float(attrs.get("capacity_factor", 1.25))
    n_exp = int(attrs.get("num_experts", gate_weight.shape[1]))
    if n_exp != w1_weight.shape[0]:
        raise MXNetError(
            "MoE: num_experts=%d but w1_weight carries %d experts"
            % (n_exp, w1_weight.shape[0]))
    mesh = False  # force the single-device path unless expert_parallel
    if bool(attrs.get("expert_parallel", False)):
        from ..parallel import current_mesh

        mesh = current_mesh()
        if mesh is None or "expert" not in mesh.shape:
            raise MXNetError(
                "MoE(expert_parallel=True) needs an active mesh with an "
                "'expert' axis (parallel.mesh_scope)")
    shape = data.shape
    tokens = data.reshape(-1, shape[-1])
    out, aux = routed_moe_ffn(tokens, gate_weight, w1_weight, w2_weight,
                              top_k=top_k, capacity_factor=cf, mesh=mesh)
    return out.reshape(shape), aux
