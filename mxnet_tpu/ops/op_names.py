"""Per-op input/aux naming metadata for the Symbol frontend.

In the reference, each operator property declares its argument names
(``ListArguments``/``ListAuxiliaryStates``, ``include/mxnet/operator.h``),
which is how ``Symbol.list_arguments()`` produces ``conv0_weight``,
``bn0_moving_mean`` … and how ``simple_bind`` knows what to allocate.
This table provides the same metadata for the TPU registry.

``aux`` marks trailing inputs that are *auxiliary states* (not arguments,
not differentiated — reference ``MXSymbolListAuxiliaryStates``); these must
line up with the op's ``mutable_inputs``.
"""
from __future__ import annotations

# op name -> (input names, aux input names)
INPUT_NAMES = {
    "FullyConnected": (("data", "weight", "bias"), ()),
    "Convolution": (("data", "weight", "bias"), ()),
    "Convolution_v1": (("data", "weight", "bias"), ()),
    "Deconvolution": (("data", "weight", "bias"), ()),
    "BatchNorm": (("data", "gamma", "beta"), ("moving_mean", "moving_var")),
    "BatchNorm_v1": (("data", "gamma", "beta"), ("moving_mean", "moving_var")),
    "Embedding": (("data", "weight"), ()),
    "LeakyReLU": (("data", "gamma"), ()),
    "InstanceNorm": (("data", "gamma", "beta"), ()),
    "LayerNorm": (("data", "gamma", "beta"), ()),
    "SoftmaxOutput": (("data", "label"), ()),
    "Softmax": (("data", "label"), ()),
    "LinearRegressionOutput": (("data", "label"), ()),
    "MAERegressionOutput": (("data", "label"), ()),
    "LogisticRegressionOutput": (("data", "label"), ()),
    "SVMOutput": (("data", "label"), ()),
    "softmax_cross_entropy": (("data", "label"), ()),
    "SequenceMask": (("data", "sequence_length"), ()),
    "SequenceLast": (("data", "sequence_length"), ()),
    "SequenceReverse": (("data", "sequence_length"), ()),
    "BilinearSampler": (("data", "grid"), ()),
    "SpatialTransformer": (("data", "loc"), ()),
    "GridGenerator": (("data",), ()),
    "ROIPooling": (("data", "rois"), ()),
    "dot": (("lhs", "rhs"), ()),
    "batch_dot": (("lhs", "rhs"), ()),
    "where": (("condition", "x", "y"), ()),
    "take": (("a", "indices"), ()),
    "RNN": (("data", "parameters", "state", "state_cell"), ()),
    "MultiBoxTarget": (("anchor", "label", "cls_pred"), ()),
    "MultiBoxDetection": (("cls_prob", "loc_pred", "anchor"), ()),
    "Proposal": (("cls_prob", "bbox_pred", "im_info"), ()),
    "MultiProposal": (("cls_prob", "bbox_pred", "im_info"), ()),
    "PSROIPooling": (("data", "rois"), ()),
    "DeformableConvolution": (("data", "offset", "weight", "bias"), ()),
    "CTCLoss": (("data", "label"), ()),
    "Correlation": (("data1", "data2"), ()),
    "DeformablePSROIPooling": (("data", "rois", "trans"), ()),
    "MultiHeadAttention": (("data", "in_weight", "in_bias", "out_weight",
                            "out_bias"), ()),
    "MoE": (("data", "gate_weight", "w1_weight", "w2_weight"), ()),
    "quantize": (("data", "min_range", "max_range"), ()),
    "dequantize": (("data", "min_range", "max_range"), ()),
    "count_sketch": (("data", "h", "s"), ()),
}
# contrib ops answer under both their legacy and _contrib_ names
_CONTRIB = ("MultiBoxPrior", "MultiBoxTarget", "MultiBoxDetection",
            "Proposal", "MultiProposal", "PSROIPooling",
            "DeformableConvolution", "DeformablePSROIPooling", "CTCLoss",
            "quantize", "dequantize", "count_sketch",
            "MultiHeadAttention", "MoE")
for _name in _CONTRIB:
    if _name in INPUT_NAMES:
        INPUT_NAMES["_contrib_" + _name] = INPUT_NAMES[_name]
INPUT_NAMES["ctc_loss"] = INPUT_NAMES["CTCLoss"]

_BINARY_DEFAULT = ("lhs", "rhs")


def input_names_for(op_name, num_inputs):
    """Names for an op's tensor inputs (after any rng key)."""
    if op_name in INPUT_NAMES:
        names, aux = INPUT_NAMES[op_name]
        return (names + aux)[:num_inputs] if num_inputs else names + aux
    if num_inputs == 2:
        return _BINARY_DEFAULT
    if num_inputs and num_inputs > 2:
        return tuple("arg%d" % i for i in range(num_inputs))
    return ("data",)


def aux_names_for(op_name):
    return INPUT_NAMES.get(op_name, ((), ()))[1]


def expected_inputs(op_name, attrs):
    """Full (arg_names, aux_names) an op instance wants, given its attrs
    (handles optional inputs like bias under ``no_bias``)."""
    names, aux = INPUT_NAMES.get(op_name, (("data",), ()))
    names = list(names)
    if attrs.get("no_bias") and "bias" in names:
        names.remove("bias")
    if op_name == "LeakyReLU" and attrs.get("act_type", "leaky") != "prelu":
        names = ["data"]
    if op_name in ("SequenceMask", "SequenceLast", "SequenceReverse") and \
            not attrs.get("use_sequence_length"):
        names = ["data"]
    if op_name == "RNN" and attrs.get("mode", "lstm") != "lstm":
        names = [n for n in names if n != "state_cell"]
    if op_name == "Custom" and "op_type" in attrs:
        from ..operator import _make_prop

        names = list(_make_prop(attrs).list_arguments())
    return tuple(names), tuple(aux)
