"""Per-operator parameter declarations — the ``dmlc::Parameter`` analogue.

In the reference every operator declares a parameter struct
(``DMLC_DECLARE_PARAMETER``, 75 files) giving each attribute a type, a
default, and a description; the registry renders those into the
docstrings of every generated frontend function and validates kwargs at
call time.  This module is the same single source of truth for the TPU
registry: ``ops/__init__`` attaches each spec to its ``OpDef``, the
``nd``/``sym`` frontends render the table into ``__doc__``, and — with
``MXNET_STRICT_OP_PARAMS=1`` — unknown attribute names raise instead of
being silently ignored.

Each spec is ``(name, type, default, description)``; ``default=REQUIRED``
marks a mandatory attribute.
"""
from __future__ import annotations

REQUIRED = "__required__"

# op name -> [(param, type, default, description), ...]
PARAM_SPECS = {
    "FullyConnected": [
        ("num_hidden", "int", REQUIRED, "Number of output units."),
        ("no_bias", "bool", False, "Disable the bias term."),
        ("flatten", "bool", True,
         "Flatten trailing input dims into one feature axis."),
    ],
    "Convolution": [
        ("kernel", "tuple of int", REQUIRED, "Spatial kernel size."),
        ("num_filter", "int", REQUIRED, "Number of output channels."),
        ("stride", "tuple of int", None, "Spatial stride (default 1s)."),
        ("pad", "tuple of int", None, "Zero padding (default 0s)."),
        ("dilate", "tuple of int", None, "Dilation (default 1s)."),
        ("num_group", "int", 1, "Grouped-convolution group count."),
        ("no_bias", "bool", False, "Disable the bias term."),
        ("layout", "str", "NCHW",
         "Input layout: NCHW/NHWC (NCW/NWC, NCDHW/NDHWC by rank)."),
        ("cudnn_tune", "str", None,
         "Accepted for reference parity; XLA owns algorithm choice."),
        ("workspace", "int", None,
         "Accepted for reference parity; XLA owns scratch memory."),
    ],
    "Deconvolution": [
        ("kernel", "tuple of int", REQUIRED, "Spatial kernel size."),
        ("num_filter", "int", REQUIRED, "Number of output channels."),
        ("stride", "tuple of int", None, "Upsampling stride."),
        ("pad", "tuple of int", None, "Padding removed from the output."),
        ("adj", "tuple of int", None, "Output-size adjustment."),
        ("target_shape", "tuple of int", None,
         "Explicit output spatial shape (overrides adj)."),
        ("num_group", "int", 1, "Group count."),
        ("no_bias", "bool", True, "Disable the bias term."),
    ],
    "Pooling": [
        ("kernel", "tuple of int", REQUIRED, "Pooling window."),
        ("pool_type", "str", "max", "max | avg | sum."),
        ("stride", "tuple of int", None, "Stride (default 1s)."),
        ("pad", "tuple of int", None, "Padding (default 0s)."),
        ("global_pool", "bool", False, "Pool over the whole spatial extent."),
        ("pooling_convention", "str", "valid",
         "Output-shape rounding: valid | full."),
        ("layout", "str", "NCHW", "Input layout."),
    ],
    "BatchNorm": [
        ("eps", "float", 1e-3, "Variance epsilon."),
        ("momentum", "float", 0.9, "Moving-average momentum."),
        ("fix_gamma", "bool", True, "Freeze gamma at 1."),
        ("use_global_stats", "bool", False,
         "Normalize with moving stats even in training."),
        ("output_mean_var", "bool", False, "Also output batch mean/var."),
        ("axis", "int", 1, "Channel axis."),
    ],
    "Activation": [
        ("act_type", "str", REQUIRED,
         "relu | sigmoid | tanh | softrelu | softsign | gelu."),
    ],
    "LeakyReLU": [
        ("act_type", "str", "leaky", "leaky | prelu | elu | rrelu."),
        ("slope", "float", 0.25, "Negative-region slope (leaky/elu)."),
        ("lower_bound", "float", 0.125, "rrelu slope lower bound."),
        ("upper_bound", "float", 0.334, "rrelu slope upper bound."),
    ],
    "Dropout": [
        ("p", "float", 0.5, "Drop probability."),
        ("mode", "str", "training",
         "training: scale at train time only; always: also at inference."),
    ],
    "SoftmaxOutput": [
        ("grad_scale", "float", 1.0, "Scale applied to the gradient."),
        ("ignore_label", "float", -1.0,
         "Label value excluded from gradient when use_ignore is set."),
        ("use_ignore", "bool", False, "Enable ignore_label."),
        ("multi_output", "bool", False,
         "Softmax over axis 1 with trailing spatial axes."),
        ("preserve_shape", "bool", False, "Softmax over the last axis."),
        ("normalization", "str", "null",
         "Gradient normalization: null | batch | valid."),
        ("out_grad", "bool", False, "Accept an incoming head gradient."),
        ("smooth_alpha", "float", 0.0, "Label smoothing."),
    ],
    "Embedding": [
        ("input_dim", "int", REQUIRED, "Vocabulary size."),
        ("output_dim", "int", REQUIRED, "Embedding width."),
        ("dtype", "str", "float32", "Weight dtype."),
    ],
    "RNN": [
        ("state_size", "int", REQUIRED, "Hidden state width."),
        ("num_layers", "int", REQUIRED, "Stacked layer count."),
        ("mode", "str", REQUIRED, "rnn_relu | rnn_tanh | lstm | gru."),
        ("bidirectional", "bool", False, "Bidirectional stacking."),
        ("state_outputs", "bool", False, "Also output final states."),
        ("p", "float", 0.0, "Inter-layer dropout."),
    ],
}
PARAM_SPECS.update({
    "Reshape": [
        ("shape", "tuple of int", REQUIRED,
         "Target shape; 0 copies, -1 infers, -2/-3/-4 reference "
         "split/merge codes."),
        ("reverse", "bool", False, "Match shape right-to-left."),
    ],
    "slice": [
        ("begin", "tuple of int", REQUIRED, "Start per axis."),
        ("end", "tuple of int", REQUIRED, "End per axis (None = to end)."),
        ("step", "tuple of int", None, "Step per axis."),
    ],
    "Cast": [("dtype", "str", REQUIRED, "Target dtype.")],
    "clip": [
        ("a_min", "float", REQUIRED, "Lower bound."),
        ("a_max", "float", REQUIRED, "Upper bound."),
    ],
    "Concat": [
        ("dim", "int", 1, "Concatenation axis."),
        ("num_args", "int", None, "Accepted for reference parity."),
    ],
    "SliceChannel": [
        ("num_outputs", "int", REQUIRED, "Number of splits."),
        ("axis", "int", 1, "Split axis."),
        ("squeeze_axis", "bool", False, "Drop the split axis when size 1."),
    ],
    "dot": [
        ("transpose_a", "bool", False, "Transpose the first input."),
        ("transpose_b", "bool", False, "Transpose the second input."),
    ],
    "MultiHeadAttention": [
        ("num_heads", "int", REQUIRED, "Attention head count."),
        ("causal", "bool", True, "Apply the causal (autoregressive) mask."),
        ("seq_parallel", "bool", False,
         "Ring attention over the active mesh's 'seq' axis "
         "(long-context: shard T over chips, rotate K/V on ICI)."),
    ],
    "MoE": [
        ("num_experts", "int", REQUIRED, "Expert count E."),
        ("top_k", "int", 2, "Experts routed per token."),
        ("hidden_size", "int", None,
         "Expert FFN hidden width H (default 4*D; used for parameter "
         "shape inference)."),
        ("capacity_factor", "float", 1.25,
         "Per-expert buffer = ceil(cf * top_k * tokens / E); overflow "
         "tokens are dropped from that expert."),
        ("expert_parallel", "bool", False,
         "Shard tokens + experts over the active mesh's 'expert' axis; "
         "dispatch/return ride all_to_all on ICI."),
    ],
    "LayerNorm": [
        ("eps", "float", 1e-5, "Variance epsilon."),
        ("axis", "int", -1, "Normalized axis."),
    ],
    "topk": [
        ("k", "int", 1, "Number of elements."),
        ("axis", "int", -1, "Axis to rank along."),
        ("ret_typ", "str", "indices", "value | indices | mask | both."),
        ("is_ascend", "bool", False, "Rank ascending."),
        ("dtype", "str", "float32", "Index output dtype."),
    ],
})


def attach_specs(registry_get):
    """Attach each spec list to its OpDef (and its aliases share the
    OpDef, so they share the spec)."""
    for name, spec in PARAM_SPECS.items():
        try:
            registry_get(name).param_specs = spec
        except Exception:  # pragma: no cover - spec for unregistered op
            raise
