"""Fused optimizer-update operators.

Reference: ``src/operator/optimizer_op.cc`` (sgd_update, sgd_mom_update,
mp_sgd_*, adam_update, rmsprop_update, rmspropalex_update, ftrl_update).
In the reference these run through the engine like any op; here they are
pure functions the compiled train step folds into one XLA program (the
reference's aspiration — "on TPU these fold into the compiled train step",
SURVEY.md Appendix A).

Each returns the updated weight (and updated state tensors) — the invoke
layer rebinds the NDArrays, and ``Optimizer.update`` / the fused Module
train step call these directly.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep_grad(grad, attrs):
    g = grad * float(attrs.get("rescale_grad", 1.0))
    clip = float(attrs.get("clip_gradient", -1.0))
    if clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


@register("sgd_update")
def _sgd_update(attrs, weight, grad):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    g = _prep_grad(grad, attrs)
    return weight - lr * (g + wd * weight)


@register("sgd_mom_update", mutable_inputs=(2,))
def _sgd_mom_update(attrs, weight, grad, mom):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad, attrs)
    new_mom = momentum * mom - lr * (g + wd * weight)
    return weight + new_mom, new_mom


@register("mp_sgd_update", mutable_inputs=(2,))
def _mp_sgd_update(attrs, weight, grad, weight32):
    """fp16 weights with fp32 master copy (reference mp_sgd_update)."""
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    g = _prep_grad(grad.astype(jnp.float32), attrs)
    new_w32 = weight32 - lr * (g + wd * weight32)
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", mutable_inputs=(2, 3))
def _mp_sgd_mom_update(attrs, weight, grad, mom, weight32):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    momentum = float(attrs.get("momentum", 0.0))
    g = _prep_grad(grad.astype(jnp.float32), attrs)
    new_mom = momentum * mom - lr * (g + wd * weight32)
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", mutable_inputs=(2, 3))
def _adam_update(attrs, weight, grad, mean, var):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, attrs) + wd * weight
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + eps)
    return new_w, new_mean, new_var


@register("rmsprop_update", mutable_inputs=(2,))
def _rmsprop_update(attrs, weight, grad, n):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    gamma1 = float(attrs.get("gamma1", 0.95))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, attrs) + wd * weight
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + eps)
    return new_w, new_n


@register("rmspropalex_update", mutable_inputs=(2, 3, 4))
def _rmspropalex_update(attrs, weight, grad, n, g_state, delta):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    gamma1 = float(attrs.get("gamma1", 0.95))
    gamma2 = float(attrs.get("gamma2", 0.9))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(grad, attrs) + wd * weight
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1 - gamma1) * g + gamma1 * g_state
    new_delta = (gamma2 * delta -
                 lr * g / jnp.sqrt(new_n - jnp.square(new_g) + eps))
    return weight + new_delta, new_n, new_g, new_delta


@register("ftrl_update", mutable_inputs=(2, 3))
def _ftrl_update(attrs, weight, grad, z, n):
    lr = float(attrs["lr"])
    wd = float(attrs.get("wd", 0.0))
    lamda1 = float(attrs.get("lamda1", 0.01))
    beta = float(attrs.get("beta", 1.0))
    g = _prep_grad(grad, attrs)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        jnp.zeros_like(weight),
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n
