"""Pallas TPU kernels for BatchNorm's channel reductions.

Profiling the ResNet-50 fused step (bench.py, TPU v5e) shows the BN stat
and BN-backward reductions — `convert_reduce`/`multiply_reduce` fusions —
eating ~45% of device step time at ~175-260 GB/s, far under HBM peak,
while the convs themselves run at ~75% MFU.  These kernels stream the
activation once per pass and accumulate per-channel sums in fp32.

Forward needs (Σx, Σx²); backward needs (Σdy, Σdy·x̂) — both are one
read-only pass over activation-sized data with a (C,) result, the
memory-streaming shape Pallas is for (reference for the BN gradient
algebra: ``src/operator/batch_norm-inl.h`` in the reference repo).

Blocks are (NB, C, HW) slices of the NCHW tensor viewed as (N, C, H·W):
HW rides the 128-lane dimension, so the path is gated to HW ≥ 128 (late
ResNet stages with 7×7 maps would pad lanes 2.6× and are cheap to reduce
anyway) and C a multiple of the bf16 sublane tile.  Used by
``nn_ops._bn_train``; everything else falls back to the jnp formulation.
Set ``MXNET_BN_PALLAS=0`` to disable.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["bn_stats", "bn_grad_sums", "pallas_bn_enabled"]

_LANE = 128
# bytes/element resident in VMEM per input stream: bf16 block + one fp32
# temp + headroom for the compiler's double buffering
_VMEM_BYTES_PER_ELEM = 10
_VMEM_BUDGET = 8 << 20


def _hw_pad(hw):
    return -(-hw // _LANE) * _LANE


def pallas_bn_enabled(data, streams=1):
    from ..base import get_env

    # Off by default: measured end-to-end on ResNet-50/v5e these kernels
    # LOSE to XLA's reduce fusions (~140 vs ~260 GB/s) — with HW on the
    # lane dimension the cross-lane reduction is VPU-compute-bound, and
    # the pallas_call boundary also blocks producer fusion.  Kept as the
    # custom-kernel facility + a working example; the NHWC layout path
    # (C on lanes) is the layout under which streaming BN kernels win.
    if not get_env("MXNET_BN_PALLAS", False, bool):
        return False
    if data.ndim != 4 or data.dtype != jnp.bfloat16:
        # bf16 only: it is the case the kernel was justified for, and the
        # fp32 jnp path keeps the stable two-pass variance these kernels'
        # E[x^2]-E[x]^2 form would lose
        return False
    n, c, h, w = data.shape
    hw = h * w
    if hw < _LANE or c < 32 or c % 16 != 0:
        return False
    # one batch row must fit the budget even at NB=1
    if streams * c * _hw_pad(hw) * _VMEM_BYTES_PER_ELEM > _VMEM_BUDGET:
        return False
    return jax.default_backend() == "tpu"


def _pick_nb(n, c, hw, streams=1):
    """Rows per grid step: biggest power-of-two divisor of n keeping the
    (padded) resident block set under the VMEM budget."""
    per_row = streams * c * _hw_pad(hw) * _VMEM_BYTES_PER_ELEM
    nb = 1
    while nb * 2 <= n and n % (nb * 2) == 0 and \
            nb * 2 * per_row <= _VMEM_BUDGET:
        nb *= 2
    return nb


@functools.partial(jax.jit, static_argnames=("interpret",))
def bn_stats(x4d, interpret=False):
    """Per-channel (Σx, Σx²) over (N, H, W) of an NCHW tensor, fp32
    accumulation.  Returns two (C,) fp32 arrays."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c, h, w = x4d.shape
    hw = h * w
    x = x4d.reshape(n, c, hw)
    nb = _pick_nb(n, c, hw)

    def kernel(x_ref, s1_ref, s2_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        xf = x_ref[...].astype(jnp.float32)  # (NB, C, HW)
        s1_ref[0, :] += jnp.sum(xf, axis=(0, 2))
        s2_ref[0, :] += jnp.sum(xf * xf, axis=(0, 2))

    s1, s2 = pl.pallas_call(
        kernel,
        grid=(n // nb,),
        in_specs=[pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, c), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        interpret=interpret,
    )(x)
    return s1[0], s2[0]


@functools.partial(jax.jit, static_argnames=("interpret",))
def bn_grad_sums(dy4d, x4d, mean, inv, interpret=False):
    """Per-channel (Σdy, Σdy·x̂) with x̂ = (x−mean)·inv computed inline,
    fp32 accumulation.  Returns two (C,) fp32 arrays.

    These two sums are sufficient for the whole BN backward:
    dβ = Σdy, dγ = Σdy·x̂, and dx = γ·inv·(dy − E[dy] − x̂·E[dy·x̂])
    (the γ factor folds in per-channel outside the kernel).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, c, h, w = x4d.shape
    hw = h * w
    x = x4d.reshape(n, c, hw)
    dy = dy4d.reshape(n, c, hw)
    nb = _pick_nb(n, c, hw, streams=2)
    mean2d = mean.reshape(1, c).astype(jnp.float32)
    inv2d = inv.reshape(1, c).astype(jnp.float32)

    def kernel(dy_ref, x_ref, m_ref, i_ref, s1_ref, s2_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        m = m_ref[0, :].reshape(1, c, 1)
        iv = i_ref[0, :].reshape(1, c, 1)
        dyf = dy_ref[...].astype(jnp.float32)   # (NB, C, HW)
        xhat = (x_ref[...].astype(jnp.float32) - m) * iv
        s1_ref[0, :] += jnp.sum(dyf, axis=(0, 2))
        s2_ref[0, :] += jnp.sum(dyf * xhat, axis=(0, 2))

    s1, s2 = pl.pallas_call(
        kernel,
        grid=(n // nb,),
        in_specs=[pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((nb, c, hw), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, c), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((1, c), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, c), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        interpret=interpret,
    )(dy, x, mean2d, inv2d)
    return s1[0], s2[0]
