"""Random samplers.

Covers the reference's ``src/operator/random/sample_op.cc`` (global-param
samplers), ``multisample_op.cc`` (per-row distribution params) and
``sample_multinomial_op.cc`` (SURVEY.md Appendix A).

Instead of the per-device PRNG resource (``ResourceRequest::kRandom``,
``src/resource.cc``), every sampler is a pure function of an explicit
``jax.random`` key supplied by the invoke layer from the global seed state in
``mxnet_tpu.random`` — deterministic, replayable, and trace-safe under jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _shape_dtype(attrs, default_dtype="float32"):
    shape = tuple(attrs.get("shape", ()))
    dtype = jnp.dtype(attrs.get("dtype") or default_dtype)
    return shape, dtype


@register("random_uniform", aliases=("_sample_uniform", "uniform"), needs_rng=True)
def _uniform(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.uniform(rng, shape, dtype,
                              float(attrs.get("low", 0.0)),
                              float(attrs.get("high", 1.0)))


@register("random_normal", aliases=("_sample_normal", "normal"), needs_rng=True)
def _normal(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    return (float(attrs.get("loc", 0.0)) +
            float(attrs.get("scale", 1.0)) * jax.random.normal(rng, shape, dtype))


@register("random_gamma", aliases=("_sample_gamma",), needs_rng=True)
def _gamma(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    return (jax.random.gamma(rng, float(attrs.get("alpha", 1.0)), shape, dtype)
            * float(attrs.get("beta", 1.0)))


@register("random_exponential", aliases=("_sample_exponential",), needs_rng=True)
def _exponential(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.exponential(rng, shape, dtype) / float(attrs.get("lam", 1.0))


@register("random_poisson", aliases=("_sample_poisson",), needs_rng=True)
def _poisson(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    return jax.random.poisson(rng, float(attrs.get("lam", 1.0)), shape).astype(dtype)


@register("random_negative_binomial", aliases=("_sample_negbinomial",), needs_rng=True)
def _neg_binomial(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    k = float(attrs.get("k", 1))
    p = float(attrs.get("p", 1.0))
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(dtype)


@register("random_generalized_negative_binomial",
          aliases=("_sample_gennegbinomial",), needs_rng=True)
def _gen_neg_binomial(attrs, rng):
    shape, dtype = _shape_dtype(attrs)
    mu = float(attrs.get("mu", 1.0))
    alpha = float(attrs.get("alpha", 1.0))
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    lam = jax.random.gamma(k1, r, shape) * (mu * alpha)
    return jax.random.poisson(k2, lam, shape).astype(dtype)


# --- per-row-parameter samplers (reference multisample_op.cc) --------------

def _msample(fn):
    def compute(attrs, rng, *params):
        shape = tuple(attrs.get("shape", ()))
        out_shape = params[0].shape + shape
        return fn(rng, out_shape, *params)
    return compute


def _bcast(p, out_shape):
    return p.reshape(p.shape + (1,) * (len(out_shape) - p.ndim))


register("sample_uniform", _msample(
    lambda rng, s, low, high: jax.random.uniform(rng, s) *
    (_bcast(high, s) - _bcast(low, s)) + _bcast(low, s)), needs_rng=True)
register("sample_normal", _msample(
    lambda rng, s, mu, sigma: _bcast(mu, s) +
    _bcast(sigma, s) * jax.random.normal(rng, s)), needs_rng=True)
register("sample_gamma", _msample(
    lambda rng, s, alpha, beta: jax.random.gamma(rng, _bcast(alpha, s), s) *
    _bcast(beta, s)), needs_rng=True)
register("sample_exponential", _msample(
    lambda rng, s, lam: jax.random.exponential(rng, s) / _bcast(lam, s)),
    needs_rng=True)
register("sample_poisson", _msample(
    lambda rng, s, lam: jax.random.poisson(rng, _bcast(lam, s), s).astype(jnp.float32)),
    needs_rng=True)


@register("sample_multinomial", aliases=("_sample_multinomial",), needs_rng=True)
def _multinomial(attrs, rng, data):
    shape = attrs.get("shape", ())
    n = 1
    for s in (shape if isinstance(shape, (tuple, list)) else (shape,)):
        n *= int(s) if s else 1
    get_prob = bool(attrs.get("get_prob", False))
    logits = jnp.log(jnp.maximum(data, 1e-37))
    idx = jax.random.categorical(rng, logits, axis=-1,
                                 shape=(n,) + data.shape[:-1])
    idx = jnp.moveaxis(idx, 0, -1)
    if n == 1:
        idx = idx[..., 0]
    out = idx.astype(jnp.dtype(attrs.get("dtype", "int32")))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits),
            idx[..., None] if n == 1 else idx, axis=-1)
        return out, logp.reshape(out.shape)
    return out


@register("shuffle", aliases=("_shuffle",), needs_rng=True)
def _shuffle(attrs, rng, data):
    return jax.random.permutation(rng, data, axis=0)
