"""The operator registry — single source of truth for all ops.

This is the TPU-native replacement for the reference's operator registries
(legacy ``MXNET_REGISTER_OP_PROPERTY`` in ``include/mxnet/operator.h:126`` and
nnvm ``NNVM_REGISTER_OP`` + ``FCompute`` in ``include/mxnet/op_attr_types.h:223``;
see SURVEY.md §2.1).  As in the reference, every frontend surface is *generated*
from this registry: ``mx.nd.<op>`` (imperative), ``mx.sym.<op>`` (symbolic),
and Gluon layers call through the same entries.

Design (TPU-first, not a port):

* an op's ``compute`` is a **pure JAX function** ``compute(attrs, *inputs)``
  returning a tuple of ``jax.Array``s.  There is no per-op CUDA kernel, no
  mshadow expression template, and no shape-inference function to write:
  XLA compiles the function per (shapes, dtypes, static attrs) and
  ``jax.eval_shape`` provides shape/dtype inference for the Symbol frontend.
* imperative invoke jit-compiles ``compute`` with ``attrs`` frozen as static
  arguments and caches the executable — this is the analogue of the
  per-op executable cache in the reference's ``MXImperativeInvoke`` path
  (``src/c_api/c_api_ndarray.cc:548``), except the cache is XLA's.
* gradients come from ``jax.vjp`` over the composed program rather than
  per-op ``FGradient`` node rewrites.  Ops with special gradient semantics
  (e.g. ``SoftmaxOutput``, whose backward is ``softmax - label`` regardless of
  head gradients — reference ``src/operator/softmax_output-inl.h``) use
  ``jax.custom_vjp`` inside their ``compute``.
* ops that mutate state (BatchNorm moving stats; reference ``FMutateInputs``)
  declare ``mutable_inputs``; their compute returns the updated values as
  extra outputs and the invoke layer writes them back — functional state
  threading instead of in-place mutation, which is what XLA wants.
* ops that consume randomness declare ``needs_rng``; the invoke layer passes
  a fresh ``jax.random`` key as the first input (replacing the reference's
  per-device PRNG resource, ``ResourceRequest::kRandom``, ``src/resource.cc``).
"""
from __future__ import annotations

import functools

from ..base import MXNetError

__all__ = ["OpDef", "register", "get", "list_ops", "invoke", "FrozenAttrs"]

_OP_REGISTRY = {}


def _freeze(value):
    """Make an attr value hashable for the jit cache key."""
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    return value


class FrozenAttrs(dict):
    """Hashable attr dict passed as a jit static argument."""

    def __hash__(self):
        return hash(_freeze(self))

    def __setitem__(self, key, value):  # pragma: no cover - guard
        raise TypeError("FrozenAttrs is immutable")


class OpDef:
    """One registered operator."""

    def __init__(self, name, compute, num_outputs=1, needs_rng=False,
                 mutable_inputs=(), uses_train_mode=False, aliases=(),
                 doc=None, spans_mesh=None):
        self.name = name
        self.compute = compute
        # int, or callable(attrs)->int for attr-dependent output counts
        # (e.g. SliceChannel / split, reference src/operator/slice_channel.cc)
        self.num_outputs = num_outputs
        self.needs_rng = needs_rng
        self.mutable_inputs = tuple(mutable_inputs)
        self.uses_train_mode = uses_train_mode
        self.aliases = tuple(aliases)
        self.doc = doc or (compute.__doc__ or "")
        # (name, type, default, description) rows attached from
        # ops/op_params.py — the dmlc::Parameter analogue
        self.param_specs = None
        # predicate(attrs) -> True when this op's compute contains a
        # mesh-spanning program (shard_map): imperative inputs must be
        # replicated over the active mesh, not committed to one device
        self.spans_mesh = spans_mesh

    def describe(self):
        """Render the full docstring: op doc + declared parameters +
        input names (the reference generates frontend docstrings from the
        registry the same way, ``python/mxnet/ndarray/op.py:174-209``)."""
        from .op_params import REQUIRED
        from .op_names import INPUT_NAMES

        parts = [self.doc.strip() or self.name]
        if self.name in INPUT_NAMES:
            args, aux = INPUT_NAMES[self.name]
            parts.append("Inputs:\n" + "\n".join(
                "    - %s" % a for a in args + aux))
        if self.param_specs:
            rows = []
            for pname, ptype, default, desc in self.param_specs:
                dflt = "required" if default is REQUIRED \
                    else "optional, default=%r" % (default,)
                rows.append("%s : %s (%s)\n    %s"
                            % (pname, ptype, dflt, desc))
            parts.append("Parameters\n----------\n" + "\n".join(rows))
        return "\n\n".join(parts)

    def validate_attrs(self, attrs):
        """With ``MXNET_STRICT_OP_PARAMS=1``, reject attribute names not
        declared in the op's parameter spec (reference dmlc::Parameter
        ``Init`` kwargs checking).  No-op for ops without a spec."""
        if not self.param_specs:
            return
        from ..base import get_env

        if not get_env("MXNET_STRICT_OP_PARAMS", 0, int):
            return
        known = {p[0] for p in self.param_specs}
        from .op_params import REQUIRED

        unknown = [k for k in attrs
                   if not k.startswith("__") and k not in known]
        if unknown:
            raise MXNetError(
                "%s: unknown parameter(s) %s (declared: %s)"
                % (self.name, sorted(unknown), sorted(known)))
        missing = [p[0] for p in self.param_specs
                   if p[2] is REQUIRED and p[0] not in attrs]
        if missing:
            raise MXNetError("%s: missing required parameter(s) %s"
                             % (self.name, missing))

    def count_outputs(self, attrs):
        if callable(self.num_outputs):
            return self.num_outputs(attrs)
        return self.num_outputs

    # -- executable cache --------------------------------------------------
    @functools.lru_cache(maxsize=None)
    def _jitted(self, frozen_attrs):
        import jax

        def fn(*inputs):
            out = self.compute(frozen_attrs, *inputs)
            return out if isinstance(out, tuple) else (out,)

        return jax.jit(fn)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(name, compute=None, **kwargs):
    """Register an op.  Usable as a decorator::

        @register("relu")
        def _(attrs, x):
            return jnp.maximum(x, 0)
    """
    def _do(fn):
        op = OpDef(name, fn, **kwargs)
        _OP_REGISTRY[name] = op
        for alias in op.aliases:
            _OP_REGISTRY[alias] = op
        return fn

    if compute is not None:
        return _do(compute)
    return _do


def get(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("operator %r is not registered" % (name,)) from None


def exists(name):
    return name in _OP_REGISTRY


def list_ops():
    return sorted(_OP_REGISTRY)


def invoke(op, inputs, attrs):
    """Run an op's jitted compute on raw jax arrays.

    ``inputs`` are ``jax.Array``s (rng key already prepended when the op
    declares ``needs_rng``).  Returns a tuple of arrays:
    ``(*outputs, *updated_mutable_values)``.
    """
    if not isinstance(op, OpDef):
        op = get(op)
    frozen = attrs if isinstance(attrs, FrozenAttrs) else FrozenAttrs(attrs)
    return op._jitted(frozen)(*inputs)
