"""Fused multi-layer RNN operator.

Reference: ``src/operator/rnn-inl.h`` (the legacy ``RNN`` op, cuDNN-fused
LSTM/GRU/vanilla RNN; SURVEY.md §2.1 "Operators — neural net").  The
reference hands the whole stacked, optionally bidirectional network to one
cuDNN call over a packed parameter blob.

TPU-native form: one ``lax.scan`` per (layer, direction) with the input
projection for the *entire sequence* hoisted out of the scan into a single
batched matmul — the (T·N, G·H) GEMM rides the MXU while the scan carries
only the (N, G·H) recurrent term.  Gradients fall out of ``jax.vjp``
through the scan (XLA keeps the standard scan-transpose memory plan);
there is no hand-written backward like the reference's
``RNNOp::Backward``.

Parameter packing matches the reference/cuDNN layout so checkpoints and
``rnn.unfuse()`` slicing line up: for each layer, for each direction,
``W_x`` then ``W_h`` (row-major, gate-major), then for each layer and
direction ``b_x`` then ``b_h``.  Gate order: LSTM ``i, f, g, o``; GRU
``r, z, n``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..base import MXNetError
from .registry import register

__all__ = ["rnn_param_size", "rnn_gates"]

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_gates(mode):
    try:
        return _GATES[mode]
    except KeyError:
        raise MXNetError("RNN mode must be one of %s, got %r"
                         % (sorted(_GATES), mode)) from None


def rnn_param_size(input_size, state_size, num_layers, mode,
                   bidirectional=False):
    """Total packed-parameter length (reference ``rnn-inl.h``
    ``GetRnnParamSize``)."""
    g = rnn_gates(mode)
    d = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * d
        # per direction: W_x (g*H, in) + W_h (g*H, H) + b_x + b_h
        size += d * (g * state_size * (in_sz + state_size)
                     + 2 * g * state_size)
    return size


def _unpack_params(params, input_size, state_size, num_layers, mode, d):
    """Slice the flat blob into per-(layer, direction) weight/bias arrays.

    Returns [(Wx, Wh, bx, bh), ...] ordered layer-major then direction —
    matching the packing in :func:`rnn_param_size`.
    """
    g = rnn_gates(mode)
    h = state_size
    mats, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else h * d
        for _ in range(d):
            wx = params[off:off + g * h * in_sz].reshape(g * h, in_sz)
            off += g * h * in_sz
            wh = params[off:off + g * h * h].reshape(g * h, h)
            off += g * h * h
            mats.append((wx, wh))
    for layer in range(num_layers):
        for _ in range(d):
            bx = params[off:off + g * h]
            off += g * h
            bh = params[off:off + g * h]
            off += g * h
            biases.append((bx, bh))
    return [(wx, wh, bx, bh)
            for (wx, wh), (bx, bh) in zip(mats, biases)]


def _run_direction(mode, x, wx, wh, bx, bh, h0, c0, reverse):
    """Scan one (layer, direction). x: (T, N, in). Returns (out, hT, cT)."""
    t, n = x.shape[0], x.shape[1]
    # hoist the input projection out of the scan: one (T*N, in)x(in, G*H)
    # MXU matmul instead of T small ones
    xp = (x.reshape(t * n, -1) @ wx.T + bx).reshape(t, n, -1)
    wh_t = wh.T

    if mode == "lstm":
        def step(carry, xpt):
            hidden, cell = carry
            gates = xpt + hidden @ wh_t + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * cell + jax.nn.sigmoid(i) * jnp.tanh(g)
            new_h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (new_h, c), new_h

        (h_f, c_f), out = lax.scan(step, (h0, c0), xp, reverse=reverse)
        return out, h_f, c_f
    if mode == "gru":
        def step(hidden, xpt):
            hp = hidden @ wh_t + bh
            rx, zx, nx = jnp.split(xpt, 3, axis=-1)
            rh, zh, nh = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            nval = jnp.tanh(nx + r * nh)
            new_h = (1.0 - z) * nval + z * hidden
            return new_h, new_h

        h_f, out = lax.scan(step, h0, xp, reverse=reverse)
        return out, h_f, None
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(hidden, xpt):
        new_h = act(xpt + hidden @ wh_t + bh)
        return new_h, new_h

    h_f, out = lax.scan(step, h0, xp, reverse=reverse)
    return out, h_f, None


def _rnn_num_outputs(attrs):
    if not bool(attrs.get("state_outputs", False)):
        return 1
    return 3 if attrs.get("mode", "lstm") == "lstm" else 2


@register("RNN", needs_rng=True, uses_train_mode=True,
          num_outputs=_rnn_num_outputs)
def _rnn(attrs, rng, data, parameters, *states):
    """Fused stacked RNN (reference ``src/operator/rnn-inl.h``).

    ``data``: (T, N, input_size) time-major (TNC — the legacy op's layout).
    ``parameters``: flat blob (see :func:`rnn_param_size`).
    ``states``: initial hidden state (L*D, N, H), plus cell state for LSTM.
    """
    mode = attrs.get("mode", "lstm")
    h = int(attrs["state_size"])
    layers = int(attrs.get("num_layers", 1))
    bidir = bool(attrs.get("bidirectional", False))
    p = float(attrs.get("p", 0.0))
    state_outputs = bool(attrs.get("state_outputs", False))
    is_train = bool(attrs.get("__is_train__", False))
    d = 2 if bidir else 1
    g = rnn_gates(mode)

    if data.ndim != 3:
        raise MXNetError("RNN expects (seq_len, batch, input) data, got %s"
                         % (data.shape,))
    input_size = data.shape[2]
    expect = rnn_param_size(input_size, h, layers, mode, bidir)
    if parameters.shape != (expect,):
        raise MXNetError(
            "RNN parameter blob has shape %s, expected (%d,) for "
            "input_size=%d state_size=%d num_layers=%d mode=%s bidir=%s"
            % (parameters.shape, expect, input_size, h, layers, mode, bidir))
    del g  # used only through helpers

    h0 = states[0]
    c0_all = states[1] if mode == "lstm" else None
    slots = _unpack_params(parameters, input_size, h, layers, mode, d)

    x = data
    h_finals, c_finals = [], []
    for layer in range(layers):
        if layer > 0 and p > 0 and is_train:
            rng, sub = jax.random.split(rng)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, x.shape)
            x = jnp.where(mask, x / keep, 0).astype(x.dtype)
        outs = []
        for direction in range(d):
            idx = layer * d + direction
            wx, wh, bx, bh = slots[idx]
            c0 = c0_all[idx] if c0_all is not None else None
            out, h_f, c_f = _run_direction(
                mode, x, wx, wh, bx, bh, h0[idx], c0,
                reverse=(direction == 1))
            outs.append(out)
            h_finals.append(h_f)
            if c_f is not None:
                c_finals.append(c_f)
        x = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)

    if not state_outputs:
        return x
    hT = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        return x, hT, jnp.stack(c_finals, axis=0)
    return x, hT


@register("_state_zeros")
def _state_zeros(attrs, data):
    """Zero initial RNN state shaped from a reference input's batch dim.

    Replaces the reference's ``sym.zeros(shape=(0, h))`` deferred-shape
    idiom (nnvm infers the 0): XLA needs static shapes, so the state is
    built from the symbol it will run with.  ``data`` is (N, ...) —
    output is (N, num_hidden), or (leading, N, num_hidden) when the
    ``leading`` attr is set (FusedRNNCell's stacked (L*D, N, H) states).
    """
    h = int(attrs["num_hidden"])
    lead = int(attrs.get("leading", 0))
    n = data.shape[0] if not bool(attrs.get("batch_axis1", False)) \
        else data.shape[1]
    shape = (lead, n, h) if lead > 0 else (n, h)
    return jnp.zeros(shape, data.dtype)
