"""Linear-attention / SSM scan kernels for the serving runtime.

Not in the 0.11 reference (state-space layers post-date it) — added for
the hybrid serving stacks of ``serve/model.py``: interleaving
full-attention, sliding-window, and SSM layers makes per-slot decode
state O(1) in context length ("Compiler-First State Space Duality and
Portable O(1) Autoregressive Caching", arXiv 2603.09555: a linear
attention layer *is* a diagonal SSM, so one recurrence serves both
framings).

The layer is retention-style linear attention with a fixed per-head
exponential decay (no new parameters — the q/k/v projections reuse the
block's existing ``attn_in`` weights):

    state_t = gamma_h * state_{t-1} + k_t (outer) v_t      # (D, D) per head
    y_t     = (q_t . state_t) * scale                      # (D,)

Two execution forms, one op sequence:

* chunked-scan prefill — ``lax.scan`` over the chunk's rows inside one
  executable (one dispatch per prefill chunk, no host round-trips);
* recurrent decode — the same scan with T == 1: one state update per
  emitted token, O(1) memory and compute per step.

Bit-exactness contract (the serving M-invariance analog): every state
update and readout is an elementwise multiply-add plus a fixed-order
reduction over D, independent of how many rows share the call — so a
chunked prefill over T rows, T single-row decode steps, and a W-row
speculative verify scan produce bit-identical states and outputs from
the same inputs.  All arithmetic is fp32; the state round-trips through
the cache's fp32 state pool exactly.

Padded rows (bucket tail) pass the state through untouched — a masked
update is the identity, not a rounded no-op — so chunk padding cannot
perturb the recurrence.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..base import MXNetError

__all__ = ["ssm_decay", "ssm_scan"]


def ssm_decay(num_heads):
    """Fixed per-head decay vector (fp32, shape (H,)).

    ``gamma_h = 1 - 2**-(5 + h)`` (retention-style geometric ladder):
    head 0 remembers ~32 tokens, each further head doubles the horizon.
    Deterministic in the head index — no learned parameters, so hybrid
    stacks reuse existing attention checkpoints unchanged.
    """
    if num_heads < 1:
        raise MXNetError("ssm_decay: num_heads must be >= 1, got %d"
                         % num_heads)
    h = jnp.arange(num_heads, dtype=jnp.float32)
    return 1.0 - jnp.exp2(-(5.0 + h))


def ssm_scan(q, k, v, state0, gamma, scale=None, row_valid=None,
             collect=False):
    """Scan the linear-attention recurrence over ``T`` rows.

    q/k/v: (S, T, H, D); state0: (S, H, D, D) fp32 — the state *before*
    row 0; gamma: (H,) fp32 per-head decay; row_valid: optional (S, T)
    bool — rows marked invalid (bucket padding) leave the state exactly
    unchanged and their outputs are zeroed.  Returns ``(y, state)`` with
    y (S, T, H, D) fp32 and state (S, H, D, D) the post-scan state; with
    ``collect=True`` returns ``(y, state, states)`` where states
    (T, S, H, D, D) holds the state *after* each row — the speculative
    verify step selects the snapshot at its commit point, making
    rollback an O(1) gather instead of a re-scan.
    """
    if q.ndim != 4:
        raise MXNetError("ssm_scan: expected (S, T, H, D) inputs, got %r"
                         % (q.shape,))
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    q32 = q.astype(jnp.float32) * scale
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    g = gamma.astype(jnp.float32)[:, None, None]  # (H, 1, 1)
    state0 = state0.astype(jnp.float32)

    # scan over T: move the row axis to the front
    qs = jnp.moveaxis(q32, 1, 0)  # (T, S, H, D)
    ks = jnp.moveaxis(k32, 1, 0)
    vs = jnp.moveaxis(v32, 1, 0)
    if row_valid is not None:
        rv = jnp.moveaxis(row_valid, 1, 0)  # (T, S)
    else:
        rv = None

    def body(state, xs):
        if rv is None:
            qt, kt, vt = xs
            valid = None
        else:
            qt, kt, vt, valid = xs
        new = g * state + kt[..., :, None] * vt[..., None, :]
        if valid is not None:
            new = jnp.where(valid[:, None, None, None], new, state)
        # readout: fixed-order reduction over the first state axis
        yt = jnp.sum(qt[..., :, None] * new, axis=-2)
        if valid is not None:
            yt = jnp.where(valid[:, None, None], yt, 0.0)
        out = (yt, new) if collect else yt
        return new, out

    xs = (qs, ks, vs) if rv is None else (qs, ks, vs, rv)
    state, out = lax.scan(body, state0, xs)
    if collect:
        ys, states = out
        return jnp.moveaxis(ys, 0, 1), state, states
    return jnp.moveaxis(out, 0, 1), state
