"""Optimizers.

Reference: ``python/mxnet/optimizer.py`` — an ``Optimizer`` registry plus
updaters.  The reference dispatches the hot optimizers to fused C++ ops
(sgd_update/adam_update/…); here those same registered ops are pure XLA
functions (``mxnet_tpu/ops/optimizer_ops.py``), so ``update()`` stays a
single cached executable per parameter, and the fused Module train step can
inline them into one program.

Full reference set: SGD, DCASGD, NAG, SGLD, Adam, AdaGrad, RMSProp,
AdaDelta, Ftrl, Adamax, Nadam, Test (+ ccSGD alias).  lr/wd multipliers,
param_idx2name, rescale_grad, clip_gradient, lr_scheduler all match the
reference semantics.
"""
from __future__ import annotations

import math
import pickle

from .base import MXNetError, _Registry
from .ndarray import NDArray, zeros, ones, imperative_invoke
from .ndarray import ndarray as _ndmod

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test",
           "create", "register", "get_updater", "Updater"]

_registry = _Registry("optimizer")


def register(klass):
    """Register an optimizer class by (lowercased) name (reference
    ``Optimizer.register``)."""
    _registry.register(klass.__name__.lower(), klass)
    return klass


def create(name, **kwargs):
    return _registry.get(name.lower())(**kwargs)


class Optimizer:
    """Base optimizer (reference ``python/mxnet/optimizer.py:30``)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym_attrs = sym.attr_dict() if sym is not None else {}
        self.lr_mult = {}
        self.wd_mult = {}

    create_optimizer = staticmethod(create)

    # -- state ---------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    # -- lr/wd plumbing (reference semantics incl. symbol attrs) -------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        for name, attrs in self.sym_attrs.items():
            if "__lr_mult__" in attrs:
                self.lr_mult[name] = float(attrs["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # biases/gammas/betas get no weight decay by default
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        for name, attrs in self.sym_attrs.items():
            if "__wd_mult__" in attrs:
                self.wd_mult[name] = float(attrs["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_kwargs(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD with momentum, dispatching to the fused sgd(_mom)_update ops
    (reference ``optimizer.py`` SGD + ``src/operator/optimizer_op.cc``).
    ``multi_precision`` keeps an fp32 master copy for fp16 weights."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        import numpy as np

        use_mp = self.multi_precision and weight.dtype == np.float16
        mom = zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None
        if use_mp:
            w32 = weight.astype("float32")
            return (mom, w32)
        return mom

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs()
        if isinstance(state, tuple):
            mom, w32 = state
            if mom is not None:
                imperative_invoke("mp_sgd_mom_update", [weight, grad, mom, w32],
                                  dict(lr=lr, wd=wd, momentum=self.momentum,
                                       **kw), out=weight)
            else:
                imperative_invoke("mp_sgd_update", [weight, grad, w32],
                                  dict(lr=lr, wd=wd, **kw), out=weight)
        elif state is not None:
            imperative_invoke("sgd_mom_update", [weight, grad, state],
                              dict(lr=lr, wd=wd, momentum=self.momentum,
                                   **kw), out=weight)
        else:
            imperative_invoke("sgd_update", [weight, grad],
                              dict(lr=lr, wd=wd, **kw), out=weight)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference SGLD)."""

    def update(self, index, weight, grad, state):
        from . import random as _rnd
        from .ndarray import random_normal

        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        noise = random_normal(loc=0, scale=math.sqrt(lr),
                              shape=weight.shape)
        weight += -lr / 2 * (grad + wd * weight) + noise


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = grad + self.lamda * grad * grad * (weight - prev)
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (comp + wd * weight)
            delta = mom
        else:
            delta = -lr * (comp + wd * weight)
        prev[:] = weight
        weight += delta


@register
class Adam(Optimizer):
    """Adam with the reference's bias-corrected lr and fused op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        imperative_invoke("adam_update", [weight, grad, mean, var],
                          dict(lr=lr, wd=wd, beta1=self.beta1,
                               beta2=self.beta2, epsilon=self.epsilon,
                               **self._common_kwargs()), out=weight)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / (history + self.float_stable_eps).sqrt()
                         + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp; centered=True uses Alex Graves' variant (reference)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context))
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                  **self._common_kwargs())
        if self.centered:
            n, g, delta = state
            imperative_invoke("rmspropalex_update",
                              [weight, grad, n, g, delta],
                              dict(gamma2=self.gamma2, **kw), out=weight)
        else:
            imperative_invoke("rmsprop_update", [weight, grad, state], kw,
                              out=weight)
        if self.clip_weights:
            weight._set_data(
                weight.clip(-self.clip_weights, self.clip_weights)._data)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * grad * grad)._data)
        delta = (acc_delta + self.epsilon).sqrt() / \
                (acc_g + self.epsilon).sqrt() * grad
        acc_delta._set_data(
            (self.rho * acc_delta + (1 - self.rho) * delta * delta)._data)
        weight += -delta - wd * weight


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        z, n = state
        imperative_invoke("ftrl_update", [weight, grad, z, n],
                          dict(lr=lr, wd=wd, lamda1=self.lamda1,
                               beta=self.beta, **self._common_kwargs()),
                          out=weight)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t._set_data((self.beta1 * m_t + (1. - self.beta1) * grad)._data)
        from .ndarray import elemwise_maximum

        u_t._set_data(elemwise_maximum(self.beta2 * u_t, grad.abs())._data)
        weight += -lr * m_t / u_t


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        grad_prime = grad / (1. - self.m_schedule)
        m_t._set_data((self.beta1 * m_t + (1. - self.beta1) * grad)._data)
        v_t._set_data((self.beta2 * v_t + (1. - self.beta2) * grad * grad)._data)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight += -lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon)


@register
class Test(Optimizer):
    """Trivial optimizer used by the reference test suite."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight


ccSGD = SGD
_registry.register("ccsgd", SGD)


class Updater:
    """Worker-side updater closure (reference ``get_updater`` /
    ``Updater`` — the thing a KVStore calls per key)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        self.states = pickle.loads(states)

    def get_states(self):
        return pickle.dumps(self.states)


def get_updater(optimizer):
    return Updater(optimizer)
