"""Optimizers.

Reference: ``python/mxnet/optimizer.py`` — an ``Optimizer`` registry plus
updaters.  The reference dispatches the hot optimizers to fused C++ ops
(sgd_update/adam_update/…); here those same registered ops are pure XLA
functions (``mxnet_tpu/ops/optimizer_ops.py``), so ``update()`` stays a
single cached executable per parameter, and the fused Module train step can
inline them into one program.

Full reference set: SGD, DCASGD, NAG, SGLD, Adam, AdaGrad, RMSProp,
AdaDelta, Ftrl, Adamax, Nadam, Test (+ ccSGD alias).  lr/wd multipliers,
param_idx2name, rescale_grad, clip_gradient, lr_scheduler all match the
reference semantics.
"""
from __future__ import annotations

import math
import pickle

from .base import MXNetError, _Registry
from .ndarray import NDArray, zeros, ones, imperative_invoke
from .ndarray import ndarray as _ndmod

__all__ = ["Optimizer", "SGD", "DCASGD", "NAG", "SGLD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Test",
           "create", "register", "get_updater", "Updater"]

_registry = _Registry("optimizer")


def register(klass):
    """Register an optimizer class by (lowercased) name (reference
    ``Optimizer.register``)."""
    _registry.register(klass.__name__.lower(), klass)
    return klass


def create(name, **kwargs):
    return _registry.get(name.lower())(**kwargs)


class Optimizer:
    """Base optimizer (reference ``python/mxnet/optimizer.py:30``)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, clip_global_norm=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        if clip_global_norm is not None and clip_global_norm <= 0:
            raise MXNetError("clip_global_norm must be > 0 (got %r)"
                             % (clip_global_norm,))
        # true global-norm clipping: the whole gradient vector is scaled
        # by min(1, clip/||g||) — norm taken over ALL trainable params
        # jointly (after rescale_grad, before the per-element
        # clip_gradient).  Applied by the fused step / Module.update,
        # not per-parameter `update()` calls, because the norm spans
        # parameters.
        self.clip_global_norm = clip_global_norm
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym_attrs = sym.attr_dict() if sym is not None else {}
        self.lr_mult = {}
        self.wd_mult = {}

    create_optimizer = staticmethod(create)

    # -- state ---------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    # -- lr/wd plumbing (reference semantics incl. symbol attrs) -------
    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        for name, attrs in self.sym_attrs.items():
            if "__lr_mult__" in attrs:
                self.lr_mult[name] = float(attrs["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            # biases/gammas/betas get no weight decay by default
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        for name, attrs in self.sym_attrs.items():
            if "__wd_mult__" in attrs:
                self.wd_mult[name] = float(attrs["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_kwargs(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    # -- fused (in-XLA-program) form -----------------------------------
    # The reference dispatches hot optimizers to fused engine ops
    # (src/operator/optimizer_op.cc:36,132); here every optimizer exposes
    # a *pure* update so the whole step — forward, backward, allreduce,
    # update — compiles into one XLA program (mxnet_tpu/fused.py).
    #
    # ``init_fused_state(weight)`` returns a pytree of raw jax arrays
    # mirroring ``create_state``'s structure; ``fused_update`` maps
    # (weight, grad, state, lr, wd, t, rng) -> (new_weight, new_state)
    # where grad is the raw (pre-rescale) gradient, lr/wd already carry
    # the per-parameter multipliers, and ``t`` is the traced update count
    # (for bias correction), starting at 1 on the first call.

    def init_fused_state(self, weight):
        raise NotImplementedError(
            "%s has no fused form; Module falls back to the split "
            "update path" % type(self).__name__)

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        raise NotImplementedError

    @property
    def supports_fused(self):
        return type(self).fused_update is not Optimizer.fused_update

    def _fused_prep(self, grad):
        import jax.numpy as jnp

        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

    def fused_state_to_nd(self, fused, ctx):
        """Convert a fused state pytree back to the ``create_state``
        NDArray structure (for optimizer-state checkpoints)."""
        return _tree_jax_to_nd(fused, ctx)

    def fused_state_from_nd(self, state):
        """Convert a ``create_state``-structured state (NDArrays) to the
        fused raw-jax pytree."""
        return _tree_nd_to_jax(state)


def global_grad_norm(grads, rescale_grad=1.0):
    """Global L2 norm over a list/dict of raw-jax gradients, as the
    optimizer will see them (i.e. scaled by ``rescale_grad``).  Pure and
    traceable — the fused step inlines it; ``Module.update`` calls it on
    the split path."""
    import jax.numpy as jnp

    leaves = list(grads.values()) if isinstance(grads, dict) else list(grads)
    sq = jnp.asarray(0.0, "float32")
    for g in leaves:
        sq = sq + jnp.sum(jnp.square(g.astype("float32")))
    return jnp.sqrt(sq) * abs(rescale_grad)


def global_norm_scale(norm, max_norm, dtype="float32"):
    """Traceable min(1, max_norm/||g||) clip factor (eps-guarded)."""
    import jax.numpy as jnp

    return jnp.minimum(1.0, max_norm / (norm + 1e-12)).astype(dtype)


def sharded_fused_update(optimizer, weight, flat_grad, state, lr, wd, t,
                         rng, mesh, axis, entry):
    """ZeRO sharded-update driver for one parameter (arXiv 2004.13336).

    ``flat_grad`` is the already reduce-scattered gradient: flat
    ``(entry.padded,)``, tiled ``P(axis)`` over the data axis.  The full
    ``weight`` is sliced down to the matching flat tile, the optimizer's
    ``fused_update`` runs on 1/N of the elements (its state lives only
    on that tile), and the fresh parameter is all-gathered back to the
    replicated weight shape.  Under GSPMD all three moves are sharding
    constraints, so XLA's latency-hiding scheduler can overlap the
    gather with the next forward.  Padding lanes carry zeros in and are
    dropped at the gather, so the elementwise math is bit-identical to
    the replicated update."""
    import jax

    from .parallel import zero as _zero

    wflat = _zero.shard_flat(weight, entry, mesh, axis)
    new_flat, new_state = optimizer.fused_update(
        wflat, flat_grad, state, lr, wd, t, rng)
    new_state = jax.tree.map(
        jax.lax.with_sharding_constraint, new_state,
        _zero.state_sharding(new_state, entry, mesh, axis))
    return _zero.gather_param(new_flat, entry, mesh), new_state


def sharded_fused_update_at_rest(optimizer, flat_weight, flat_grad, state,
                                 lr, wd, t, rng, mesh, axis, entry):
    """ZeRO-3 sharded-update driver for one parameter.

    Like :func:`sharded_fused_update` but the weight is ALREADY the flat
    ``(entry.padded,)`` at-rest tile and stays that way: no slice going
    in, no trailing all-gather coming out — the next step's forward
    gathers on demand.  Same elementwise tile math, so bit-identical to
    both the replicated and the stage-1 update."""
    import jax

    from .parallel import zero as _zero

    shard = _zero.flat_sharding(mesh, axis, entry)
    wflat = jax.lax.with_sharding_constraint(flat_weight, shard)
    new_flat, new_state = optimizer.fused_update(
        wflat, flat_grad, state, lr, wd, t, rng)
    new_state = jax.tree.map(
        jax.lax.with_sharding_constraint, new_state,
        _zero.state_sharding(new_state, entry, mesh, axis))
    return jax.lax.with_sharding_constraint(new_flat, shard), new_state


def _tree_jax_to_nd(x, ctx):
    if x is None:
        return None
    if isinstance(x, tuple):
        return tuple(_tree_jax_to_nd(e, ctx) for e in x)
    return NDArray(x, ctx)


def _tree_nd_to_jax(x):
    if x is None:
        return None
    if isinstance(x, tuple):
        return tuple(_tree_nd_to_jax(e) for e in x)
    return x._data if isinstance(x, NDArray) else x


@register
class SGD(Optimizer):
    """SGD with momentum, dispatching to the fused sgd(_mom)_update ops
    (reference ``optimizer.py`` SGD + ``src/operator/optimizer_op.cc``).
    ``multi_precision`` keeps an fp32 master copy for fp16 weights."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        import jax.numpy as jnp
        import numpy as np

        # half types: fp16 (reference) and bf16 (the TPU-native half)
        use_mp = self.multi_precision and weight.dtype in (
            np.float16, jnp.bfloat16)
        mom = zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None
        if use_mp:
            w32 = weight.astype("float32")
            return (mom, w32)
        return mom

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = self._common_kwargs()
        from .ndarray.sparse import RowSparseNDArray
        from .ndarray import sparse as _sp

        if isinstance(grad, RowSparseNDArray):
            # lazy update: only rows present in the gradient are touched
            # (reference sparse-aware sgd, src/operator/optimizer_op.cc)
            if isinstance(state, tuple):
                raise MXNetError("multi-precision sparse sgd unsupported")
            if state is not None:
                _sp.sgd_mom_update(weight, grad, state, lr=lr, wd=wd,
                                   momentum=self.momentum,
                                   rescale_grad=self.rescale_grad,
                                   clip_gradient=self.clip_gradient)
            else:
                _sp.sgd_update(weight, grad, lr=lr, wd=wd,
                               rescale_grad=self.rescale_grad,
                               clip_gradient=self.clip_gradient)
            return
        if isinstance(state, tuple):
            mom, w32 = state
            if mom is not None:
                imperative_invoke("mp_sgd_mom_update", [weight, grad, mom, w32],
                                  dict(lr=lr, wd=wd, momentum=self.momentum,
                                       **kw), out=weight)
            else:
                imperative_invoke("mp_sgd_update", [weight, grad, w32],
                                  dict(lr=lr, wd=wd, **kw), out=weight)
        elif state is not None:
            imperative_invoke("sgd_mom_update", [weight, grad, state],
                              dict(lr=lr, wd=wd, momentum=self.momentum,
                                   **kw), out=weight)
        else:
            imperative_invoke("sgd_update", [weight, grad],
                              dict(lr=lr, wd=wd, **kw), out=weight)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        if self.multi_precision and weight.dtype in (jnp.float16,
                                                     jnp.bfloat16):
            mom = jnp.zeros(weight.shape, "float32") \
                if self.momentum != 0.0 else None
            return (mom, weight.astype("float32"))
        return jnp.zeros_like(weight) if self.momentum != 0.0 else None

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        g = self._fused_prep(grad)
        if isinstance(state, tuple):  # multi-precision master weights
            mom, w32 = state
            g = g.astype("float32")
            if mom is not None:
                new_mom = self.momentum * mom - lr * (g + wd * w32)
                new_w32 = w32 + new_mom
                return new_w32.astype(weight.dtype), (new_mom, new_w32)
            new_w32 = w32 - lr * (g + wd * w32)
            return new_w32.astype(weight.dtype), (None, new_w32)
        if state is not None:
            new_mom = self.momentum * state - lr * (g + wd * weight)
            return weight + new_mom, new_mom
        return weight - lr * (g + wd * weight), None


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference NAG)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            grad += wd * weight
            mom += grad
            grad += self.momentum * mom
            weight += -lr * grad
        else:
            weight += -lr * (grad + wd * weight)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return jnp.zeros_like(weight) if self.momentum != 0.0 else None

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        g = self._fused_prep(grad)
        if state is None:
            return weight - lr * (g + wd * weight), None
        g = g + wd * weight
        new_mom = self.momentum * state + g
        return weight - lr * (g + self.momentum * new_mom), new_mom


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference SGLD)."""

    def update(self, index, weight, grad, state):
        from . import random as _rnd
        from .ndarray import random_normal

        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        noise = random_normal(loc=0, scale=math.sqrt(lr),
                              shape=weight.shape)
        weight += -lr / 2 * (grad + wd * weight) + noise

    def init_fused_state(self, weight):
        return None

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax

        g = self._fused_prep(grad)
        noise = jax.numpy.sqrt(lr) * jax.random.normal(
            rng, weight.shape, weight.dtype)
        return weight - lr / 2 * (g + wd * weight) + noise, None


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = zeros(weight.shape, weight.context) \
            if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        mom, prev = state
        comp = grad + self.lamda * grad * grad * (weight - prev)
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (comp + wd * weight)
            delta = mom
        else:
            delta = -lr * (comp + wd * weight)
        prev[:] = weight
        weight += delta

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        mom = jnp.zeros_like(weight) if self.momentum != 0.0 else None
        # device copy: the state must not alias the (donated) weight buffer
        return (mom, jnp.copy(weight))

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        g = self._fused_prep(grad)
        mom, prev = state
        comp = g + self.lamda * g * g * (weight - prev)
        if mom is not None:
            new_mom = self.momentum * mom - lr * (comp + wd * weight)
            return weight + new_mom, (new_mom, weight)
        return weight - lr * (comp + wd * weight), (None, weight)


@register
class Adam(Optimizer):
    """Adam with the reference's bias-corrected lr and fused op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        from .ndarray.sparse import RowSparseNDArray
        from .ndarray import sparse as _sp

        if isinstance(grad, RowSparseNDArray):
            _sp.adam_update(weight, grad, mean, var, lr=lr, wd=wd,
                            beta1=self.beta1, beta2=self.beta2,
                            epsilon=self.epsilon,
                            rescale_grad=self.rescale_grad,
                            clip_gradient=self.clip_gradient)
            return
        imperative_invoke("adam_update", [weight, grad, mean, var],
                          dict(lr=lr, wd=wd, beta1=self.beta1,
                               beta2=self.beta2, epsilon=self.epsilon,
                               **self._common_kwargs()), out=weight)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return (jnp.zeros_like(weight), jnp.zeros_like(weight))

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        tf = t.astype("float32") if hasattr(t, "astype") else float(t)
        coef1 = 1.0 - jnp.power(self.beta1, tf)
        coef2 = 1.0 - jnp.power(self.beta2, tf)
        lr = lr * jnp.sqrt(coef2) / coef1
        g = self._fused_prep(grad) + wd * weight
        mean, var = state
        new_mean = self.beta1 * mean + (1 - self.beta1) * g
        new_var = self.beta2 * var + (1 - self.beta2) * jnp.square(g)
        new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + self.epsilon)
        return new_w, (new_mean, new_var)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        weight += -lr * (grad / (history + self.float_stable_eps).sqrt()
                         + wd * weight)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return jnp.zeros_like(weight)

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        g = self._fused_prep(grad)
        new_hist = state + g * g
        new_w = weight - lr * (
            g / jnp.sqrt(new_hist + self.float_stable_eps) + wd * weight)
        return new_w, new_hist


@register
class RMSProp(Optimizer):
    """RMSProp; centered=True uses Alex Graves' variant (reference)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context),
                    zeros(weight.shape, weight.context))
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        kw = dict(lr=lr, wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                  **self._common_kwargs())
        if self.centered:
            n, g, delta = state
            imperative_invoke("rmspropalex_update",
                              [weight, grad, n, g, delta],
                              dict(gamma2=self.gamma2, **kw), out=weight)
        else:
            imperative_invoke("rmsprop_update", [weight, grad, state], kw,
                              out=weight)
        if self.clip_weights:
            weight._set_data(
                weight.clip(-self.clip_weights, self.clip_weights)._data)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        if self.centered:
            return (jnp.zeros_like(weight), jnp.zeros_like(weight),
                    jnp.zeros_like(weight))
        return jnp.zeros_like(weight)

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        g = self._fused_prep(grad) + wd * weight
        if self.centered:
            n, gs, delta = state
            new_n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * n
            new_g = (1 - self.gamma1) * g + self.gamma1 * gs
            new_delta = (self.gamma2 * delta - lr * g / jnp.sqrt(
                new_n - jnp.square(new_g) + self.epsilon))
            new_w = weight + new_delta
            if self.clip_weights:
                new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
            return new_w, (new_n, new_g, new_delta)
        new_n = (1 - self.gamma1) * jnp.square(g) + self.gamma1 * state
        new_w = weight - lr * g / jnp.sqrt(new_n + self.epsilon)
        if self.clip_weights:
            new_w = jnp.clip(new_w, -self.clip_weights, self.clip_weights)
        return new_w, new_n


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g._set_data((self.rho * acc_g + (1 - self.rho) * grad * grad)._data)
        delta = (acc_delta + self.epsilon).sqrt() / \
                (acc_g + self.epsilon).sqrt() * grad
        acc_delta._set_data(
            (self.rho * acc_delta + (1 - self.rho) * delta * delta)._data)
        weight += -delta - wd * weight

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return (jnp.zeros_like(weight), jnp.zeros_like(weight))

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        g = self._fused_prep(grad)
        acc_g, acc_delta = state
        new_acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        delta = (jnp.sqrt(acc_delta + self.epsilon) /
                 jnp.sqrt(new_acc_g + self.epsilon) * g)
        new_acc_delta = self.rho * acc_delta + (1 - self.rho) * delta * delta
        return weight - delta - wd * weight, (new_acc_g, new_acc_delta)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        z, n = state
        imperative_invoke("ftrl_update", [weight, grad, z, n],
                          dict(lr=lr, wd=wd, lamda1=self.lamda1,
                               beta=self.beta, **self._common_kwargs()),
                          out=weight)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return (jnp.zeros_like(weight), jnp.zeros_like(weight))

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        g = self._fused_prep(grad)
        z, n = state
        new_n = n + jnp.square(g)
        sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
        new_z = z + g - sigma * weight
        new_w = jnp.where(
            jnp.abs(new_z) <= self.lamda1,
            jnp.zeros_like(weight),
            -(new_z - jnp.sign(new_z) * self.lamda1) /
            ((self.beta + jnp.sqrt(new_n)) / lr + wd))
        return new_w, (new_z, new_n)


@register
class Adamax(Optimizer):
    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t._set_data((self.beta1 * m_t + (1. - self.beta1) * grad)._data)
        from .ndarray import elemwise_maximum

        u_t._set_data(elemwise_maximum(self.beta2 * u_t, grad.abs())._data)
        weight += -lr * m_t / u_t

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return (jnp.zeros_like(weight), jnp.zeros_like(weight))

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        tf = t.astype("float32") if hasattr(t, "astype") else float(t)
        lr = lr / (1.0 - jnp.power(self.beta1, tf))
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        new_m = self.beta1 * m_t + (1 - self.beta1) * g
        new_u = jnp.maximum(self.beta2 * u_t, jnp.abs(g))
        return weight - lr * new_m / new_u, (new_m, new_u)


@register
class Nadam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.context),
                zeros(weight.shape, weight.context))

    def update(self, index, weight, grad, state):
        lr, wd = self._get_lr(index), self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = grad.clip(-self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        if len(state) == 3:
            # state saved by the fused path carries a per-param schedule
            # as a (1,) NDArray; keep advancing it in place so a fused
            # checkpoint resumes correctly on the split path too
            m_t, v_t, sched = state
            m_schedule = float(sched.asnumpy()[0]) * momentum_t
            sched[:] = m_schedule
        else:
            m_t, v_t = state
            self.m_schedule = self.m_schedule * momentum_t
            m_schedule = self.m_schedule
        m_schedule_next = m_schedule * momentum_t_1
        grad_prime = grad / (1. - m_schedule)
        m_t._set_data((self.beta1 * m_t + (1. - self.beta1) * grad)._data)
        v_t._set_data((self.beta2 * v_t + (1. - self.beta2) * grad * grad)._data)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        weight += -lr * m_t_bar / (v_t_prime.sqrt() + self.epsilon)

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        import jax

        # (m, v) mirror create_state; the scalar m_schedule rides along in
        # the fused state (the split path keeps it on the optimizer object
        # and, like the reference, loses it across checkpoints).
        # Divergence note: the reference multiplies the SHARED m_schedule
        # once per parameter per step (update() is called per index), so
        # its trajectory depends on parameter iteration order.  The fused
        # form keeps a per-parameter schedule — the Nadam paper's actual
        # recursion — so fused and split trajectories differ slightly.
        dev = list(weight.devices())[0] if hasattr(weight, "devices") else None
        sched = jnp.asarray(1.0, "float32")
        if dev is not None:
            sched = jax.device_put(sched, dev)
        return (jnp.zeros_like(weight), jnp.zeros_like(weight), sched)

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        import jax.numpy as jnp

        tf = t.astype("float32") if hasattr(t, "astype") else float(t)
        g = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (
            1. - 0.5 * jnp.power(0.96, tf * self.schedule_decay))
        momentum_t_1 = self.beta1 * (
            1. - 0.5 * jnp.power(0.96, (tf + 1) * self.schedule_decay))
        m_t, v_t, m_schedule = state
        m_schedule = m_schedule * momentum_t
        m_schedule_next = m_schedule * momentum_t_1
        grad_prime = g / (1. - m_schedule)
        new_m = self.beta1 * m_t + (1. - self.beta1) * g
        new_v = self.beta2 * v_t + (1. - self.beta2) * g * g
        m_t_prime = new_m / (1. - m_schedule_next)
        v_t_prime = new_v / (1. - jnp.power(self.beta2, tf))
        m_t_bar = (1. - momentum_t) * grad_prime + momentum_t_1 * m_t_prime
        new_w = weight - lr * m_t_bar / (jnp.sqrt(v_t_prime) + self.epsilon)
        return new_w, (new_m, new_v, m_schedule)

    def fused_state_to_nd(self, fused, ctx):
        # Persist the on-device m_schedule too: dropping it and re-seeding
        # from self.m_schedule (which the fused path never advances) would
        # snap bias correction back to step-0 behavior after a
        # save/load round-trip.
        m, v, m_schedule = fused
        return (NDArray(m, ctx), NDArray(v, ctx),
                NDArray(m_schedule.reshape((1,)), ctx))

    def fused_state_from_nd(self, state):
        import jax.numpy as jnp

        if len(state) == 3:
            m, v, m_schedule = state
            return (m._data, v._data,
                    m_schedule._data.reshape(()).astype("float32"))
        import jax

        m, v = state  # split-path state: no per-param schedule saved
        sched = jax.device_put(jnp.asarray(self.m_schedule, "float32"),
                               list(m._data.devices())[0])
        return (m._data, v._data, sched)


@register
class Test(Optimizer):
    """Trivial optimizer used by the reference test suite."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight += grad * self.rescale_grad
        state[:] = weight

    def init_fused_state(self, weight):
        import jax.numpy as jnp

        return jnp.zeros_like(weight)

    def fused_update(self, weight, grad, state, lr, wd, t, rng):
        new_w = weight + grad * self.rescale_grad
        return new_w, new_w


ccSGD = SGD
_registry.register("ccsgd", SGD)


class Updater:
    """Worker-side updater closure (reference ``get_updater`` /
    ``Updater`` — the thing a KVStore calls per key)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        self.states = pickle.loads(states)

    def get_states(self):
        return pickle.dumps(self.states)


def get_updater(optimizer):
    return Updater(optimizer)
