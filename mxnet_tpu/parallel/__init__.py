"""Parallelism: device meshes, sharding rules, collectives, ring attention.

This package is the TPU-native replacement for everything in SURVEY.md §2.3:
the reference's data-parallel Comm trees and ps-lite push/pull become XLA
collectives over a ``jax.sharding.Mesh`` (ICI in-slice, DCN across slices),
and the reference's manual ``group2ctx`` model parallelism becomes sharding
annotations.  It also provides what the reference lacks and this framework
adds as first-class: tensor parallelism, sequence/context parallelism
(ring attention), and ZeRO-style sharded optimizer state.
"""
from . import mesh
from . import collectives
from . import sharding
from . import sequence
from . import pipeline
from . import expert
from . import overlap
from . import zero
from . import plan
from . import elastic
from .mesh import (create_mesh, current_mesh, set_mesh, mesh_scope,
                   init_distributed)
from .plan import ParallelPlan
from .elastic import ElasticCoordinator, ElasticRendezvousFailed, ScaleEvent
from .sequence import ring_attention, sequence_parallel_attention
from .pipeline import pipeline_apply, split_symbol, PipelineTrainStep
from .expert import moe_ffn, routed_moe_ffn

__all__ = ["mesh", "collectives", "sharding", "sequence", "overlap",
           "zero", "plan", "elastic", "ParallelPlan",
           "ElasticCoordinator", "ElasticRendezvousFailed", "ScaleEvent",
           "create_mesh",
           "current_mesh", "set_mesh", "mesh_scope", "init_distributed", "ring_attention",
           "sequence_parallel_attention", "pipeline", "expert",
           "pipeline_apply", "split_symbol", "PipelineTrainStep",
           "moe_ffn", "routed_moe_ffn"]
