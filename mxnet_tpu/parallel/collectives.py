"""Collectives.

Replaces the reference's two communication layers (SURVEY.md §2.3):
in-process ``Comm`` tree reduction (``src/kvstore/comm.h``) and ps-lite
push/pull RPC — with XLA collectives.  Inside a jitted program these are
``lax.psum``/``all_gather``/``ppermute`` over mesh axes; at the imperative
boundary (KVStore push outside jit) cross-*process* reduction uses the
JAX multihost utilities (DCN), and single-controller SPMD needs no
explicit action because gradients of a batch-sharded loss are already
globally reduced by the compiler.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from ..compile_cache import track_lru

__all__ = ["allreduce_nd", "psum", "all_gather", "ppermute",
           "reduce_scatter"]


# -- in-jit collectives (thin lax wrappers, for shard_map'd kernels) -------

def psum(x, axis_name):
    import jax

    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


# -- imperative-boundary allreduce (KVStore push path) ---------------------

@track_lru("parallel._stacked_sum")
@functools.lru_cache(maxsize=8)
def _stacked_sum(mesh):
    """Per-mesh cached executable summing stacked partial gradients to a
    replicated result (jit caches by function identity, so the jitted fn
    must be reused across pushes)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.jit(lambda v: v.sum(axis=0),
                   out_shardings=NamedSharding(mesh, PartitionSpec()))

def allreduce_nd(arr, mesh=None, is_partial_stack=False):
    """All-reduce an NDArray across the active reduction domain.

    Three cases, mirroring where the reference reduces gradients
    (``src/kvstore/comm.h`` tree + ps-lite push):

    1. **In-chip SPMD (single controller, mesh active)** — when the train
       step is jitted over a mesh with the batch sharded on the 'data'
       axis, XLA already inserted the ICI all-reduce inside the step and
       a pushed gradient is the *global*-batch gradient: identity.
       If the caller built a stack of per-chip partial gradients on a
       leading axis (the analogue of the reference's per-device gradient
       list), it must say so with ``is_partial_stack=True``; the stack is
       then summed on-device into a replicated result.  This is explicit
       because shape+sharding alone cannot distinguish a partial stack
       from a batch-sharded value whose dim0 happens to equal the device
       count.
    2. **Multi-process (multi-host)** — per-process values are summed
       over DCN via the multihost allgather utility.
    3. Single process, no mesh — identity.
    """
    import jax

    from ..ndarray.ndarray import NDArray

    x = arr._data
    if is_partial_stack:
        if mesh is None or x.ndim < 1 or \
                x.shape[0] != mesh.shape.get("data", 1):
            raise MXNetError(
                "is_partial_stack=True requires a mesh and a leading axis "
                "of size mesh.shape['data'] (got shape %s)" % (x.shape,))
        summed = _stacked_sum(mesh)(x)
        return NDArray(summed, arr.context)
    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(x)
    summed = gathered.sum(axis=0)
    return NDArray(jax.device_put(summed), arr.context)


def allreduce_row_sparse(rsp):
    """Sum a RowSparseNDArray across processes WITHOUT densifying.

    The reference keeps row-sparse gradients sparse on the wire
    (``kvstore_dist.h:346-385`` row-sparse push/pull); the TPU-native
    equivalent pads each process's (indices, data) to the global max nnz
    (one tiny count allgather first, sentinel row id = num_rows marks
    padding), allgathers the padded blocks over DCN, and merges with the
    sparse segment-sum — traffic is O(P * max_nnz * row_bytes) instead
    of O(P * num_rows * row_bytes).

    Single-process: identity.
    """
    import numpy as np

    import jax

    if jax.process_count() == 1:
        return rsp
    from jax.experimental import multihost_utils

    from ..ndarray.sparse import RowSparseNDArray, _merge_rsp

    num_rows = rsp.shape[0]
    # strip constructor nnz-bucket padding before the wire: shipping
    # sentinel zero-rows the receiver drops would waste the bandwidth
    # row_sparse exists to save
    nnz = rsp._public_nnz()
    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([nnz], "int32"))).reshape(-1)
    max_nnz = int(counts.max())
    if max_nnz == 0:
        return rsp
    pad = max_nnz - nnz
    idx = np.asarray(rsp._indices[:nnz], "int32")
    data = np.asarray(rsp._data[:nnz])
    if pad:
        idx = np.concatenate([idx, np.full(pad, num_rows, "int32")])
        data = np.concatenate(
            [data, np.zeros((pad,) + data.shape[1:], data.dtype)])
    g_idx = np.asarray(multihost_utils.process_allgather(idx))
    g_data = np.asarray(multihost_utils.process_allgather(data))
    g_idx = g_idx.reshape(-1, max_nnz)
    g_data = g_data.reshape((-1, max_nnz) + data.shape[1:])
    parts = []
    for p in range(g_idx.shape[0]):
        keep = g_idx[p] < num_rows  # drop sentinel padding
        if not keep.any():
            continue
        parts.append(RowSparseNDArray(
            jax.numpy.asarray(g_data[p][keep]),
            jax.numpy.asarray(g_idx[p][keep], "int32"),
            rsp.shape, rsp.context))
    if not parts:
        return rsp
    if len(parts) == 1:
        return parts[0]
    return _merge_rsp(parts)
