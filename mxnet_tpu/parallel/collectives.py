"""Collectives.

Replaces the reference's two communication layers (SURVEY.md §2.3):
in-process ``Comm`` tree reduction (``src/kvstore/comm.h``) and ps-lite
push/pull RPC — with XLA collectives.  Inside a jitted program these are
``lax.psum``/``all_gather``/``ppermute`` over mesh axes; at the imperative
boundary (KVStore push outside jit) cross-*process* reduction uses the
JAX multihost utilities (DCN), and single-controller SPMD needs no
explicit action because gradients of a batch-sharded loss are already
globally reduced by the compiler.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["allreduce_nd", "psum", "all_gather", "ppermute",
           "reduce_scatter"]


# -- in-jit collectives (thin lax wrappers, for shard_map'd kernels) -------

def psum(x, axis_name):
    import jax

    return jax.lax.psum(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    import jax

    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def ppermute(x, axis_name, perm):
    import jax

    return jax.lax.ppermute(x, axis_name, perm)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    import jax

    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension,
                                tiled=True)


# -- imperative-boundary allreduce (KVStore push path) ---------------------

def allreduce_nd(arr):
    """All-reduce an NDArray across worker processes.

    Single process (the usual SPMD single-controller case): identity —
    when the train step is jitted over a mesh with the batch sharded on
    the 'data' axis, XLA already inserted the ICI all-reduce inside the
    step; there is nothing left to reduce at the host level.

    Multi-process (multi-host without a shared jit): sums the per-process
    values over DCN via the multihost allgather utility.
    """
    import jax

    if jax.process_count() == 1:
        return arr
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(arr._data)
    summed = gathered.sum(axis=0)
    from ..ndarray.ndarray import NDArray

    return NDArray(jax.device_put(summed), arr.context)
