"""Live elasticity: in-memory plan migration without a process restart.

The fault-tolerance story so far treats every topology change as a
death: a preemption checkpoints and exits (``base_module._preempt``), a
dead peer is *named* (``health.stale_peers``) but the survivors still
tear down, and the elastic *restore* (``checkpoint._assemble`` +
``zero.unflatten_tiles``) only runs on the cold path — a fresh process
re-reading the manifest from disk.  That round trip pays process
startup, XLA recompilation and a full checkpoint read for what is, at
heart, a layout change over state the survivors already hold in host
memory.

:class:`ElasticCoordinator` closes the loop in-process with four phases,
each a registered chaos site (``testing/faults.py``):

1. **quiesce** (``elastic_quiesce``) — at a batch boundary, write the
   last-good checkpoint (the fallback anchor if any later phase dies),
   then capture params / fused optimizer states / loss-scaler + fp8
   amax history into canonical host arrays through the SAME audited
   window math the on-disk path uses (``checkpoint._host_pieces`` →
   ``checkpoint.assemble_pieces``, ``zero.export_states`` →
   ``zero.import_state``).
2. **re-form** (``elastic_rendezvous``) — wait, bounded by
   ``MXNET_ELASTIC_TIMEOUT_S``, for the new world's peers to show live
   heartbeats (PR 3's ``RankHeartbeat`` files); on timeout raise the
   typed :class:`ElasticRendezvousFailed` naming the phase and the
   dead peers (``health.peer_report`` wording) instead of hanging.
3. **reshard** (``elastic_reshard``) — ``Module.reconfigure_plan``
   rebuilds the mesh + fused step under the new
   :class:`~mxnet_tpu.parallel.ParallelPlan` (the live optimizer object
   survives, so ``num_update`` and the lr schedule continue), then the
   captured canonical state is re-installed: params via ``set_params``,
   optimizer trees via ``set_fused_optimizer_states`` (re-tiled to the
   new zero layout bit-exactly), loss-scale/fp8 history via
   ``TrainStep.load_hstate``.  No disk I/O.
4. **resume** (``elastic_resume``) — seek the data stream back to the
   quiesce boundary (``seek(epoch, nbatch)``, O(1) on the data
   service) and hand control back to the batch loop.

Scale events arrive three ways, all surfaced by :meth:`poll` at batch
boundaries: SIGUSR1, a dead peer detected via ``health.stale_peers``,
or a host-count/plan change written to the ``MXNET_ELASTIC_DIR``
manifest (``tools/launch.py --scale-event`` emits it; the JSON schema
here is the contract).  A failure mid-migration falls back to the
last-good checkpoint (``BaseModule._elastic_migrate``) — the job is
always either migrated or resumable, never wedged half-moved.

Every migration writes a ``mxnet_tpu-migration-event`` artifact
(old/new plan fingerprints, per-phase wall times, ``downtime_s``) under
``MXNET_HEALTH_DIR``; ``tools/diagnose.py`` renders it and
``bench_fit.py --migration`` A/Bs the downtime against a
checkpoint-restart.  See docs/fault_tolerance.md "Live elasticity".
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time

from ..base import MXNetError, TrainingPreempted, get_env

__all__ = ["ScaleEvent", "ElasticCoordinator", "ElasticRendezvousFailed",
           "scale_event_path", "read_scale_event", "write_scale_event",
           "maybe_coordinator"]

logger = logging.getLogger(__name__)

_PHASES = ("quiesce", "rendezvous", "reshard", "resume")


class ElasticRendezvousFailed(MXNetError):
    """The re-form phase could not assemble the new world before the
    ``MXNET_ELASTIC_TIMEOUT_S`` watchdog expired (or the heartbeat
    directory itself was unreadable).  ``phase`` names where the
    migration died, ``dead_peers`` the ranks that never showed a live
    heartbeat — launchers decide between retrying with a smaller world
    and restarting from the checkpoint the quiesce phase wrote."""

    def __init__(self, msg, phase="rendezvous", dead_peers=()):
        super().__init__(msg)
        self.phase = phase
        self.dead_peers = list(dead_peers)


class ScaleEvent:
    """One resize/re-plan request: the new world size, an optional new
    plan (spec string, ``describe()`` dict or ``ParallelPlan``), why,
    and where it came from (``'manifest'`` / ``'signal'`` /
    ``'peers'``).  ``seq`` orders manifest events so a file rewrite
    fires exactly once."""

    __slots__ = ("num_workers", "plan", "reason", "seq", "source")

    def __init__(self, num_workers, plan=None, reason="", seq=0,
                 source="manifest"):
        self.num_workers = int(num_workers)
        self.plan = plan
        self.reason = str(reason)
        self.seq = int(seq)
        self.source = source

    def resolve_plan(self):
        """The event's plan as a live :class:`ParallelPlan`, or None to
        keep the module's current plan."""
        from .plan import ParallelPlan

        p = self.plan
        if p is None:
            return None
        if isinstance(p, dict):
            return ParallelPlan.from_describe(p)
        return ParallelPlan.parse(p)

    def __repr__(self):
        return ("ScaleEvent(num_workers=%d, plan=%r, source=%r, seq=%d)"
                % (self.num_workers, self.plan, self.source, self.seq))


# -- scale-event manifest (the launch.py <-> coordinator contract) ------
def scale_event_path(directory):
    return os.path.join(directory, "scale_event.json")


def read_scale_event(directory):
    """Parse ``<dir>/scale_event.json`` into a :class:`ScaleEvent`, or
    None when absent/unparseable (writes are atomic renames, so a bad
    file is a foreign artifact, not a torn write — skip it)."""
    path = scale_event_path(directory)
    try:
        with open(path) as f:
            payload = json.load(f)
        return ScaleEvent(num_workers=int(payload["num_workers"]),
                          plan=payload.get("plan") or None,
                          reason=payload.get("reason", ""),
                          seq=int(payload.get("seq", 1)),
                          source="manifest")
    except (OSError, ValueError, KeyError, TypeError):
        return None


def write_scale_event(directory, num_workers, plan=None, reason=""):
    """Atomically publish a scale event for running coordinators to
    poll.  ``seq`` auto-increments past any prior event so rewrites
    fire exactly once; returns the published sequence number.  The same
    JSON schema is emitted stdlib-only by ``tools/launch.py
    --scale-event`` — keep the two writers in sync."""
    os.makedirs(directory, exist_ok=True)
    prior = read_scale_event(directory)
    seq = (prior.seq if prior is not None else 0) + 1
    if plan is not None and not isinstance(plan, (str, dict)):
        plan = plan.describe()  # ParallelPlan → JSON-able identity
    payload = {"seq": seq, "num_workers": int(num_workers),
               "plan": plan, "reason": str(reason)}
    path = scale_event_path(directory)
    tmp = path + ".tmp.%d" % os.getpid()
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return seq


def maybe_coordinator(elastic=None):
    """Resolve ``fit(elastic=...)``: a coordinator passes through,
    truthy builds one from the environment, None defers to
    ``MXNET_ELASTIC``."""
    if isinstance(elastic, ElasticCoordinator):
        return elastic
    if elastic is None:
        elastic = get_env("MXNET_ELASTIC", False, bool)
    return ElasticCoordinator() if elastic else None


class ElasticCoordinator:
    """The quiesce → re-form → reshard → resume control loop.

    Construction reads the launcher environment (``MXNET_WORKER_ID`` /
    ``MXNET_NUM_WORKERS`` from ``tools/launch.py``,
    ``MXNET_ELASTIC_DIR`` for the scale-event manifest,
    ``MXNET_HEARTBEAT_DIR`` for peer liveness) and latches any
    pre-existing manifest as already-seen: the coordinator reacts to
    changes after it starts, not to leftovers of the previous job.
    ``poll()`` is cheap enough for every batch boundary (throttled to
    ``poll_interval_s`` between filesystem looks; a latched SIGUSR1
    bypasses the throttle).  ``migrate()`` runs the four phases and is
    deliberately exception-transparent — the caller owns the
    fall-back-to-checkpoint decision (``BaseModule._elastic_migrate``).
    """

    def __init__(self, directory=None, heartbeat_dir=None,
                 num_workers=None, rank=None, timeout_s=None,
                 poll_interval_s=1.0, install_signal=None):
        self.directory = directory if directory is not None else \
            (get_env("MXNET_ELASTIC_DIR", "", str) or None)
        self.heartbeat_dir = heartbeat_dir if heartbeat_dir is not None \
            else (get_env("MXNET_HEARTBEAT_DIR", "", str) or None)
        self.num_workers = int(num_workers if num_workers is not None
                               else get_env("MXNET_NUM_WORKERS", 1, int))
        self.rank = int(rank if rank is not None
                        else get_env("MXNET_WORKER_ID", 0, int))
        self.timeout_s = float(timeout_s if timeout_s is not None
                               else get_env("MXNET_ELASTIC_TIMEOUT_S",
                                            60.0, float))
        self.poll_interval_s = float(poll_interval_s)
        self.events = []
        prior = read_scale_event(self.directory) if self.directory else None
        self._seen_seq = prior.seq if prior is not None else 0
        self._reported_dead = frozenset()
        self._unreadable_warned = False
        self._signal_pending = 0
        self._last_poll = float("-inf")
        self._prev_handler = None
        self._signal_installed = False
        if install_signal is None:
            install_signal = \
                threading.current_thread() is threading.main_thread()
        if install_signal and hasattr(signal, "SIGUSR1"):
            try:
                self._prev_handler = signal.signal(signal.SIGUSR1,
                                                   self._on_signal)
                self._signal_installed = True
            except (ValueError, OSError):
                pass  # not the main thread after all / embedded interp

    def _on_signal(self, signum, frame):
        self._signal_pending += 1
        prev = self._prev_handler
        if callable(prev):
            prev(signum, frame)

    def close(self):
        """Restore the SIGUSR1 handler (tests; long-lived processes that
        outlive their fit)."""
        if self._signal_installed:
            try:
                signal.signal(signal.SIGUSR1, self._prev_handler
                              or signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            self._signal_installed = False

    # -- event detection -------------------------------------------------
    def poll(self):
        """One batch-boundary look at the three event sources; returns a
        :class:`ScaleEvent` at most once per distinct event, else None.
        Manifest beats signal (SIGUSR1 usually just says "look at the
        manifest now"); an unreadable heartbeat directory is a LOCAL
        failure and never evicts peers (warned once, then quiet)."""
        now = time.monotonic()
        if not self._signal_pending and \
                now - self._last_poll < self.poll_interval_s:
            return None
        self._last_poll = now
        if self.directory:
            ev = read_scale_event(self.directory)
            if ev is not None and ev.seq > self._seen_seq:
                self._seen_seq = ev.seq
                self._signal_pending = 0
                return ev
        if self._signal_pending:
            self._signal_pending = 0
            return ScaleEvent(num_workers=self.num_workers, plan=None,
                              reason="SIGUSR1 requested a re-form",
                              seq=self._seen_seq, source="signal")
        if self.heartbeat_dir and self.num_workers > 1:
            from .. import health

            scan = health.stale_peers(self.heartbeat_dir, self.num_workers,
                                      self_rank=self.rank)
            if getattr(scan, "unreadable", False):
                if not self._unreadable_warned:
                    self._unreadable_warned = True
                    logger.warning(
                        "elastic: peer liveness unknown (%s); not "
                        "shrinking on a local failure", scan.error)
                return None
            self._unreadable_warned = False
            dead = frozenset(rank for rank, _ in scan)
            if dead and dead != self._reported_dead:
                self._reported_dead = dead
                # the surviving world is the contiguous rank prefix below
                # the first dead peer — ranks above it retire in quiesce
                return ScaleEvent(
                    num_workers=max(1, min(dead)), plan=None,
                    reason="; ".join(desc for _, desc in scan),
                    seq=self._seen_seq, source="peers")
        return None

    # -- the migration ---------------------------------------------------
    def migrate(self, module, event, epoch=0, nbatch=0, train_data=None,
                checkpoint=None):
        """Run the four-phase migration on ``module`` at the batch
        boundary ``(epoch, nbatch)``.  Returns the migration report (and
        writes it as an artifact); raises on any phase failure — the
        quiesce checkpoint written first is the caller's fallback."""
        from ..testing import faults

        t_total = time.perf_counter()
        phases = {}
        old_workers = int(self.num_workers)
        old_plan = getattr(module, "_plan", None)
        old_desc = old_plan.describe() if old_plan is not None else None
        old_fp = old_plan.fingerprint() if old_plan is not None else None
        logger.info(
            "elastic: migrating at epoch %d batch %d (%s, %d -> %d "
            "workers)%s", epoch, nbatch, event.source, self.num_workers,
            event.num_workers,
            ": %s" % event.reason if event.reason else "")

        # 1. quiesce — anchor the fallback, then capture canonically
        t = time.perf_counter()
        faults.inject("elastic_quiesce")
        if checkpoint is not None:
            checkpoint.save(module, epoch=epoch, nbatch=nbatch)
            checkpoint.flush()
        if self.rank >= event.num_workers:
            # retired by the shrink: exit through the preemption path —
            # the quiesce checkpoint above is this rank's handoff
            raise TrainingPreempted(
                "rank %d retired by elastic shrink to %d workers at "
                "epoch %d, batch %d (checkpoint written in quiesce)"
                % (self.rank, event.num_workers, epoch, nbatch),
                epoch=epoch, nbatch=nbatch)
        capture = self._capture(module)
        phases["quiesce_s"] = time.perf_counter() - t

        # 2. re-form — bounded wait for the new world's heartbeats
        t = time.perf_counter()
        faults.inject("elastic_rendezvous")
        self._rendezvous(event)
        phases["rendezvous_s"] = time.perf_counter() - t

        # 3. reshard — new mesh/fused step, then re-install the capture
        t = time.perf_counter()
        faults.inject("elastic_reshard")
        new_plan = event.resolve_plan()
        if new_plan is not None and hasattr(module, "reconfigure_plan"):
            module.reconfigure_plan(new_plan)
        else:
            new_plan = old_plan
        self._install(module, capture)
        phases["reshard_s"] = time.perf_counter() - t

        # 4. resume — seek the stream back to the quiesce boundary
        t = time.perf_counter()
        faults.inject("elastic_resume")
        if train_data is not None:
            if hasattr(train_data, "mesh"):
                # a DevicePrefetchIter stages onto the mesh it was built
                # with — repoint it BEFORE the seek restarts staging, or
                # the ring fills with old-mesh shardings the new step
                # rejects
                train_data.mesh = getattr(module, "_mesh", None)
            module._fast_forward_data(train_data, epoch, nbatch)
        self.num_workers = event.num_workers
        phases["resume_s"] = time.perf_counter() - t

        report = {
            "kind": "mxnet_tpu-migration-event",
            "outcome": "migrated",
            "rank": self.rank,
            "source": event.source,
            "reason": event.reason,
            "epoch": int(epoch),
            "nbatch": int(nbatch),
            "num_update": capture["num_update"],
            "num_workers": [old_workers, int(event.num_workers)],
        }
        report["old_plan"] = {"describe": old_desc, "fingerprint": old_fp}
        report["new_plan"] = {
            "describe": new_plan.describe() if new_plan is not None else None,
            "fingerprint": new_plan.fingerprint()
            if new_plan is not None else None}
        report["phases"] = {k: round(v, 6) for k, v in phases.items()}
        report["downtime_s"] = round(time.perf_counter() - t_total, 6)
        self.events.append(report)
        self._write_artifact(report)
        logger.info(
            "elastic: migration done in %.3fs (%s -> %s)",
            report["downtime_s"], old_fp,
            report["new_plan"]["fingerprint"])
        return report

    def record_fallback(self, event, error, epoch=0, nbatch=0):
        """Artifact trail for a failed migration the caller rolled back
        to the last-good checkpoint (``_elastic_migrate``)."""
        report = {
            "kind": "mxnet_tpu-migration-event",
            "outcome": "fallback",
            "rank": self.rank,
            "source": getattr(event, "source", "?"),
            "reason": getattr(event, "reason", ""),
            "epoch": int(epoch),
            "nbatch": int(nbatch),
            "error": "%s: %s" % (type(error).__name__, error),
        }
        self.events.append(report)
        self._write_artifact(report)
        return report

    # -- phase helpers ---------------------------------------------------
    def _rendezvous(self, event):
        """Block (bounded by ``timeout_s``) until every rank of the new
        world shows a live heartbeat.  A 1-way world, or no heartbeat
        directory configured (single-host rigs), re-forms trivially."""
        n = int(event.num_workers)
        if n <= 1 or not self.heartbeat_dir:
            return
        from .. import health

        deadline = time.monotonic() + self.timeout_s
        while True:
            scan = health.stale_peers(self.heartbeat_dir, n,
                                      self_rank=self.rank)
            if getattr(scan, "unreadable", False):
                raise ElasticRendezvousFailed(
                    "elastic migration failed in phase 'rendezvous': %s"
                    % scan.error, phase="rendezvous")
            if not scan:
                return
            if time.monotonic() >= deadline:
                raise ElasticRendezvousFailed(
                    "elastic migration failed in phase 'rendezvous': "
                    "timed out after %.1fs waiting for a %d-worker "
                    "world; dead/stale peers: %s"
                    % (self.timeout_s, n,
                       "; ".join(desc for _, desc in scan)),
                    phase="rendezvous",
                    dead_peers=[rank for rank, _ in scan])
            time.sleep(min(0.2, max(0.01, self.poll_interval_s / 5.0)))

    def _capture(self, module):
        """Canonical host-memory snapshot of everything the new plan
        must inherit, through the audited window paths: params + aux
        (``_host_pieces`` → ``assemble_pieces``, extension dtypes
        bit-preserved), fused optimizer states (``export_states`` →
        ``import_state`` for zero layouts, identity for canonical
        layouts), loss-scaler + fp8 amax history
        (``TrainStep.export_hstate``), and ``num_update``."""
        from .. import checkpoint as ckpt
        from . import zero as _zero

        def _host(x):
            # one leaf → one global host array, via the audited path
            meta, owned = ckpt._host_pieces(x, rank=0)
            merged = ckpt.assemble_pieces(
                (("leaf", idx, piece) for idx, piece in owned),
                {"leaf": meta})
            return merged.get("leaf")

        arg_nd, aux_nd = module.get_params()  # syncs zero3/pipeline
        arg = {n: _host(v) for n, v in arg_nd.items()}
        aux = {n: _host(v) for n, v in aux_nd.items()}

        states = None
        exp = module._export_zero_states() \
            if hasattr(module, "_export_zero_states") else None
        if exp is not None:
            states = {}
            for name, ent in exp.items():
                leaves = [_host(leaf) for leaf in ent["leaves"]]
                states[name] = _zero.import_state(ent, leaves)
        elif getattr(module, "_fused_states", None) is not None:
            import jax

            states = {n: jax.tree.map(_host, st)
                      for n, st in module._fused_states.items()}

        fused = getattr(module, "_fused", None)
        hstate = fused.export_hstate() \
            if fused is not None and hasattr(fused, "export_hstate") \
            else None
        opt = getattr(module, "_optimizer", None)
        return {"arg": arg, "aux": aux, "states": states, "hstate": hstate,
                "num_update": int(getattr(opt, "num_update", 0) or 0)}

    def _install(self, module, capture):
        """Re-install the capture onto the (re-planned) module: params
        through ``set_params`` (re-tiled/re-sharded by the module on the
        next step), optimizer trees through
        ``set_fused_optimizer_states``, health state through
        ``load_hstate``.  The optimizer object never changed hands, so
        ``num_update``/lr continue by construction."""
        from ..ndarray import array as nd_array

        arg = {n: nd_array(a) for n, a in capture["arg"].items()}
        aux = {n: nd_array(a) for n, a in capture["aux"].items()}
        module.set_params(arg, aux)
        if capture["states"] is not None and \
                hasattr(module, "set_fused_optimizer_states"):
            module.set_fused_optimizer_states(capture["states"])
        fused = getattr(module, "_fused", None)
        if fused is not None and capture["hstate"] is not None and \
                hasattr(fused, "load_hstate"):
            fused.load_hstate(capture["hstate"])

    # -- artifacts -------------------------------------------------------
    def _write_artifact(self, report):
        """Best-effort ``migration-<pid>-<n>.json`` under
        ``MXNET_HEALTH_DIR`` (or the tmpdir) — the trail
        ``tools/diagnose.py`` renders."""
        import tempfile

        base = get_env("MXNET_HEALTH_DIR", "", str) or \
            tempfile.gettempdir()
        path = os.path.join(base, "migration-%d-%d.json"
                            % (os.getpid(), len(self.events)))
        try:
            os.makedirs(base, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(report, f, indent=2, default=str)
            os.replace(tmp, path)
            report["artifact"] = path
        except OSError as e:
            logger.debug("elastic: artifact write failed: %s", e)
