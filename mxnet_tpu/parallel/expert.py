"""Expert parallelism — mixture-of-experts FFN over the 'expert' axis.

Nothing to port (the reference predates MoE; SURVEY.md §2.3 lists EP as
a fresh first-class design).  The layout: expert weights are sharded on
their leading EXPERT axis over the mesh's 'expert' axis, tokens stay
replicated across it; each device runs only ITS experts over all tokens,
weighting by the (replicated) gate, and one ``psum`` combines — the
dense-dispatch MoE form, which is exact for any gating (soft or top-k
masked) and keeps per-device FFN compute at ``E_local/E`` of the total.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["moe_ffn"]


def moe_ffn(x, gate_w, w1, w2, top_k=None, mesh=None, axis="expert"):
    """Mixture-of-experts feed-forward.

    ``x`` (B, D) tokens; ``gate_w`` (D, E); ``w1`` (E, D, H);
    ``w2`` (E, H, D) — w1/w2 sharded over ``axis``.  Gating is softmax
    over experts, optionally masked to the ``top_k`` largest (weights
    renormalized), and each expert runs relu(x@w1_e)@w2_e.
    Returns (B, D), replicated over the expert axis.
    """
    mesh = mesh or current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise MXNetError("moe_ffn needs a mesh with a %r axis" % axis)
    n_exp = w1.shape[0]
    if gate_w.shape[1] != n_exp:
        raise MXNetError(
            "gate_w has %d expert columns but w1 has %d experts — a "
            "mismatch would silently drop/duplicate gate mass"
            % (gate_w.shape[1], n_exp))
    if n_exp % mesh.shape[axis] != 0:
        raise MXNetError("num experts %d not divisible by %s=%d"
                         % (n_exp, axis, mesh.shape[axis]))
    return _moe_fn(mesh, axis, top_k)(x, gate_w, w1, w2)


@functools.lru_cache(maxsize=32)
def _moe_fn(mesh, axis, top_k):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_dev = mesh.shape[axis]

    def body(x, gate_w, w1, w2):
        # w1/w2: local expert slices (E_local, D, H) / (E_local, H, D)
        e_local = w1.shape[0]
        rank = lax.axis_index(axis)
        logits = x @ gate_w                       # (B, E) replicated
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)   # renormalized over mask
        # this device's gate columns
        local_probs = lax.dynamic_slice_in_dim(
            probs, rank * e_local, e_local, axis=1)  # (B, E_local)
        h = jnp.einsum("bd,edh->ebh", x, w1)
        h = jnp.maximum(h, 0.0)
        y = jnp.einsum("ebh,ehd->ebd", h, w2)     # (E_local, B, D)
        out = jnp.einsum("ebd,be->bd", y, local_probs)
        return lax.psum(out, axis)

    try:
        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(), P(axis), P(axis)),
                       out_specs=P(), check_vma=False)
    except TypeError:
        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(), P(axis), P(axis)),
                       out_specs=P(), check_rep=False)
    return jax.jit(fn)
