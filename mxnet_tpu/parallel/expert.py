"""Expert parallelism — mixture-of-experts FFN over the 'expert' axis.

Nothing to port (the reference predates MoE; SURVEY.md §2.3 lists EP as
a fresh first-class design).  Two forms:

* :func:`moe_ffn` — dense dispatch: expert weights sharded on their
  leading EXPERT axis, tokens replicated; each device runs ALL tokens
  through its experts and one ``psum`` combines.  Exact for any gating,
  simple, but the FLOPs are not top-k sparse — the correctness
  reference.
* :func:`routed_moe_ffn` — the first-class training form: tokens are
  sharded over the 'expert' axis, each token is routed to its top-k
  experts through capacity-bounded ``all_to_all`` dispatch/return hops
  riding ICI (the GShard/Switch design), per-device FFN compute is
  ``k/E``-sparse, and the Switch-style load-balancing auxiliary loss
  comes back with the output so the trainer can add it to the
  objective.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from ..compile_cache import track_lru
from .mesh import current_mesh

__all__ = ["moe_ffn", "routed_moe_ffn"]


def moe_ffn(x, gate_w, w1, w2, top_k=None, mesh=None, axis="expert"):
    """Mixture-of-experts feed-forward.

    ``x`` (B, D) tokens; ``gate_w`` (D, E); ``w1`` (E, D, H);
    ``w2`` (E, H, D) — w1/w2 sharded over ``axis``.  Gating is softmax
    over experts, optionally masked to the ``top_k`` largest (weights
    renormalized), and each expert runs relu(x@w1_e)@w2_e.
    Returns (B, D), replicated over the expert axis.
    """
    mesh = mesh or current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise MXNetError("moe_ffn needs a mesh with a %r axis" % axis)
    n_exp = w1.shape[0]
    if gate_w.shape[1] != n_exp:
        raise MXNetError(
            "gate_w has %d expert columns but w1 has %d experts — a "
            "mismatch would silently drop/duplicate gate mass"
            % (gate_w.shape[1], n_exp))
    if n_exp % mesh.shape[axis] != 0:
        raise MXNetError("num experts %d not divisible by %s=%d"
                         % (n_exp, axis, mesh.shape[axis]))
    return _moe_fn(mesh, axis, top_k)(x, gate_w, w1, w2)


@track_lru("parallel._moe_fn")
@functools.lru_cache(maxsize=32)
def _moe_fn(mesh, axis, top_k):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_dev = mesh.shape[axis]

    def body(x, gate_w, w1, w2):
        # w1/w2: local expert slices (E_local, D, H) / (E_local, H, D)
        e_local = w1.shape[0]
        rank = lax.axis_index(axis)
        logits = x @ gate_w                       # (B, E) replicated
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][:, -1:]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1)   # renormalized over mask
        # this device's gate columns
        local_probs = lax.dynamic_slice_in_dim(
            probs, rank * e_local, e_local, axis=1)  # (B, E_local)
        h = jnp.einsum("bd,edh->ebh", x, w1)
        h = jnp.maximum(h, 0.0)
        y = jnp.einsum("ebh,ehd->ebd", h, w2)     # (E_local, B, D)
        out = jnp.einsum("ebd,be->bd", y, local_probs)
        return lax.psum(out, axis)

    try:
        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(), P(axis), P(axis)),
                       out_specs=P(), check_vma=False)
    except TypeError:
        fn = shard_map(body, mesh=mesh,
                       in_specs=(P(), P(), P(axis), P(axis)),
                       out_specs=P(), check_rep=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# routed top-k MoE (GShard-style all-to-all dispatch)
# ---------------------------------------------------------------------------

def routed_moe_ffn(x, gate_w, w1, w2, top_k=2, capacity_factor=1.25,
                   mesh=None, axis="expert"):
    """Top-k routed mixture-of-experts feed-forward.

    ``x`` (B, D) tokens; ``gate_w`` (D, E); ``w1`` (E, D, H);
    ``w2`` (E, H, D).  Each token is dispatched to its ``top_k``
    highest-gate experts, subject to a per-expert capacity of
    ``ceil(capacity_factor * top_k * B_group / E)`` tokens per source
    group (overflow tokens are dropped from that expert, the standard
    capacity contract).  Combine weights are the softmax of the top-k
    masked gate logits, so with ample capacity the result equals the
    dense :func:`moe_ffn` with the same ``top_k``.

    Under a mesh with an ``axis`` ('expert') dimension, tokens shard
    over the axis, expert weights shard on their leading expert dim, and
    two ``lax.all_to_all`` hops carry tokens to their experts and back —
    per-device FFN compute is ``k/E``-sparse, unlike the dense form.
    With ``mesh=None`` (and no active mesh) the identical math runs on
    one device.

    Returns ``(y, aux_loss)``: ``y`` (B, D) and the scalar Switch-style
    load-balancing loss ``E * sum_e(f_e * P_e)`` (1.0 at perfect
    balance), which the caller scales and adds to the objective.

    ``mesh=None`` auto-discovers the active mesh (like
    :func:`moe_ffn`); pass ``mesh=False`` to force the single-device
    path even under an active mesh.
    """
    if mesh is False:
        mesh = None
    elif mesh is None:
        mesh = current_mesh()
    if mesh is not None and axis not in mesh.shape:
        mesh = None
    n_exp = w1.shape[0]
    if gate_w.shape[1] != n_exp:
        raise MXNetError(
            "gate_w has %d expert columns but w1 has %d experts"
            % (gate_w.shape[1], n_exp))
    if mesh is not None:
        n_dev = mesh.shape[axis]
        if n_exp % n_dev != 0:
            raise MXNetError("num experts %d not divisible by %s=%d"
                             % (n_exp, axis, n_dev))
        if x.shape[0] % n_dev != 0:
            raise MXNetError(
                "token count %d not divisible by %s=%d (tokens shard "
                "over the expert axis)" % (x.shape[0], axis, n_dev))
        b_group = x.shape[0] // n_dev
    else:
        n_dev = 1
        b_group = x.shape[0]
    import math

    capacity = max(1, math.ceil(capacity_factor * top_k * b_group
                                / n_exp))
    if top_k > n_exp:
        raise MXNetError("top_k=%d exceeds num experts %d"
                         % (top_k, n_exp))
    if mesh is None:
        return _routed_local_fn(int(top_k), capacity)(x, gate_w, w1, w2)
    return _routed_fn(mesh, axis, int(top_k), capacity)(x, gate_w, w1, w2)


def _routed_body(x, gate_w, w1_local, w2_local, top_k, capacity, n_dev,
                 axis):
    """The dispatch→expert→combine math for one token group.

    ``w1_local``/``w2_local`` hold this group's ``E_local = E/n_dev``
    experts; with ``axis`` set, two ``all_to_all`` hops exchange the
    capacity-bounded per-expert buffers between groups.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    e_local = w1_local.shape[0]
    n_exp = e_local * n_dev
    b, d = x.shape

    logits = (x @ gate_w).astype(jnp.float32)          # (B, E)
    _, top_idx = lax.top_k(logits, top_k)              # (B, k)
    sel = jax.nn.one_hot(top_idx, n_exp, dtype=logits.dtype)  # (B, k, E)
    mask = sel.sum(axis=1)                             # (B, E) 0/1
    masked = jnp.where(mask > 0, logits, -jnp.inf)
    probs = jax.nn.softmax(masked, axis=-1)            # combine weights

    # Switch-style load balance: f_e = dispatch fraction, P_e = mean
    # full-softmax router prob; globally averaged when sharded
    full_probs = jax.nn.softmax(logits, axis=-1)
    f_e = mask.sum(axis=0) / (b * top_k)       # dispatch fraction, sums to 1
    p_e = full_probs.mean(axis=0)
    if axis is not None:
        f_e = lax.pmean(f_e, axis)
        p_e = lax.pmean(p_e, axis)
    aux = n_exp * jnp.sum(f_e * p_e)

    # position of each (token, choice) inside its expert's buffer;
    # entries past capacity get an all-zero one-hot row (dropped)
    flat_sel = sel.reshape(b * top_k, n_exp).astype(jnp.int32)
    pos = jnp.cumsum(flat_sel, axis=0) - flat_sel
    my_pos = (pos * flat_sel).sum(-1).reshape(b, top_k)     # (B, k)
    pos_oh = jax.nn.one_hot(my_pos, capacity, dtype=x.dtype)
    dm = jnp.einsum("bke,bkc->bec", sel.astype(x.dtype), pos_oh)

    expert_in = jnp.einsum("bec,bd->ecd", dm, x)       # (E, C, D)
    if axis is not None:
        buf = expert_in.reshape(n_dev, e_local, capacity, d)
        recv = lax.all_to_all(buf, axis, 0, 0)         # (n_dev, E_l, C, D)
        xin = recv.transpose(1, 0, 2, 3).reshape(
            e_local, n_dev * capacity, d)
    else:
        xin = expert_in                                # (E, C, D)

    h = jnp.maximum(jnp.einsum("ecd,edh->ech", xin, w1_local), 0.0)
    y = jnp.einsum("ech,ehd->ecd", h, w2_local)

    if axis is not None:
        yb = y.reshape(e_local, n_dev, capacity, d).transpose(1, 0, 2, 3)
        back = lax.all_to_all(yb, axis, 0, 0)          # (n_dev, E_l, C, D)
        ye = back.reshape(n_exp, capacity, d)
    else:
        ye = y
    out = jnp.einsum("bec,ecd->bd",
                     dm * probs.astype(x.dtype)[..., None], ye)
    return out, aux.astype(jnp.float32)


@track_lru("parallel._routed_local_fn")
@functools.lru_cache(maxsize=32)
def _routed_local_fn(top_k, capacity):
    import jax

    def fn(x, gate_w, w1, w2):
        return _routed_body(x, gate_w, w1, w2, top_k, capacity, 1, None)

    return jax.jit(fn)


@track_lru("parallel._routed_fn")
@functools.lru_cache(maxsize=32)
def _routed_fn(mesh, axis, top_k, capacity):
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_dev = mesh.shape[axis]

    def body(x, gate_w, w1, w2):
        out, aux = _routed_body(x, gate_w, w1, w2, top_k, capacity,
                                n_dev, axis)
        return out, aux

    specs = dict(in_specs=(P(axis), P(), P(axis), P(axis)),
                 out_specs=(P(axis), P()))
    try:
        fn = shard_map(body, mesh=mesh, check_vma=False, **specs)
    except TypeError:
        fn = shard_map(body, mesh=mesh, check_rep=False, **specs)
    return jax.jit(fn)
