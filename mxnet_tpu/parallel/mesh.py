"""Device mesh management.

The mesh is this framework's "cluster object": where the reference wires
dp via kvstore types and mp via ``group2ctx`` device placement, here both
are axes of one ``jax.sharding.Mesh`` ("data", "model", "pipe", "seq",
"expert") and XLA lays collectives onto ICI neighbors (SURVEY.md §7
item 7; scaling-book recipe: pick a mesh, annotate shardings, let XLA
insert collectives).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import numpy as np

from ..base import MXNetError

__all__ = ["create_mesh", "current_mesh", "set_mesh", "mesh_scope",
           "data_axis_size", "axis_size"]

_state = threading.local()

# canonical axis order: batch-like axes first (fastest-varying ICI ring
# gets the highest-traffic collective)
AXIS_ORDER = ("data", "fsdp", "seq", "pipe", "model", "expert")


def create_mesh(axes=None, devices=None):
    """Build a ``jax.sharding.Mesh``.

    ``axes``: dict axis-name -> size (e.g. ``{"data": 4, "model": 2}``);
    -1 for one axis means "all remaining devices".  Defaults to pure data
    parallelism over every visible device.
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"data": n}
    axes = dict(axes)
    # resolve -1
    known = 1
    wild = None
    for k, v in axes.items():
        if v == -1:
            if wild is not None:
                raise MXNetError("only one mesh axis may be -1")
            wild = k
        else:
            known *= v
    if wild is not None:
        if n % known:
            raise MXNetError("cannot infer axis %r: %d devices not divisible "
                             "by %d" % (wild, n, known))
        axes[wild] = n // known
        known *= axes[wild]
    if known != n:
        raise MXNetError("mesh axes %r use %d devices but %d are available"
                         % (axes, known, n))
    names = sorted(axes, key=lambda a: AXIS_ORDER.index(a)
                   if a in AXIS_ORDER else len(AXIS_ORDER))
    shape = tuple(axes[a] for a in names)
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def set_mesh(mesh):
    """Set the process-wide active mesh (imperative ops and KVStore
    consult it)."""
    _state.mesh = mesh


def current_mesh():
    return getattr(_state, "mesh", None)


@contextmanager
def mesh_scope(mesh):
    prev = current_mesh()
    set_mesh(mesh)
    try:
        yield mesh
    finally:
        set_mesh(prev)


def axis_size(name):
    mesh = current_mesh()
    if mesh is None or name not in mesh.shape:
        return 1
    return mesh.shape[name]


def data_axis_size():
    return axis_size("data")


def init_distributed(coordinator=None, num_processes=None,
                     process_id=None):
    """Join a multi-process job (the worker-side counterpart of
    ``tools/launch.py``; the reference's ps-lite rendezvous role is
    played by ``jax.distributed``'s coordination service).

    Arguments default from the launcher env: ``MXNET_COORDINATOR``,
    ``MXNET_NUM_WORKERS``, ``MXNET_WORKER_ID``.  No-op when those are
    absent (single-process run).
    """
    import os

    import jax

    coordinator = coordinator or os.environ.get("MXNET_COORDINATOR")
    if coordinator is None:
        return False
    # already joined (e.g. the worker called jax.distributed.initialize
    # itself before any mxnet_tpu entry): a second initialize would
    # raise "must be called before any JAX calls", not "already"
    try:
        from jax._src import distributed as _jdist

        if getattr(_jdist.global_state, "client", None) is not None:
            return True
    except ImportError:  # pragma: no cover - jax internals moved
        pass
    if num_processes is None:
        num_processes = int(os.environ.get("MXNET_NUM_WORKERS", "1"))
    if process_id is None:
        process_id = int(os.environ.get("MXNET_WORKER_ID", "0"))
    try:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    except RuntimeError as e:
        # idempotent: callers (kvstore.create for dist types, user
        # scripts, the CI dist worker) may race to initialize
        if "already" not in str(e).lower():
            raise
    return True
