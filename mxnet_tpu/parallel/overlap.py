"""Compute/collective overlap for the data-parallel gradient reduction.

The fused train step compiles forward+backward+update into one XLA
program; under a data-parallel mesh the cross-replica gradient sum is
the largest exposed collective.  Under plain ``jit``+GSPMD the gradient
tree is a *logical global value* — the per-replica partial sums never
appear in the program we write, so there is nothing to bucket or
reorder, and whether the all-reduce hides under backward compute is
entirely up to the compiler.  This module makes the reduction explicit,
DDP-style: ``shard_map`` the loss/grad computation over the batch axis
so each replica's local gradients exist as values, then issue the
cross-replica sum as a sequence of bucket-sized tuple all-reduces in
*reverse production order* (``MXNET_GRAD_BUCKET_MB`` per bucket).  Each
bucket's collective depends only on its own gradients, so it becomes
schedulable the moment backward emits the bucket's last tensor and
XLA's latency-hiding scheduler (armed by :func:`arm_latency_hiding` for
the TPU build) can overlap it with the rest of the backward — instead
of one step-ending all-reduce over every parameter at once.

Semantics: gradients, the loss value, and the stacked outputs match the
GSPMD path (the loss is a sum over batch elements, so the bucketed psum
of local grads IS the global gradient).  Ops whose math depends on the
*global* batch read the trace context set by
:func:`ddp_value_and_grad` — SoftmaxOutput's ``normalization="batch"``
/``"valid"`` gradient scale widens by :func:`ddp_batch_factor` /
:func:`ddp_psum`, and BatchNorm training statistics ``pmean`` their
local moments (exact sync-BN, equal to the GSPMD global-batch stats) —
so the DDP path stays numerically equivalent, not approximately so.
The per-replica RNG is folded with the replica index so stochastic ops
(dropout) decorrelate across replicas.

Eligibility is checked at trace time; anything unsupported (non-batch
mesh axes, sharded params, outputs whose leading dim is not the batch)
declines with a one-time warning and the step falls back to the GSPMD
reduction — never wrong answers, only a missed optimization.
"""
from __future__ import annotations

import importlib.util
import os
import warnings

from ..base import get_env

__all__ = ["arm_latency_hiding", "bucket_partition", "ddp_axis",
           "ddp_batch_factor", "ddp_pmean", "ddp_psum",
           "ddp_value_and_grad", "grad_bucket_bytes", "overlap_mode"]

# the MaxText-standard trio: latency-hiding scheduler + async collective
# fusion.  Delivered via LIBTPU_INIT_ARGS, NOT XLA_FLAGS: only libtpu
# reads it (at TPU client init), while XLA_FLAGS is parsed strictly by
# every backend build and unknown --xla_tpu_* flags abort a CPU/GPU
# process outright.
_LHS_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
)

class DeclineWarner:
    """One-shot decline reporter with an explicit scope.

    Decline warnings must fire once per *consumer*, not once per
    process: a second ``TrainStep`` built with a different config in
    the same process has its own decline reasons to report, so each
    step owns a :class:`DeclineWarner` and passes it down.  The
    module-level default (``_warned``) keeps the old once-per-process
    behavior for direct callers."""

    def __init__(self):
        self.seen = set()

    def warn(self, key, msg):
        if key not in self.seen:
            self.seen.add(key)
            warnings.warn(msg, RuntimeWarning, stacklevel=3)

    def discard(self, key):
        self.seen.discard(key)


_default_warner = DeclineWarner()
# back-compat alias: tests/pre-existing callers reach the process-wide
# key set through ``overlap._warned``
_warned = _default_warner.seen

# (axis_name, replica_count) while the DDP local step is being traced,
# else None.  Batch-global ops consult this: under shard_map they see
# only the local batch shard, so anything whose math depends on the
# global batch — SoftmaxOutput's normalization="batch"/"valid" gradient
# scale, BatchNorm's training statistics — must widen its reduction by
# the replica count (or a psum) to keep the DDP path numerically equal
# to the GSPMD one.
_ddp_ctx = None


def ddp_batch_factor():
    """Replica count of the active DDP reduction (1 outside the trace)."""
    return _ddp_ctx[1] if _ddp_ctx else 1


def ddp_psum(x):
    """Sum ``x`` across the active DDP replicas (identity outside)."""
    if _ddp_ctx is None:
        return x
    from jax import lax

    return lax.psum(x, _ddp_ctx[0])


def ddp_pmean(x):
    """Mean of ``x`` across the active DDP replicas (identity outside)."""
    if _ddp_ctx is None:
        return x
    from jax import lax

    return lax.pmean(x, _ddp_ctx[0])


def _warn_once(key, msg, warner=None):
    (warner or _default_warner).warn(key, msg)


def overlap_mode():
    """``MXNET_GRAD_OVERLAP``: ``auto`` (default) | ``on`` | ``off``."""
    raw = str(get_env("MXNET_GRAD_OVERLAP", "auto")).strip().lower()
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    return "auto"


def grad_bucket_bytes():
    """Bucket size for the explicit reduction (``MXNET_GRAD_BUCKET_MB``,
    default 4 MB; 0 = one collective per parameter)."""
    mb = get_env("MXNET_GRAD_BUCKET_MB", 4.0)
    return max(0, int(mb * (1 << 20)))


def arm_latency_hiding():
    """Append the latency-hiding-scheduler flags to ``LIBTPU_INIT_ARGS``
    (idempotent).

    Best-effort: the flags only take effect when set before the TPU
    client initializes, so the first ``TrainStep`` construction in a
    process arms them.  ``auto`` (default) arms only when a TPU is
    plausibly present (``JAX_PLATFORMS`` mentions tpu, or libtpu is
    importable) — CPU/GPU backends never read ``LIBTPU_INIT_ARGS``, so
    arming is inert there; ``MXNET_XLA_LHS=1`` forces, ``0`` disables.
    Returns True when the flags are present after the call.
    """
    mode = str(get_env("MXNET_XLA_LHS", "auto")).strip().lower()
    if mode in ("0", "off", "false", "no"):
        return False
    tpu_hint = ("tpu" in os.environ.get("JAX_PLATFORMS", "").lower()
                or importlib.util.find_spec("libtpu") is not None)
    if mode == "auto" and not tpu_hint:
        return False
    flags = os.environ.get("LIBTPU_INIT_ARGS", "")
    missing = [f for f in _LHS_FLAGS if f.split("=")[0] not in flags]
    if missing:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join([flags] + missing).strip()
    return True


def ddp_axis(mesh, batch_axis, param_sharding=None, warner=None,
             param_names=()):
    """The mesh axis the explicit DDP reduction runs over, or None.

    Eligible: a live mesh whose only non-trivial axis is the batch axis
    (pure data parallelism) with replicated parameters — sharded-param
    styles (fsdp) already reduce-scatter through GSPMD and have their
    own overlap story.  ``warner``: per-consumer decline reporter;
    ``param_names`` lets a forced-on decline name the specific blocking
    parameter.  A style whose every resolved spec is trivial on this
    mesh is effectively pure DP and stays eligible.
    """
    if overlap_mode() == "off":
        return None
    if param_sharding not in (None, "replicated"):
        from .zero import _blocking_param

        blocking = _blocking_param(mesh, param_sharding, param_names)
        if blocking is not None:
            if overlap_mode() == "on":
                name, spec = blocking
                _warn_once(
                    "params",
                    "MXNET_GRAD_OVERLAP=on but param_sharding=%r places "
                    "%s as PartitionSpec%r — sharded grads reduce "
                    "through GSPMD (compose the layouts with a "
                    "ParallelPlan instead)"
                    % (param_sharding, name, tuple(spec)), warner)
            return None
    if mesh is None or batch_axis not in mesh.shape:
        return None
    if int(mesh.shape[batch_axis]) < 2:
        return None
    if any(int(s) != 1 for ax, s in mesh.shape.items()
           if ax != batch_axis):
        if overlap_mode() == "on":
            _warn_once("mesh", "MXNET_GRAD_OVERLAP=on but the mesh has "
                       "non-batch axes %r; using the GSPMD reduction"
                       % (dict(mesh.shape),), warner)
        return None
    return batch_axis


def bucket_partition(order, sizes, bucket_bytes):
    """Greedily group ``order`` (reverse production order) into buckets
    of at most ``bucket_bytes`` each (always at least one name per
    bucket, so oversized tensors get their own collective)."""
    buckets, cur, cur_bytes = [], [], 0
    for name in order:
        sz = int(sizes[name])
        if cur and cur_bytes + sz > bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(name)
        cur_bytes += sz
    if cur:
        buckets.append(cur)
    return buckets


def _shard_map(fn, mesh, in_specs, out_specs):
    import jax

    try:
        smap = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map as smap
    try:
        return smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=False)
    except TypeError:  # older jax spells the flag check_rep
        return smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False)


def ddp_value_and_grad(loss_fn, params, batch, rng, mesh, axis,
                       frozen=frozenset(), order=None, bucket_bytes=None,
                       warner=None, zero_layout=None, zero_rest=False):
    """Explicit data-parallel ``value_and_grad`` with bucketed reduction.

    ``loss_fn(p, b, r) -> (loss, (outs, new_aux))`` must compute the
    *sum-over-batch* objective (the fused step's contract), so the
    global gradient is exactly the psum of per-replica local gradients.
    Returns ``((loss, (outs, new_aux)), grads)`` with global semantics
    — a drop-in for ``jax.value_and_grad(...)(params)`` — or ``None``
    when this trace cannot run the DDP path (caller falls back to the
    GSPMD reduction).  Called at trace time inside the fused step's
    ``jit``.

    ``zero_layout`` ({name: ``parallel.zero.ZeroParam``}, sharing this
    ``axis``): sharded members of each bucket come back *reduce-
    scattered* — one tuple ``psum_scatter`` per bucket instead of the
    tuple ``psum`` — as flat ``(padded,)`` arrays tiled ``P(axis)``;
    unsharded members keep the full psum.  Same overlap schedule, 1/N
    of the reduction's receive bytes.

    ``zero_rest`` (ZeRO-3): the sharded members of ``params`` are
    ALREADY the flat at-rest tiles (in_spec ``P(axis)``), ``loss_fn``
    gathers them on demand, and AD's transpose of that
    ``all_gather(tiled=True)`` is itself the ``psum_scatter`` — their
    gradients arrive pre-reduce-scattered exactly where backward
    produces them, so they are EXCLUDED from the bucketed reduction
    (summing them again would double-count).  Only the unsharded
    leftovers ride the psum buckets.
    """
    import math

    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n = int(mesh.shape[axis])
    for k, b in batch.items():
        if b.ndim == 0 or b.shape[0] % n:
            _warn_once("batch", "grad-overlap declined: batch input %r "
                       "shape %r not divisible by %s=%d"
                       % (k, tuple(b.shape), axis, n), warner)
            return None

    def full_vag(p, b, r):
        return jax.value_and_grad(
            lambda q: loss_fn(q, b, r), has_aux=True)(p)

    S = jax.ShapeDtypeStruct
    local_batch = {k: S((b.shape[0] // n,) + b.shape[1:], b.dtype)
                   for k, b in batch.items()}
    g_abs = jax.eval_shape(full_vag, params, batch, rng)
    l_abs = jax.eval_shape(full_vag, params, local_batch, rng)
    (_, (g_outs, g_aux)), g_grads = g_abs
    (_, (l_outs, _)), _ = l_abs

    # classify outputs: every leaf must carry the batch on its leading
    # dim so shard_map can stitch the global value back (out_spec
    # P(axis)).  Anything else (scalar MakeLoss heads, reductions) has
    # replica-dependent values with no inferable global semantics.
    out_specs_leaves = []
    for gl, ll in zip(jax.tree.leaves(g_outs), jax.tree.leaves(l_outs)):
        if (gl.ndim and gl.shape[0] == ll.shape[0] * n
                and gl.shape[1:] == ll.shape[1:]):
            out_specs_leaves.append(P(axis))
        else:
            _warn_once("outs", "grad-overlap declined: output leaf shape "
                       "%r does not carry the batch on its leading dim"
                       % (tuple(gl.shape),), warner)
            return None
    outs_spec = jax.tree.unflatten(jax.tree.structure(g_outs),
                                   out_specs_leaves)

    if bucket_bytes is None:
        bucket_bytes = grad_bucket_bytes()

    def _is_scattered(k):
        return (zero_layout is not None and k in zero_layout
                and zero_layout[k].sharded)

    live = [k for k in (order if order is not None else sorted(g_grads))
            if k in g_grads and k not in frozen
            and not (zero_rest and _is_scattered(k))]
    sizes = {k: math.prod(g_grads[k].shape) * g_grads[k].dtype.itemsize
             for k in live}
    buckets = bucket_partition(live, sizes, bucket_bytes) if live else []

    def local_step(p, b, r):
        from . import zero as _zero

        # decorrelate stochastic ops (dropout) across replicas
        r = jax.random.fold_in(r, lax.axis_index(axis))
        (loss, (outs, new_aux)), grads = full_vag(p, b, r)
        grads = dict(grads)
        # one tuple all-reduce per bucket, reverse production order:
        # bucket i's collective depends only on its own gradients, so
        # the scheduler can issue it while backward still computes the
        # earlier layers' buckets.  Under the zero layout the bucket's
        # sharded members flatten/pad first and reduce-SCATTER instead:
        # each replica keeps only its 1/N tile of the summed gradient.
        for bucket in buckets:
            plain = [k for k in bucket if not _is_scattered(k)]
            scat = [k for k in bucket if _is_scattered(k)]
            if plain:
                summed = lax.psum(tuple(grads[k] for k in plain), axis)
                for k, g in zip(plain, summed):
                    grads[k] = g
            if scat:
                tiles = lax.psum_scatter(
                    tuple(_zero.flat_pad(grads[k], zero_layout[k])
                          for k in scat),
                    axis, scatter_dimension=0, tiled=True)
                for k, g in zip(scat, tiles):
                    grads[k] = g
        loss = lax.psum(loss, axis)
        new_aux = lax.pmean(new_aux, axis)
        return (loss, (outs, new_aux)), grads

    bspec = {k: P(axis) for k in batch}
    gspec = {k: (P(axis) if _is_scattered(k) else P())
             for k in g_grads}
    # ZeRO-3 at-rest tiles enter sharded P(axis); everything else
    # (full params, zero-1 replicated weights) enters replicated
    pspec = ({k: (P(axis) if _is_scattered(k) else P()) for k in params}
             if zero_rest else P())
    spec_tree = ((P(), (outs_spec, jax.tree.map(lambda _: P(), g_aux))),
                 gspec)
    fn = _shard_map(local_step, mesh, (pspec, bspec, P()), spec_tree)
    # trace the local step under the DDP context so batch-global ops
    # (SoftmaxOutput normalization, BatchNorm training stats) widen
    # their reductions to the global batch
    global _ddp_ctx
    prev, _ddp_ctx = _ddp_ctx, (axis, n)
    try:
        return fn(params, batch, rng)
    finally:
        _ddp_ctx = prev
