"""Pipeline parallelism — GPipe-style microbatch schedule over the
'pipe' mesh axis.

The reference's only pipelining is manual ``group2ctx`` staging
(``example/model-parallel-lstm``, SURVEY.md §2.3 "Model parallelism"):
layers pinned to devices, activations copied at boundaries, no
microbatching.  This is the fresh TPU-first design: stage parameters are
stacked on a leading axis sharded over 'pipe' (each device HOLDS one
stage), and inside ``shard_map`` a ``lax.fori_loop`` runs the classic
GPipe schedule — at tick t, stage 0 ingests microbatch t while stage s
processes the activation ``ppermute``'d from stage s-1, so all stages
are busy once the pipeline fills (M + S - 1 ticks for M microbatches on
S stages).  The hop rides ICI between ring neighbors.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stage_params, microbatches, mesh=None,
                   axis="pipe"):
    """Run ``microbatches`` through a pipeline of stages.

    ``stage_fn(params, x) -> y``: one stage's computation; every stage
    shares this code (same shapes in = shapes out, the homogeneous-stage
    form — e.g. a transformer block).

    ``stage_params``: pytree whose leaves have a leading STAGE axis of
    size ``mesh.shape[axis]``; it is sharded so each device holds its
    stage's slice.

    ``microbatches``: (M, micro_batch, ...) array; returns the stacked
    outputs (M, micro_batch, ...), replicated over the pipe axis.
    """
    import jax

    mesh = mesh or current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise MXNetError("pipeline_apply needs a mesh with a %r axis"
                         % axis)
    n_stages = mesh.shape[axis]
    leaves = jax.tree.leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise MXNetError(
                "stage_params leading dim %d != pipe axis size %d"
                % (leaf.shape[0], n_stages))
    return _pipeline_fn(mesh, axis, stage_fn,
                        jax.tree.structure(stage_params))(
        stage_params, microbatches)


@functools.lru_cache(maxsize=32)
def _pipeline_fn(mesh, axis, stage_fn, params_treedef):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape[axis]

    def body(params, micro):
        # params leaves: (1, ...) local stage slice; micro: (M, mb, ...)
        local_params = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        m = micro.shape[0]
        ticks = m + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        carry0 = jnp.zeros_like(micro[0])   # activation arriving from prev
        out0 = jnp.zeros_like(micro)

        def tick(t, state):
            carry, out = state
            feed = micro[jnp.minimum(t, m - 1)]
            x = jnp.where(stage == 0, feed, carry)
            y = stage_fn(local_params, x)
            # last stage emits microbatch t-(S-1) at tick t
            emit_idx = t - (n_stages - 1)
            is_emit = (stage == n_stages - 1) & (emit_idx >= 0)
            out = lax.cond(
                is_emit,
                lambda o: o.at[jnp.maximum(emit_idx, 0)].set(y),
                lambda o: o, out)
            carry = lax.ppermute(y, axis, perm)
            return carry, out

        _, out = lax.fori_loop(0, ticks, tick, (carry0, out0))
        # outputs live on the last stage; replicate over the pipe axis
        out = lax.psum(
            jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
            axis)
        return out

    pspec = jax.tree.unflatten(
        params_treedef,
        [P(axis)] * params_treedef.num_leaves)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_vma=False)
    except TypeError:
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_rep=False)
    return jax.jit(fn)
