"""Pipeline parallelism — microbatch schedules over the 'pipe' mesh axis.

The reference's only pipelining is manual ``group2ctx`` staging
(``example/model-parallel-lstm``, SURVEY.md §2.3 "Model parallelism"):
layers pinned to devices, activations copied at boundaries, no
microbatching.  This module is the fresh TPU-first design, in two tiers:

* :func:`pipeline_apply` — homogeneous stages (every stage shares one
  ``stage_fn``), forward-only GPipe schedule: stage parameters stacked
  on a leading axis sharded over 'pipe', a ``lax.fori_loop`` runs the
  fill/drain wave, activations hop between ring neighbors on ICI via
  ``ppermute``.
* :class:`PipelineTrainStep` — the first-class training form:
  **heterogeneous** stages (embed → N blocks → head) derived from a
  Symbol via :func:`split_symbol`, per-stage parameters flat-packed into
  one ``(S, L)`` buffer sharded over 'pipe' (each device physically
  holds only its stage's weights + optimizer state), and a choice of
  schedules:

  - ``schedule='gpipe'`` — all-forward wave stashing every stage
    input (M slots — the GPipe memory profile), then an explicit
    validity-gated all-backward wave that recomputes each stage
    forward under ``jax.vjp`` (gating matters: differentiating the
    whole forward scan would let the loss heads' custom vjp — which
    ignores its cotangent per the reference contract — emit junk
    gradients for the fill/drain ticks);
  - ``schedule='1f1b'`` — interleaved one-forward-one-backward: each
    stage keeps a bounded ring of at most ``2S`` stage-input
    activations and **recomputes** the stage forward during its
    backward tick (remat, the TPU-idiomatic trade — XLA already
    offers it as ``jax.checkpoint``), so peak activation memory is
    O(S), independent of M.  Gradients accumulate locally on each
    stage's device; no cross-stage gradient collective is needed
    because every parameter lives on exactly one stage.

  Both schedules move activations forward (and 1F1B moves cotangents
  backward) with ``lax.ppermute`` between mesh ring neighbors — the
  ICI-friendly hop — and compile to ONE XLA program including the
  optimizer update (donated buffers), the same single-program stance as
  ``fused.TrainStep``.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from ..compile_cache import track_lru
from .mesh import current_mesh

__all__ = ["pipeline_apply", "split_symbol", "PipelineTrainStep"]


def pipeline_apply(stage_fn, stage_params, microbatches, mesh=None,
                   axis="pipe"):
    """Run ``microbatches`` through a pipeline of stages.

    ``stage_fn(params, x) -> y``: one stage's computation; every stage
    shares this code (same shapes in = shapes out, the homogeneous-stage
    form — e.g. a transformer block).

    ``stage_params``: pytree whose leaves have a leading STAGE axis of
    size ``mesh.shape[axis]``; it is sharded so each device holds its
    stage's slice.

    ``microbatches``: (M, micro_batch, ...) array; returns the stacked
    outputs (M, micro_batch, ...), replicated over the pipe axis.
    """
    import jax

    mesh = mesh or current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise MXNetError("pipeline_apply needs a mesh with a %r axis"
                         % axis)
    n_stages = mesh.shape[axis]
    leaves = jax.tree.leaves(stage_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise MXNetError(
                "stage_params leading dim %d != pipe axis size %d"
                % (leaf.shape[0], n_stages))
    return _pipeline_fn(mesh, axis, stage_fn,
                        jax.tree.structure(stage_params))(
        stage_params, microbatches)


@track_lru("parallel._pipeline_fn")
@functools.lru_cache(maxsize=32)
def _pipeline_fn(mesh, axis, stage_fn, params_treedef):
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape[axis]

    def body(params, micro):
        # params leaves: (1, ...) local stage slice; micro: (M, mb, ...)
        local_params = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        m = micro.shape[0]
        ticks = m + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        carry0 = jnp.zeros_like(micro[0])   # activation arriving from prev
        out0 = jnp.zeros_like(micro)

        def tick(t, state):
            carry, out = state
            feed = micro[jnp.minimum(t, m - 1)]
            x = jnp.where(stage == 0, feed, carry)
            y = stage_fn(local_params, x)
            # last stage emits microbatch t-(S-1) at tick t
            emit_idx = t - (n_stages - 1)
            is_emit = (stage == n_stages - 1) & (emit_idx >= 0)
            out = lax.cond(
                is_emit,
                lambda o: o.at[jnp.maximum(emit_idx, 0)].set(y),
                lambda o: o, out)
            carry = lax.ppermute(y, axis, perm)
            return carry, out

        _, out = lax.fori_loop(0, ticks, tick, (carry0, out0))
        # outputs live on the last stage; replicate over the pipe axis
        out = lax.psum(
            jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
            axis)
        return out

    pspec = jax.tree.unflatten(
        params_treedef,
        [P(axis)] * params_treedef.num_leaves)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_vma=False)
    except TypeError:
        fn = shard_map(body, mesh=mesh, in_specs=(pspec, P()),
                       out_specs=P(), check_rep=False)
    return jax.jit(fn)


# ---------------------------------------------------------------------------
# heterogeneous stages from a Symbol
# ---------------------------------------------------------------------------

def split_symbol(sym, n_stages, data_names=("data",),
                 label_names=("softmax_label",), input_shapes=None):
    """Cut a Symbol into ``n_stages`` stage symbols at graph positions
    where a fixed-size set of live tensors crosses (the pipeline
    boundary contract: every hop carries the same pytree of
    activations).

    The reference analogue is manual ``group2ctx`` staging
    (``/root/reference/example/model-parallel-lstm/lstm.py:65-129``)
    where the user assigns layers to devices by hand; here the cut
    points are found automatically: the smallest boundary width K with
    enough single-width positions is chosen, and the S-1 cuts are
    placed at even quantiles of the op-node order (transformer blocks
    are uniform, so this balances compute).

    Returns ``stage_syms``: stage k consumes boundary Variables
    ``pipe_in0..pipe_in{K-1}`` (except stage 0, which consumes the data
    variables) and outputs the K live tensors at its cut (the last
    stage outputs the original symbol heads).
    """
    from ..symbol.symbol import Symbol, _Node

    if n_stages < 2:
        raise MXNetError("split_symbol needs n_stages >= 2")
    topo = sym._topo()
    op_nodes = [n for n in topo if not n.is_variable]
    if len(op_nodes) < n_stages:
        raise MXNetError("symbol has %d op nodes, cannot make %d stages"
                         % (len(op_nodes), n_stages))
    # DFS order visits whole output chains one at a time, which strands
    # side chains (e.g. a running aux-loss sum) at the end and hides the
    # narrow boundaries; re-order by longest-path level (a valid topo
    # order — every edge goes to a strictly higher level) so each node
    # sits right after its inputs
    level = {}
    for n in topo:
        level[id(n)] = 0 if n.is_variable else 1 + max(
            (level[id(s)] for (s, _) in n.inputs), default=0)
    dfs_pos = {id(n): i for i, n in enumerate(op_nodes)}
    op_nodes.sort(key=lambda n: (level[id(n)], dfs_pos[id(n)]))
    pos = {id(n): i for i, n in enumerate(op_nodes)}

    # nodes computable from data/label variables alone (no parameters)
    # are "feed-local": cheap to recompute in whichever stage consumes
    # them (e.g. a label reshape feeding the loss head), so they never
    # ride the inter-stage hop
    feed_names = set(data_names) | set(label_names)
    replicable = {}

    def _replicable(n):
        if id(n) in replicable:
            return replicable[id(n)]
        if n.is_variable:
            r = n.name in feed_names
        else:
            r = all(_replicable(s) for (s, _) in n.inputs)
        replicable[id(n)] = r
        return r

    for n in topo:
        _replicable(n)

    # last consumer position of every op-node output entry
    consumed_at = {}
    for n in op_nodes:
        for (src, idx) in n.inputs:
            if not src.is_variable:
                key = (id(src), idx)
                consumed_at[key] = max(consumed_at.get(key, -1),
                                       pos[id(n)])
    out_entries = [(id(n), i) for (n, i) in sym._outputs]
    for key in out_entries:
        consumed_at[key] = len(op_nodes)  # live to the very end

    # live entries after each op position p (ordered by producer, idx)
    def live_after(p):
        live = []
        for n in op_nodes[:p + 1]:
            if replicable[id(n)]:
                continue
            for i in range(n.num_outputs):
                key = (id(n), i)
                if consumed_at.get(key, -1) > p:
                    live.append(key)
        return live

    lives = [live_after(p) for p in range(len(op_nodes) - 1)]

    # group candidate positions by boundary signature.  With
    # ``input_shapes`` the signature is the sorted multiset of live
    # tensor shapes (every hop must carry the same buffer set); without
    # shapes it degrades to the live width alone.
    if input_shapes is not None:
        entry_shapes = _entry_shapes(sym, topo, dict(input_shapes))

        def signature(lv):
            return tuple(sorted(str(entry_shapes[key]) for key in lv))
    else:
        def signature(lv):
            return (len(lv),)

    groups = {}
    for p, lv in enumerate(lives):
        groups.setdefault((len(lv), signature(lv)), []).append(p)

    # pick the smallest boundary width whose candidate positions cover
    # every quantile cut (a group with candidates only near one end
    # would produce wildly unbalanced stages)
    targets = [k * len(op_nodes) / n_stages for k in range(1, n_stages)]
    tol = max(1.0, len(op_nodes) / (2.0 * n_stages))
    cand = None
    for (width, _sig), c in sorted(groups.items()):
        if len(c) >= n_stages - 1 and all(
                any(abs(p - t) <= tol for p in c) for t in targets):
            cand = c
            break
    if cand is None:
        for (width, _sig), c in sorted(groups.items()):
            if len(c) >= n_stages - 1:
                cand = c
                break
    if cand is None:
        raise MXNetError(
            "no boundary signature offers %d cut points; this symbol "
            "does not decompose into a fixed-width pipeline (try fewer "
            "stages; %d op nodes, boundary groups: %s)"
            % (n_stages - 1, len(op_nodes),
               sorted((k[0], len(v)) for k, v in groups.items())))

    # even quantiles over the op order -> nearest candidate (distinct)
    cuts = []
    for k in range(1, n_stages):
        target = k * len(op_nodes) / n_stages
        best = min((c for c in cand if c not in cuts),
                   key=lambda c: abs(c - target), default=None)
        if best is None:
            raise MXNetError("not enough distinct cut candidates for %d "
                             "stages" % n_stages)
        cuts.append(best)
    cuts.sort()
    if len(set(cuts)) != len(cuts):
        raise MXNetError("cut positions collide; reduce n_stages")

    node_by_id = {id(n): n for n in topo}
    stage_syms = []
    prev_cut = -1
    in_entries = []        # boundary entries feeding the current stage
    for k in range(n_stages):
        end = cuts[k] if k < n_stages - 1 else len(op_nodes) - 1
        segment = op_nodes[prev_cut + 1:end + 1]
        bvars = {entry: _Node(None, "pipe_in%d" % i, {}, [])
                 for i, entry in enumerate(in_entries)}
        mapping = {}

        def remap(src, idx):
            if (id(src), idx) in bvars:
                return (bvars[(id(src), idx)], 0)
            if id(src) in mapping:
                return (mapping[id(src)], idx)
            if src.is_variable:
                return (src, idx)
            if replicable[id(src)]:
                # feed-local producer from an earlier segment: clone its
                # whole (parameter-free) subtree into this stage
                clone = _Node(src.op, src.name, src.attrs,
                              [remap(s, i) for (s, i) in src.inputs],
                              src.aux_slots)
                mapping[id(src)] = clone
                return (clone, idx)
            raise MXNetError(
                "pipeline cut is not closed: node %r (stage %d) consumes "
                "a non-boundary tensor from an earlier stage" %
                (src.name, k))

        for n in segment:
            clone = _Node(n.op, n.name, n.attrs,
                          [remap(s, i) for (s, i) in n.inputs],
                          n.aux_slots)
            mapping[id(n)] = clone

        if k < n_stages - 1:
            out_keys = lives[cuts[k]]
        else:
            out_keys = out_entries
        outs = []
        for (nid, idx) in out_keys:
            if nid in mapping:
                outs.append((mapping[nid], idx))
            elif (nid, idx) in bvars:     # pass-through tensor
                outs.append((bvars[(nid, idx)], 0))
            else:
                src = node_by_id[nid]
                if src.is_variable:
                    outs.append((src, idx))
                else:
                    raise MXNetError("internal: stage %d output %r not "
                                     "in segment" % (k, src.name))
        stage_syms.append(Symbol(outs))
        in_entries = out_keys if k < n_stages - 1 else []
        prev_cut = end

    # a parameter Variable consumed by more than one stage (weight
    # tying) would pack as independent per-stage copies with partial
    # gradients — silently wrong; refuse
    feed = set(data_names) | set(label_names)
    seen_params = {}
    for k, ssym in enumerate(stage_syms):
        for a in ssym.list_arguments():
            if a in feed or a.startswith("pipe_in"):
                continue
            if a in seen_params:
                raise MXNetError(
                    "parameter %r is shared between pipeline stages %d "
                    "and %d (weight tying); tied weights cannot shard "
                    "over stages — untie them or use the dense fused "
                    "step" % (a, seen_params[a], k))
            seen_params[a] = k
    return stage_syms


def _entry_shapes(sym, topo, known_shapes):
    """Shape of every (node, out_idx) entry, given input shapes (drives
    the shape-aware boundary signatures)."""
    from ..symbol.symbol import _abstract_eval, _infer_param_shapes

    var_shapes = _infer_param_shapes(sym, known_shapes)
    env = {}
    for n in topo:
        if n.is_variable:
            env[(id(n), 0)] = tuple(var_shapes.get(n.name, ()))
            continue
        in_shapes = [env[(id(s), i)] for (s, i) in n.inputs]
        for i, shp in enumerate(_abstract_eval(n, in_shapes)):
            env[(id(n), i)] = shp
    return env


# ---------------------------------------------------------------------------
# packed stage state + the 1F1B / GPipe training step
# ---------------------------------------------------------------------------

class _Packer:
    """Static flat-packing layout for a pytree of arrays.

    Each pipeline stage has a different parameter/optimizer-state pytree;
    packing every stage into one fp32 row of a shared ``(S, L)`` buffer
    is what lets heterogeneous stages shard over the 'pipe' mesh axis
    (each device holds exactly its stage's row).  Layout (offsets,
    shapes, dtypes) is static per stage, so unpacking inside a
    ``lax.switch`` branch is pure static slicing — XLA sees one fused
    program, no gathers."""

    def __init__(self, template):
        import jax
        import numpy as np

        leaves, self.treedef = jax.tree.flatten(template)
        self.shapes = [tuple(l.shape) for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.offsets = []
        off = 0
        for sz in self.sizes:
            self.offsets.append(off)
            off += sz
        self.total = off

    def pack(self, tree, length=None):
        """Concrete pytree -> fp32 row (padded to ``length``)."""
        import jax
        import jax.numpy as jnp

        leaves = jax.tree.leaves(tree)
        parts = [jnp.asarray(x).astype(jnp.float32).ravel()
                 for x in leaves]
        row = jnp.concatenate(parts) if parts else jnp.zeros((0,), "float32")
        length = length or self.total
        if row.shape[0] < length:
            row = jnp.pad(row, (0, length - row.shape[0]))
        return row

    def unpack(self, row):
        import jax
        import jax.numpy as jnp

        parts = []
        for shp, dt, off, sz in zip(self.shapes, self.dtypes,
                                    self.offsets, self.sizes):
            leaf = row[off:off + sz].reshape(shp).astype(dt)
            parts.append(leaf)
        return jax.tree.unflatten(self.treedef, parts)


class PipelineTrainStep:
    """Compiled pipelined train step: fwd + bwd + optimizer in ONE XLA
    program over the 'pipe' mesh axis, heterogeneous stages derived
    from a Symbol (``split_symbol``), parameters/optimizer states
    flat-packed and stage-sharded.

    ``schedule='1f1b'`` interleaves one-forward-one-backward with a
    bounded activation ring (stage inputs only; the stage forward is
    recomputed during its backward — remat); ``'gpipe'`` runs the
    all-forward wave over an M-slot stage-input stash, then an
    explicit validity-gated backward wave (same recompute strategy,
    O(M) stash instead of the O(S) ring).

    Call contract mirrors ``fused.TrainStep``:
    ``(params, aux, states, batch, rng, lr, t) -> (params, aux, states,
    outs)`` — but params/aux/states live INTERNALLY as packed
    stage-sharded buffers between steps; the dicts handed back are the
    same handles passed in (stale), and :meth:`unpack_params` /
    :meth:`unpack_aux` gather the live values for checkpointing/eval
    (``Module`` syncs lazily through them).

    Aux states (BatchNorm moving stats) thread through the schedule as a
    third stage-sharded packed buffer: each stage blends its own BN
    stats once per valid microbatch tick, so after one step the moving
    stats equal the sequential microbatch-loop semantics
    (``new = mom^M * old + (1-mom) * sum_m mom^(M-1-m) * stat_m``) —
    training-mode BN *reads* batch stats, never the aux buffer, so the
    1F1B recompute stays consistent no matter when the backward tick
    lands (reference aux-state semantics:
    ``src/operator/batch_norm.cc`` FMutateInputs).

    Rng ops (Dropout) draw a per-(stage, microbatch) key
    ``fold_in(fold_in(step_rng, m), k)``: the 1F1B backward recompute
    re-derives the same key from its tick index, so the recomputed
    dropout mask is bit-identical to the forward's.
    """

    def __init__(self, symbol, optimizer="sgd", optimizer_params=None,
                 mesh=None, n_microbatches=None,
                 data_names=("data",), label_names=("softmax_label",),
                 axis="pipe", schedule="1f1b", grad_scale=None,
                 fixed_param_names=(), plan=None):
        from .. import optimizer as opt_mod

        if plan is not None:
            # a composed ParallelPlan carries the pipe topology: the
            # mesh, the schedule and the microbatch count come from ONE
            # declaration (Module routes pipe>1 plans here)
            from .plan import ParallelPlan

            plan = ParallelPlan.parse(plan)
            if plan.pipe < 2:
                raise MXNetError(
                    "PipelineTrainStep got a plan without a >=2-stage "
                    "pipe axis: %r (use fused.TrainStep)" % (plan,))
            if mesh is None:
                mesh = plan.mesh()
            else:
                plan.validate_mesh(mesh)
            schedule = plan.schedule
            if n_microbatches is None:
                n_microbatches = plan.n_microbatches
        self.plan = plan
        mesh = mesh if mesh is not None else current_mesh()
        if mesh is None or axis not in mesh.shape:
            raise MXNetError(
                "PipelineTrainStep needs a mesh with a %r axis" % axis)
        if mesh.shape[axis] < 2:
            raise MXNetError("pipeline needs >= 2 stages")
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape[axis]
        if schedule not in ("1f1b", "gpipe"):
            raise MXNetError("schedule must be '1f1b' or 'gpipe', got %r"
                             % (schedule,))
        self.schedule = schedule
        self.symbol = symbol
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.n_micro = n_microbatches or 2 * self.n_stages

        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        if not optimizer.supports_fused:
            raise MXNetError("optimizer %s has no fused form"
                             % type(optimizer).__name__)
        self.optimizer = optimizer
        self.lr = optimizer.lr

        # the split itself is deferred to the first batch (_build) where
        # input shapes make the boundary signatures shape-aware
        feed_set = set(self.data_names) | set(self.label_names)
        self.param_names = [a for a in symbol.list_arguments()
                            if a not in feed_set]
        self.aux_names = list(symbol.list_auxiliary_states())
        self._frozen = frozenset(fixed_param_names)

        # default grad scale: per-microbatch losses sum over M; 'batch'-
        # normalized heads (grad ~ 1/mb per micro) need 1/M for parity
        # with the dense full-batch step
        if grad_scale is None:
            loss_heads = [n for n in symbol._topo() if not n.is_variable
                          and n.op.name in
                          ("SoftmaxOutput", "Softmax", "SVMOutput",
                           "LinearRegressionOutput",
                           "LogisticRegressionOutput",
                           "MAERegressionOutput")]
            batch_heads = [n for n in loss_heads
                           if n.attrs.get("normalization") == "batch"]
            sum_heads = [n for n in loss_heads if n not in batch_heads]
            if batch_heads and sum_heads:
                raise MXNetError(
                    "symbol mixes batch-normalized and sum-normalized "
                    "loss heads; one grad scale cannot match both under "
                    "microbatching — pass grad_scale explicitly")
            grad_scale = 1.0 / self.n_micro if batch_heads else 1.0
        self.grad_scale = float(grad_scale)

        self._built = None      # lazy: needs concrete batch shapes
        self._packed_params = None
        self._packed_states = None
        self._packed_aux = None
        self._t = 0

    # -- layout build (first call) ---------------------------------------
    def _build(self, batch):
        import jax
        import numpy as np

        from ..executor import _trace_fn
        from ..symbol.symbol import _infer_param_shapes

        S, M = self.n_stages, self.n_micro
        full_shapes = {k: tuple(v.shape) for k, v in batch.items()}
        nbatch = full_shapes[self.data_names[0]][0]
        if nbatch % M:
            raise MXNetError(
                "batch size %d not divisible by n_microbatches=%d"
                % (nbatch, M))
        mb = nbatch // M
        micro_shapes = {k: (mb,) + s[1:] for k, s in full_shapes.items()}
        micro_dtypes = {k: v.dtype for k, v in batch.items()}

        # shape-aware split: every boundary carries an identical buffer
        # set (the micro-batch shapes, not the full batch, cross hops)
        self._stage_syms = split_symbol(
            self.symbol, S, self.data_names, self.label_names,
            input_shapes=micro_shapes)
        self._stage_fns = []
        self._stage_args = []
        self._stage_param_names = []
        self._stage_aux_names = []
        feed_set = set(self.data_names) | set(self.label_names)
        for k, ssym in enumerate(self._stage_syms):
            fn, args, auxn = _trace_fn(ssym, is_train=True)
            self._stage_fns.append(fn)
            self._stage_args.append(args)
            self._stage_param_names.append(
                [a for a in args if a not in feed_set
                 and not a.startswith("pipe_in")])
            self._stage_aux_names.append(list(auxn))

        pshapes = _infer_param_shapes(self.symbol, dict(full_shapes))
        # microbatch-sized shape inference for the boundary templates
        param_tpls = []
        for pnames in self._stage_param_names:
            param_tpls.append({n: jax.ShapeDtypeStruct(pshapes[n],
                                                       np.float32)
                               for n in pnames})
        self._param_packers = [_Packer(t) for t in param_tpls]
        self._lp = max(max(p.total for p in self._param_packers), 1)

        state_tpls = []
        for tpl in param_tpls:
            state_tpls.append({
                n: jax.eval_shape(self.optimizer.init_fused_state,
                                  tpl[n])
                for n in tpl})
        self._state_packers = [_Packer(t) for t in state_tpls]
        self._ls = max(max(p.total for p in self._state_packers), 1)

        # per-stage aux states (BatchNorm moving stats) pack into a
        # third stage-sharded buffer; fp32, like the Module aux dicts
        aux_tpls = []
        for auxn in self._stage_aux_names:
            aux_tpls.append({n: jax.ShapeDtypeStruct(pshapes[n],
                                                     np.float32)
                             for n in auxn})
        self._aux_packers = [_Packer(t) for t in aux_tpls]
        self._la = max(max(p.total for p in self._aux_packers), 1)
        self._aux_tpls = aux_tpls

        # chain eval_shape through stages for boundary templates + the
        # canonical (shape-sorted) slot permutation per boundary
        rngspec = jax.ShapeDtypeStruct((2,), np.uint32)
        feed_spec = {k: jax.ShapeDtypeStruct(micro_shapes[k],
                                             micro_dtypes[k])
                     for k in micro_shapes}
        self._boundary_perm = []   # perm[i] = out position of slot i
        carry_tpl = None
        cur = None
        for k, (fn, args) in enumerate(zip(self._stage_fns,
                                           self._stage_args)):
            argspec = {}
            for a in args:
                if a.startswith("pipe_in"):
                    argspec[a] = cur[int(a[7:])]
                elif a in feed_spec:
                    argspec[a] = feed_spec[a]
                else:
                    argspec[a] = param_tpls[k][a]
            outs, _ = jax.eval_shape(
                lambda ar, ax: fn(ar, ax, jax.random.PRNGKey(0)),
                argspec, aux_tpls[k])
            cur = list(outs)
            if k < S - 1:
                order = sorted(
                    range(len(cur)),
                    key=lambda i: (str(cur[i].shape), str(cur[i].dtype),
                                   i))
                tpl = [jax.ShapeDtypeStruct(cur[i].shape, cur[i].dtype)
                       for i in order]
                if carry_tpl is None:
                    carry_tpl = tpl
                elif [(t.shape, t.dtype) for t in tpl] != \
                        [(t.shape, t.dtype) for t in carry_tpl]:
                    raise MXNetError(
                        "pipeline boundaries carry different tensor "
                        "sets (%r vs %r); choose a different n_stages"
                        % (tpl, carry_tpl))
                self._boundary_perm.append(order)
        self._carry_tpl = carry_tpl
        self._out_tpl = cur          # last stage outputs (per micro)
        self._micro_shapes = micro_shapes
        self._mb = mb
        self._full_shapes = full_shapes
        self._built = True
        self._jit_step = self._make_jit()

    # -- the compiled step -----------------------------------------------
    def _make_jit(self):
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec as P

        try:
            shard_map = jax.shard_map
        except AttributeError:  # pragma: no cover - older jax
            from jax.experimental.shard_map import shard_map

        S, M, axis = self.n_stages, self.n_micro, self.axis
        R = 2 * S
        mesh = self.mesh
        carry_tpl = self._carry_tpl
        out_tpl = self._out_tpl
        opt = self.optimizer
        lr_mults = {n: opt.lr_mult.get(n, 1.0) for n in self.param_names}
        wd_mults = {n: opt.wd_mult.get(n, 1.0) for n in self.param_names}
        base_wd = opt.wd
        gscale = self.grad_scale
        perm_f = [(i, (i + 1) % S) for i in range(S)]
        perm_b = [(i, (i - 1) % S) for i in range(S)]

        def zeros_carry():
            return tuple(jnp.zeros(t.shape, t.dtype) for t in carry_tpl)

        def zeros_emit():
            return tuple(jnp.zeros(t.shape, t.dtype) for t in out_tpl)

        la = self._la

        def stage_fwd(k):
            """fwd branch for stage k: (p_row, a_row, carry, feed, key)
            -> (carry_out, emits, new_a_row).  ``key`` is the
            per-microbatch key; the per-stage fold keeps rng streams of
            different stages independent."""
            fn = self._stage_fns[k]
            args_k = self._stage_args[k]
            packer = self._param_packers[k]
            apacker = self._aux_packers[k]
            in_perm = self._boundary_perm[k - 1] if k > 0 else None
            out_perm = self._boundary_perm[k] if k < S - 1 else None

            def branch(p_row, a_row, carry, feed, key):
                params = packer.unpack(p_row[:packer.total])
                aux = apacker.unpack(a_row[:apacker.total])
                ar = {}
                for a in args_k:
                    if a.startswith("pipe_in"):
                        want = int(a[7:])
                        # carry slot holding the boundary's out position
                        slot = in_perm.index(want)
                        ar[a] = carry[slot]
                    elif a in feed:
                        ar[a] = lax.stop_gradient(feed[a])
                    else:
                        ar[a] = params[a]
                outs, new_aux = fn(ar, aux, jax.random.fold_in(key, k))
                outs = list(outs)
                new_a_row = lax.stop_gradient(apacker.pack(new_aux, la))
                if k < S - 1:
                    carry_out = tuple(outs[i] for i in out_perm)
                    return carry_out, zeros_emit(), new_a_row
                return zeros_carry(), tuple(outs), new_a_row

            return branch

        fwd_branches = [stage_fwd(k) for k in range(S)]

        def stage_bwd(k):
            """bwd branch for stage k (recompute + vjp): (p_row, a_row,
            x, feed, g_in, key) -> (g_p_row, g_carry_out).  ``key`` is
            re-derived from the backward tick's microbatch index, so
            the recomputed rng ops (dropout masks) are bit-identical to
            the forward's; training-mode BN reads batch stats only, so
            the recompute is aux-timing independent."""
            branch_f = fwd_branches[k]

            def branch(p_row, a_row, x, feed, g_in, key):
                def f(pr, c):
                    c_out, emits, _na = branch_f(pr, a_row, c, feed, key)
                    return c_out, emits

                (c_out, emits), vjp_fn = jax.vjp(f, p_row, x)
                if k == S - 1:
                    cts = (zeros_carry(),
                           tuple(jnp.ones(t.shape, t.dtype)
                                 for t in out_tpl))
                else:
                    cts = (g_in, zeros_emit())
                g_pr, g_c = vjp_fn(cts)
                return g_pr, g_c

            return branch

        bwd_branches = [stage_bwd(k) for k in range(S)]

        def upd_branch(k):
            ppk = self._param_packers[k]
            spk = self._state_packers[k]
            names = self._stage_param_names[k]

            frozen = self._frozen

            def branch(p_row, s_row, g_row, lr, t, rng):
                params = ppk.unpack(p_row[:ppk.total])
                grads = ppk.unpack(g_row[:ppk.total])
                states = spk.unpack(s_row[:spk.total])
                new_p, new_s = {}, {}
                for i, n in enumerate(names):
                    if n in frozen:
                        new_p[n], new_s[n] = params[n], states[n]
                        continue
                    new_p[n], new_s[n] = opt.fused_update(
                        params[n], grads[n] * gscale, states[n],
                        lr * lr_mults[n], base_wd * wd_mults[n], t,
                        jax.random.fold_in(rng, k * 1000 + i))
                return (ppk.pack(new_p, self._lp),
                        spk.pack(new_s, self._ls))

            return branch

        upd_branches = [upd_branch(k) for k in range(S)]

        def feed_at(micro, m):
            m = jnp.clip(m, 0, M - 1)
            return {k: v[m] for k, v in micro.items()}

        def micro_key(rng, m):
            # per-microbatch key; fwd and bwd recompute derive the SAME
            # key from their own tick indices, keeping dropout masks
            # bit-identical across the 1F1B recompute
            return jax.random.fold_in(rng, jnp.clip(m, 0, M - 1))

        def body_1f1b(pp, ps, pa, micro, rng, lr, t):
            p_row = pp[0]
            s_row = ps[0]
            a_row = pa[0]
            sidx = lax.axis_index(axis)
            ring = tuple(jnp.zeros((R,) + tp.shape, tp.dtype)
                         for tp in carry_tpl)
            outs_buf = tuple(jnp.zeros((M,) + tp.shape, tp.dtype)
                             for tp in out_tpl)
            grad_acc = jnp.zeros_like(p_row)
            carry_f = zeros_carry()
            g_carry = zeros_carry()

            def tick(state, t_idx):
                carry_f, g_carry, ring, grad_acc, outs_buf, a_row = state
                m_f = t_idx - sidx
                valid_f = (m_f >= 0) & (m_f < M)
                feed_f = feed_at(micro, m_f)
                c_out, emits, a_new = lax.switch(
                    sidx, fwd_branches, p_row, a_row, carry_f, feed_f,
                    micro_key(rng, m_f))
                # BN moving stats blend once per VALID microbatch tick
                a_row = jnp.where(valid_f, a_new, a_row)
                slot_f = jnp.mod(m_f, R)
                ring = tuple(
                    lax.dynamic_update_index_in_dim(r, v, slot_f, 0)
                    for r, v in zip(ring, carry_f))
                emit_gate = jnp.where(valid_f & (sidx == S - 1), 1.0, 0.0)
                m_safe = jnp.clip(m_f, 0, M - 1)
                outs_buf = tuple(
                    lax.dynamic_update_index_in_dim(
                        b, jnp.where(emit_gate > 0, v,
                                     lax.dynamic_index_in_dim(
                                         b, m_safe, 0, keepdims=False)),
                        m_safe, 0)
                    for b, v in zip(outs_buf, emits))
                carry_next = tuple(lax.ppermute(v, axis, perm_f)
                                   for v in c_out)

                m_b = t_idx - 2 * (S - 1) + sidx
                valid_b = (m_b >= 0) & (m_b < M)
                slot_b = jnp.mod(m_b, R)
                x_b = tuple(lax.dynamic_index_in_dim(r, slot_b, 0,
                                                     keepdims=False)
                            for r in ring)
                feed_b = feed_at(micro, m_b)
                g_pr, g_c = lax.switch(sidx, bwd_branches, p_row, a_row,
                                       x_b, feed_b, g_carry,
                                       micro_key(rng, m_b))
                grad_acc = grad_acc + jnp.where(valid_b, 1.0, 0.0) * g_pr
                g_next = tuple(lax.ppermute(
                    jnp.where(valid_b, v, jnp.zeros_like(v)), axis,
                    perm_b) for v in g_c)
                return (carry_next, g_next, ring, grad_acc, outs_buf,
                        a_row), None

            ticks = jnp.arange(M + 2 * (S - 1))
            (carry_f, g_carry, ring, grad_acc, outs_buf, a_row), _ = \
                lax.scan(tick, (carry_f, g_carry, ring, grad_acc,
                                outs_buf, a_row), ticks)

            outs_rep = tuple(
                lax.psum(jnp.where(sidx == S - 1, b, jnp.zeros_like(b)),
                         axis) for b in outs_buf)
            new_p_row, new_s_row = lax.switch(
                sidx, upd_branches, p_row, s_row, grad_acc, lr, t, rng)
            return new_p_row[None], new_s_row[None], a_row[None], outs_rep

        def body_gpipe(pp, ps, pa, micro, rng, lr, t):
            # All-forward wave stashing every stage INPUT (M slots — the
            # GPipe memory profile), then an explicit all-backward wave
            # over the stash.  The backward is validity-GATED per tick:
            # differentiating the whole forward scan instead would let
            # the loss heads' custom vjp (which by the reference
            # contract ignores its cotangent) emit junk gradients for
            # the fill/drain ticks.
            p_row = pp[0]
            s_row = ps[0]
            a_row = pa[0]
            sidx = lax.axis_index(axis)
            stash = tuple(jnp.zeros((M,) + tp.shape, tp.dtype)
                          for tp in carry_tpl)
            outs_buf = tuple(jnp.zeros((M,) + tp.shape, tp.dtype)
                             for tp in out_tpl)
            carry_f = zeros_carry()

            def tick_f(state, t_idx):
                carry_f, stash, outs_buf, a_row = state
                m_f = t_idx - sidx
                valid_f = (m_f >= 0) & (m_f < M)
                m_safe = jnp.clip(m_f, 0, M - 1)
                feed_f = feed_at(micro, m_f)
                c_out, emits, a_new = lax.switch(
                    sidx, fwd_branches, p_row, a_row, carry_f,
                    feed_f, micro_key(rng, m_f))
                a_row = jnp.where(valid_f, a_new, a_row)
                stash = tuple(
                    lax.dynamic_update_index_in_dim(b, v, m_safe, 0)
                    for b, v in zip(stash, carry_f))
                emit_gate = valid_f & (sidx == S - 1)
                outs_buf = tuple(
                    lax.dynamic_update_index_in_dim(
                        b, jnp.where(emit_gate, v,
                                     lax.dynamic_index_in_dim(
                                         b, m_safe, 0, keepdims=False)),
                        m_safe, 0)
                    for b, v in zip(outs_buf, emits))
                carry_next = tuple(lax.ppermute(v, axis, perm_f)
                                   for v in c_out)
                return (carry_next, stash, outs_buf, a_row), None

            (carry_f, stash, outs_buf, a_row), _ = lax.scan(
                tick_f, (carry_f, stash, outs_buf, a_row),
                jnp.arange(M + S - 1))

            grad_acc = jnp.zeros_like(p_row)
            g_carry = zeros_carry()

            def tick_b(state, t_idx):
                g_carry, grad_acc = state
                m_b = t_idx - (S - 1 - sidx)
                valid_b = (m_b >= 0) & (m_b < M)
                m_safe = jnp.clip(m_b, 0, M - 1)
                x_b = tuple(lax.dynamic_index_in_dim(b, m_safe, 0,
                                                     keepdims=False)
                            for b in stash)
                feed_b = feed_at(micro, m_b)
                g_pr, g_c = lax.switch(sidx, bwd_branches, p_row, a_row,
                                       x_b, feed_b, g_carry,
                                       micro_key(rng, m_b))
                grad_acc = grad_acc + jnp.where(valid_b, 1.0, 0.0) * g_pr
                g_next = tuple(lax.ppermute(
                    jnp.where(valid_b, v, jnp.zeros_like(v)), axis,
                    perm_b) for v in g_c)
                return (g_next, grad_acc), None

            (g_carry, grad_acc), _ = lax.scan(
                tick_b, (g_carry, grad_acc), jnp.arange(M + S - 1))

            outs_rep = tuple(
                lax.psum(jnp.where(sidx == S - 1, b, jnp.zeros_like(b)),
                         axis) for b in outs_buf)
            new_p_row, new_s_row = lax.switch(
                sidx, upd_branches, p_row, s_row, grad_acc, lr, t, rng)
            return new_p_row[None], new_s_row[None], a_row[None], outs_rep

        body = body_1f1b if self.schedule == "1f1b" else body_gpipe
        pspec = P(axis)
        specs = dict(
            in_specs=(pspec, pspec, pspec, P(), P(), P(), P()),
            out_specs=(pspec, pspec, pspec, P()))
        try:
            fn = shard_map(body, mesh=mesh, check_vma=False, **specs)
        except TypeError:
            fn = shard_map(body, mesh=mesh, check_rep=False, **specs)
        row_sh = NamedSharding(mesh, P(axis))
        repl = NamedSharding(mesh, P())
        return jax.jit(
            fn,
            in_shardings=(row_sh, row_sh, row_sh, repl, repl, repl, repl),
            out_shardings=(row_sh, row_sh, row_sh, repl),
            donate_argnums=(0, 1, 2))

    # -- packing interface -----------------------------------------------
    def pack_params(self, params):
        """{name: array} -> stage-sharded (S, Lp) packed buffer."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows = []
        for k, pk in enumerate(self._param_packers):
            sub = {n: params[n] for n in self._stage_param_names[k]}
            rows.append(pk.pack(sub, self._lp))
        stacked = jnp.stack(rows)
        return jax.device_put(stacked,
                              NamedSharding(self.mesh, P(self.axis)))

    def pack_states(self, states):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows = []
        for k, pk in enumerate(self._state_packers):
            sub = {n: states[n] for n in self._stage_param_names[k]}
            rows.append(pk.pack(sub, self._ls))
        stacked = jnp.stack(rows)
        return jax.device_put(stacked,
                              NamedSharding(self.mesh, P(self.axis)))

    def pack_aux(self, aux):
        """{name: array} aux states -> stage-sharded (S, La) buffer."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        rows = []
        for k, pk in enumerate(self._aux_packers):
            sub = {n: aux[n] for n in self._stage_aux_names[k]}
            rows.append(pk.pack(sub, self._la))
        stacked = jnp.stack(rows)
        return jax.device_put(stacked,
                              NamedSharding(self.mesh, P(self.axis)))

    def unpack_params(self):
        """Gather the live packed parameters back to a {name: array}
        dict (replicated) — the checkpoint/eval sync point."""
        import numpy as np

        out = {}
        if self._packed_params is None:
            return out
        host = np.asarray(self._packed_params)
        for k, pk in enumerate(self._param_packers):
            sub = pk.unpack(host[k][:pk.total])
            out.update(sub)
        return out

    def unpack_states(self):
        import numpy as np

        out = {}
        if self._packed_states is None:
            return out
        host = np.asarray(self._packed_states)
        for k, pk in enumerate(self._state_packers):
            out.update(pk.unpack(host[k][:pk.total]))
        return out

    def unpack_aux(self):
        """Gather the live packed aux states (BN moving stats) back to
        a replicated {name: array} dict."""
        import numpy as np

        out = {}
        if getattr(self, "_packed_aux", None) is None:
            return out
        host = np.asarray(self._packed_aux)
        for k, pk in enumerate(self._aux_packers):
            out.update(pk.unpack(host[k][:pk.total]))
        return out

    # -- call -------------------------------------------------------------
    def __call__(self, params, aux, states, batch, rng, lr=None, t=None):
        import jax.numpy as jnp

        if t is None:
            self._t += 1
            t = self._t
        else:
            self._t = int(t)
        if self._built is None:
            self._build(batch)
        if self._packed_params is None:
            self._packed_params = self.pack_params(params)
            self._packed_states = self.pack_states(states)
            self._packed_aux = self.pack_aux(aux)
        micro = {}
        for k, v in batch.items():
            arr = jnp.asarray(v)
            micro[k] = arr.reshape((self.n_micro, self._mb)
                                   + tuple(arr.shape[1:]))
        (self._packed_params, self._packed_states, self._packed_aux,
         outs) = self._jit_step(
            self._packed_params, self._packed_states, self._packed_aux,
            micro, rng,
            jnp.asarray(self.lr if lr is None else lr, "float32"),
            jnp.asarray(t, "int32"))
        # un-microbatch the outputs: (M, mb, ...) -> (N, ...)
        flat_outs = tuple(
            o.reshape((o.shape[0] * o.shape[1],) + tuple(o.shape[2:]))
        if o.ndim >= 2 else o for o in outs)
        return params, aux, states, flat_outs

    def init_state(self, shapes, dtype="float32", seed=0):
        """Allocate params/aux/states directly (bench convenience;
        Module initializes through its own initializer path).  Returns
        ``(params, aux, states)`` — the same triple as
        ``fused.TrainStep.init_state``."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ..symbol.symbol import _infer_param_shapes

        all_shapes = _infer_param_shapes(self.symbol, dict(shapes))
        key = jax.random.PRNGKey(seed)
        params, states = {}, {}
        for n in self.param_names:
            shp = all_shapes[n]
            key, sub = jax.random.split(key)
            if n.endswith("_gamma"):
                params[n] = jnp.ones(shp, dtype)
            elif n.endswith(("_bias", "_beta")):
                params[n] = jnp.zeros(shp, dtype)
            else:
                fan_in = int(np.prod(shp[1:])) if len(shp) > 1 else shp[0]
                scale = (2.0 / max(1, fan_in)) ** 0.5
                params[n] = scale * jax.random.normal(sub, shp, dtype)
            states[n] = self.optimizer.init_fused_state(params[n])
        aux = {}
        for n in self.aux_names:
            shp = all_shapes[n]
            aux[n] = jnp.ones(shp, "float32") if n.endswith("_var") \
                else jnp.zeros(shp, "float32")
        return params, aux, states
