"""Unified parallel plan: TP x PP x DP/ZeRO composed as ONE sharding pass.

The reference framework had exactly one parallelism (device lists +
``kvstore``); this repo grew the modern dimensions one at a time —
Megatron tensor parallelism (``sharding.py``), ring sequence parallelism
(``sequence.py``), pipeline schedules (``pipeline.py``), bucketed DDP
overlap (``overlap.py``) and ZeRO-1/3 weight-update sharding
(``zero.py``, arXiv 2004.13336).  Until now they were mutually exclusive
islands: ``zero_axis`` declined on tp/fsdp layouts and the pipeline
composed with neither.

:class:`ParallelPlan` is the single composition point.  It owns the mesh
axis sizes (data, model, pipe, seq) and assigns every parameter,
optimizer-state leaf, gradient and activation a placement exactly once:

* **model** — Megatron column/row specs (``tp_rules_for_transformer``:
  FullyConnected stacks, attention QKV/O head sharding) on the canonical
  parameter shapes.
* **data** — ZeRO flat tiles taken over the data axis *within* each
  model group (``zero.plan_layout``): a TP-sharded parameter's at-rest
  ZeRO-3 tile is a shard-major flat array laid out ``P((model, data))``
  so the forward gather is an all-gather over the data axis scoped to
  the model group — never a global collective.
* **pipe** — stage assignment via ``split_symbol``/``PipelineTrainStep``
  (``fused.TrainStep`` refuses pipe plans and points there).
* **seq** — the ring-attention axis; the batch/heads dims of the ring
  shard_map compose with the data/model axes (``sequence.py``).

``fused.TrainStep(symbol, plan=...)`` is the composed entry point: the
plan replaces the per-dimension ``mesh``/``param_sharding``/``zero``
kwargs.  Everything stays ONE jitted XLA program (arXiv 2301.13062
discipline): health/loss-scale/clip-global-norm are ordinary jnp
reductions over sharded arrays, which GSPMD lowers to partial norms plus
a scalar psum across all axes — exact by construction.
"""
from __future__ import annotations

import json
import re

from ..base import MXNetError
from .mesh import AXIS_ORDER, create_mesh

__all__ = ["ParallelPlan", "tp_rules_for_transformer"]

_ZERO_MODES = {"off": "off", "0": "off", "1": "on", "on": "on",
               "3": "3", "auto": "auto"}


def tp_rules_for_transformer():
    """Megatron tensor-parallel rules for the transformer family on top
    of the MLP pairing: attention QKV projection column-parallel (head
    sharding — the fused (3C, C) in_weight splits its output dim over
    'model', so each group member computes its heads' Q/K/V locally),
    output projection row-parallel (the once-per-block all-reduce), and
    the FFN pair column-then-row.  Embeddings / LayerNorm / biases of
    row-parallel layers stay replicated; ZeRO tiles (``zero.plan_layout``)
    shard those over the data axis within each model group instead."""
    from .sharding import tp_rules_for_mlp

    return [
        (re.compile(r".*_attn_in_weight$"), ("model", None)),
        (re.compile(r".*_attn_in_bias$"), ("model",)),
        (re.compile(r".*_attn_out_weight$"), (None, "model")),
        (re.compile(r".*_ffn1_weight$"), ("model", None)),
        (re.compile(r".*_ffn1_bias$"), ("model",)),
        (re.compile(r".*_ffn2_weight$"), (None, "model")),
    ] + tp_rules_for_mlp()


class ParallelPlan:
    """One declaration of how a training run spreads over the mesh.

    ``data``/``model``/``pipe``/``seq`` are mesh axis sizes (1 = axis
    unused); ``zero`` is the weight-update sharding mode over the data
    axis within each (model, pipe) group: ``None`` defers to MXNET_ZERO,
    ``"off"``/``"on"``/``"3"`` force it (``"1"`` is accepted as an alias
    of ``"on"``).  ``data=-1`` absorbs whatever devices the other axes
    leave (``create_mesh`` wildcard).
    """

    __slots__ = ("data", "model", "pipe", "seq", "zero", "schedule",
                 "n_microbatches")

    def __init__(self, data=-1, model=1, pipe=1, seq=1, zero=None,
                 schedule="1f1b", n_microbatches=None):
        self.data = int(data)
        self.model = int(model)
        self.pipe = int(pipe)
        self.seq = int(seq)
        for ax in ("model", "pipe", "seq"):
            if getattr(self, ax) < 1:
                raise MXNetError("ParallelPlan %s size must be >= 1, got "
                                 "%d" % (ax, getattr(self, ax)))
        if self.data < 1 and self.data != -1:
            raise MXNetError("ParallelPlan data size must be >= 1 or the "
                             "-1 wildcard, got %d" % self.data)
        if zero is not None:
            zero = str(zero).lower()
            if zero not in _ZERO_MODES:
                raise MXNetError("ParallelPlan zero must be one of %s, "
                                 "got %r" % (sorted(set(_ZERO_MODES)),
                                             zero))
            zero = _ZERO_MODES[zero]
        self.zero = zero
        if schedule not in ("1f1b", "gpipe"):
            raise MXNetError("ParallelPlan schedule must be '1f1b' or "
                             "'gpipe', got %r" % (schedule,))
        self.schedule = schedule
        self.n_microbatches = (None if n_microbatches is None
                               else int(n_microbatches))

    # -- construction -----------------------------------------------------
    @classmethod
    def parse(cls, spec):
        """Parse ``"data=4,model=2,zero=3"`` (the MXNET_PLAN / CLI
        surface).  Keys: data, model, pipe, seq, zero, schedule,
        microbatches."""
        if isinstance(spec, ParallelPlan):
            return spec
        kwargs = {}
        for tok in str(spec).replace(";", ",").split(","):
            tok = tok.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise MXNetError("bad plan token %r in %r (want key=value)"
                                 % (tok, spec))
            key, val = (t.strip() for t in tok.split("=", 1))
            if key in ("data", "model", "pipe", "seq"):
                kwargs[key] = cls._int(key, val, spec)
            elif key == "zero":
                kwargs["zero"] = val
            elif key == "schedule":
                kwargs["schedule"] = val
            elif key in ("microbatches", "n_microbatches"):
                kwargs["n_microbatches"] = cls._int(key, val, spec)
            else:
                raise MXNetError("unknown plan key %r in %r" % (key, spec))
        return cls(**kwargs)

    @classmethod
    def from_describe(cls, d):
        """Rebuild a plan from its :meth:`describe` identity dict —
        checkpoint manifests, migration-event artifacts, and scale-event
        files all record plans in that form, and the elastic control
        loop needs them back as live objects."""
        if d is None:
            return None
        if isinstance(d, ParallelPlan):
            return d
        kwargs = {k: d[k] for k in ("data", "model", "pipe", "seq",
                                    "zero", "schedule", "n_microbatches")
                  if d.get(k) is not None}
        return cls(**kwargs)

    @staticmethod
    def _int(key, val, spec):
        try:
            return int(val)
        except ValueError:
            raise MXNetError("plan key %r wants an integer, got %r in %r"
                             % (key, val, spec)) from None

    # -- mesh -------------------------------------------------------------
    def axes(self):
        """Mesh axis sizes in canonical ``AXIS_ORDER``, size-1 axes
        dropped (a trivial axis is pure noise in every PartitionSpec) —
        except 'data', which is always present so the batch has a home
        even on a 1-way mesh."""
        sizes = {"data": self.data, "seq": self.seq, "pipe": self.pipe,
                 "model": self.model}
        return {ax: sizes[ax] for ax in AXIS_ORDER if ax in sizes
                and (sizes[ax] != 1 or ax == "data")}

    def mesh(self, devices=None):
        """Build the plan's mesh over ``devices`` (default: the first
        ``prod(axes)`` local devices — a ``data=2,model=2`` plan on an
        8-device host deliberately uses 4; elastic restores depend on
        a plan meaning the SAME topology on any host big enough)."""
        if devices is None and self.data != -1:
            import jax

            want = 1
            for n in self.axes().values():
                want *= n
            have = jax.devices()
            if want < len(have):
                devices = have[:want]
        return create_mesh(self.axes(), devices)

    def validate_mesh(self, mesh):
        """Check an externally-built mesh carries the plan's axes at the
        plan's sizes (the -1 data wildcard matches any size)."""
        shape = dict(mesh.shape)
        for ax, n in self.axes().items():
            have = int(shape.get(ax, 1))
            if ax == "data" and n == -1:
                continue
            if have != n:
                raise MXNetError(
                    "mesh axis %r is %d-way but the plan wants %d "
                    "(plan %s, mesh %s)" % (ax, have, n,
                                            self.describe(), dict(shape)))

    def model_size(self, mesh=None):
        if mesh is not None:
            return int(dict(mesh.shape).get("model", 1))
        return self.model

    # -- identity ---------------------------------------------------------
    def describe(self):
        """JSON-able identity dict (checkpoint manifests, bench rows)."""
        out = {"data": self.data, "model": self.model, "pipe": self.pipe,
               "seq": self.seq, "zero": self.zero}
        if self.pipe > 1:
            out["schedule"] = self.schedule
            if self.n_microbatches:
                out["n_microbatches"] = self.n_microbatches
        return out

    def fingerprint(self, mesh=None):
        """Stable slug keying autotune records and audit artifacts:
        tuned knobs for a tp x zero3 plan must not leak onto pure-DP
        runs of the same symbol.  Pass the resolved mesh so the ``-1``
        data wildcard fingerprints as its concrete size."""
        data = self.data
        if mesh is not None and data == -1:
            data = int(dict(mesh.shape).get("data", data))
        parts = ["%s%d" % (ax, n) for ax, n in
                 (("data", data), ("model", self.model),
                  ("pipe", self.pipe), ("seq", self.seq)) if n != 1]
        if not parts:
            parts = ["data%d" % data]
        if self.zero is not None:
            parts.append("z%s" % self.zero)
        return "-".join(parts)

    def __repr__(self):
        return "ParallelPlan(%s)" % json.dumps(self.describe(),
                                               sort_keys=True)

    def __eq__(self, other):
        return isinstance(other, ParallelPlan) and \
            self.describe() == other.describe()

    def __hash__(self):
        return hash(json.dumps(self.describe(), sort_keys=True))

    # -- parameter placement ----------------------------------------------
    def tp_rules(self):
        """Pattern -> PartitionSpec rules for the model axis."""
        return tp_rules_for_transformer()

    def param_spec(self, name, shape, mesh=None):
        """The canonical-shape PartitionSpec TUPLE for one parameter
        under this plan's model axis, with the divisibility fallback of
        ``sharding_from_spec``: a dim the model size does not divide
        replicates on that dim instead of erroring.  Pure-DP plans (and
        seq>1 plans, where the ring owns the attention layout) return
        the empty spec for everything."""
        model_n = self.model
        if model_n <= 1 or self.seq > 1:
            return ()
        if mesh is not None:
            model_n = self.model_size(mesh)
            if model_n <= 1:
                return ()
        spec = ()
        for pat, s in self.tp_rules():
            if pat.match(name):
                spec = s
                break
        out = []
        for i, entry in enumerate(tuple(spec)[:len(shape)]):
            if entry == "model" and int(shape[i]) % model_n == 0:
                out.append("model")
            else:
                out.append(None)
        while out and out[-1] is None:
            out.pop()
        return tuple(out)

    def param_specs(self, params, mesh=None):
        """{name: PartitionSpec tuple} over a {name: array-like} dict."""
        return {name: self.param_spec(name, tuple(arr.shape), mesh)
                for name, arr in params.items()}

    def param_shardings(self, mesh, params):
        """{name: NamedSharding} for the canonical (full-shape)
        parameters — what a zero-off plan jit uses as in/out shardings,
        and what ``zero.gather_param`` re-lays a gathered TP param onto."""
        from .sharding import named_sharding

        return {name: named_sharding(mesh, *self.param_spec(
                    name, tuple(arr.shape), mesh))
                for name, arr in params.items()}
