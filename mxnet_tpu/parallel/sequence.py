"""Sequence/context parallelism — ring attention over the 'seq' mesh axis.

The reference has nothing to port here (2017: bucketing + truncated BPTT
were its long-sequence story, SURVEY.md §5 "Long-context"); this is the
fresh TPU-first design the blueprint calls for: shard the SEQUENCE axis
of Q/K/V over the mesh's 'seq' axis, and rotate K/V blocks around the
ring with ``lax.ppermute`` while each device accumulates its queries'
attention in flash-attention style (running max + running sum), so the
full T×T score matrix never materializes and each hop's communication
is scheduled so it CAN overlap the current block's compute (Liu et al.,
Ring Attention, 2023 — public technique; the overlap itself is a
pending-real-ICI measurement, see ``ring_attention``'s docstring).

Two entry points:

* :func:`ring_attention` — inside ``shard_map``/``pjit`` code: takes the
  LOCAL (per-device) Q/K/V chunks and an axis name.
* :func:`sequence_parallel_attention` — whole-array convenience: shards
  (B, H, T, D) tensors over the active mesh's 'seq' axis via shard_map
  and runs :func:`ring_attention`.

Causal masking is supported: block positions are recovered from the ring
hop index, so masking stays exact under rotation.
"""
from __future__ import annotations

import functools

from ..base import MXNetError
from ..compile_cache import track_lru
from .mesh import current_mesh

__all__ = ["ring_attention", "sequence_parallel_attention"]


def _online_softmax_merge(acc, m, l, scores, v):
    """Back-compat alias: the flash accumulation step now lives in
    ``ops/attention.py`` (shared with the single-chip blockwise
    kernel)."""
    from ..ops.attention import online_block_merge

    return online_block_merge(acc, m, l, scores, v)


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Ring attention on LOCAL chunks inside shard_map.

    q/k/v: (..., T_local, D) — leading dims (batch, heads) are free; the
    sequence axis is sharded over ``axis_name``.  Each of the
    ``axis_size`` hops computes one (T_local x T_local) score block and
    rotates K/V to the next neighbor over ICI (``ppermute``), so peak
    memory is O(T_local^2 / ring) per device.  Design intent (pending
    real-ICI measurement — this environment has one chip): the hop
    structure gives XLA's scheduler independent send/compute chains so
    the transfer of hop i+1 CAN overlap the matmul of hop i; the
    measurement to run on a pod is a profiler trace of one layer at
    T_local >= 1024 checking ppermute slots hide under the score
    matmuls (docs/distributed.md "pending hardware" list).
    """
    import jax.numpy as jnp
    from jax import lax

    from ..ops.attention import attend_block, finalize_attention

    # psum of a constant folds to the static axis size on every jax
    # version; lax.axis_size only exists on newer releases
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    t_local = q.shape[-2]
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    perm = [(i, (i + 1) % n) for i in range(n)]

    acc0 = jnp.zeros(q.shape[:-1] + (v.shape[-1],), jnp.float32)
    m0 = jnp.full(q.shape[:-1] + (1,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)

    q32 = q.astype(jnp.float32) * scale
    # global positions of this device's queries (causal masking)
    q_pos = rank * t_local + jnp.arange(t_local)

    def hop(i, state):
        acc, m, l, kk, vv = state
        # the K/V block now resident came from rank - i (ring rotation):
        # one visit of the shared blockwise kernel per hop
        src = (rank - i) % n
        k_pos = src * t_local + jnp.arange(t_local)
        acc, m, l = attend_block(q32, kk, vv, acc, m, l, q_pos=q_pos,
                                 k_pos=k_pos, causal=causal)
        kk = lax.ppermute(kk, axis_name, perm)
        vv = lax.ppermute(vv, axis_name, perm)
        return acc, m, l, kk, vv

    state = (acc0, m0, l0, k, v)
    for i in range(n):  # static unroll: n is a mesh constant
        state = hop(i, state)
    acc, m, l, _, _ = state
    return finalize_attention(acc, l).astype(q.dtype)


def sequence_parallel_attention(q, k, v, causal=False, mesh=None,
                                axis="seq"):
    """Whole-array sequence-parallel attention.

    q/k/v: (B, H, T, D) with T divisible by the mesh's ``axis`` size.
    Shards T over the mesh and runs :func:`ring_attention` under
    ``shard_map``.  The batch and heads dims COMPOSE with the other plan
    axes: B additionally shards over the data (and fsdp) axes and H over
    the 'model' axis whenever the sizes divide — attention is
    independent across batch and heads, so the ring stays the only
    cross-device exchange and each (data, model) group runs its own.
    """
    mesh = mesh or current_mesh()
    if mesh is None or axis not in mesh.shape:
        raise MXNetError(
            "sequence_parallel_attention needs a mesh with a %r axis "
            "(create one with parallel.create_mesh)" % axis)
    t = q.shape[-2]
    n = mesh.shape[axis]
    if t % n != 0:
        raise MXNetError("sequence length %d not divisible by %s=%d"
                         % (t, axis, n))
    shape = dict(mesh.shape)
    batch_axes = tuple(ax for ax in ("data", "fsdp")
                       if int(shape.get(ax, 1)) > 1
                       and int(q.shape[0]) % int(shape[ax]) == 0)
    heads_axis = ("model" if int(shape.get("model", 1)) > 1
                  and int(q.shape[1]) % int(shape["model"]) == 0
                  else None)
    return _sp_attention_fn(mesh, axis, causal, batch_axes,
                            heads_axis)(q, k, v)


@track_lru("parallel._sp_attention_fn")
@functools.lru_cache(maxsize=32)
def _sp_attention_fn(mesh, axis, causal, batch_axes=(), heads_axis=None):
    """Cached jitted shard_map program per (mesh, axis, causal,
    batch/heads placement): jit caches by function identity, so
    rebuilding per call would re-trace and recompile every step."""
    import jax
    from jax.sharding import PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(batch_axes or None, heads_axis, axis, None)
    body = functools.partial(ring_attention, axis_name=axis,
                             causal=causal)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # older jax spells the flag check_rep
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return jax.jit(fn)
