"""Sharding rules: how arrays lay out over the mesh.

Replaces the reference's placement machinery — ``group2ctx`` attrs +
``PlaceDevice`` pass + ``_CrossDeviceCopy`` nodes
(``src/executor/graph_executor.cc:395``) — with named shardings: a
parameter/activation is annotated with mesh axes and XLA inserts the
transfers/collectives.  Also implements what the reference never had:
tensor-parallel weight sharding and ZeRO/FSDP-style parameter sharding.
"""
from __future__ import annotations

import re

from ..base import MXNetError
from .mesh import current_mesh

__all__ = ["named_sharding", "replicated", "shard_batch", "constraint",
           "param_sharding_rules", "apply_rules", "tp_rules_for_mlp",
           "sharding_from_spec"]


def named_sharding(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def batch_axes(mesh, axis="data"):
    """Mesh axes the batch dimension shards over: the data axis (plus
    'fsdp' when present), or empty for meshes with no batch axis (pure
    seq/expert/pipe parallelism — the batch replicates and the mesh
    axes are consumed inside the ops).  Single source of truth for
    ``shard_batch`` and the fused step's in_shardings."""
    return tuple(a for a in (axis, "fsdp") if a in mesh.shape)


def shard_batch(mesh, x, axis="data", leading=0):
    """Device-put a host batch sharded along the batch dimension over the
    mesh's batch axes (the input side of data parallelism).

    ``leading``: number of unsharded leading dims before the batch dim —
    a packed super-batch (``steps_per_call=K`` → shape ``(K, batch, …)``)
    passes ``leading=1`` so the *second* dim shards."""
    import jax

    names = batch_axes(mesh, axis)
    if not names:
        return jax.device_put(x, replicated(mesh))
    spec = [None] * leading + [names]
    return jax.device_put(x, named_sharding(mesh, *spec))


def constraint(x, *spec):
    """In-jit sharding constraint (the ``group2ctx`` annotation of this
    framework: tell XLA where an intermediate lives, it inserts the
    collectives)."""
    import jax

    mesh = current_mesh()
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, named_sharding(mesh, *spec))


def param_sharding_rules(style="replicated"):
    """Pattern → PartitionSpec rule list for parameter dicts.

    styles:
      * ``replicated`` — pure DP: every param on every chip.
      * ``fsdp``       — ZeRO-3-ish: every param sharded on its largest
                         dim over the 'fsdp' (or 'data') axis.
      * ``tp``         — tensor parallelism for FullyConnected stacks:
                         alternate column/row sharding over 'model'.
    """
    if style == "replicated":
        return [(re.compile(".*"), ())]
    if style == "fsdp":
        return [(re.compile(".*"), ("__largest__",))]
    if style == "tp":
        return tp_rules_for_mlp()
    raise MXNetError("unknown sharding style %r" % style)


def tp_rules_for_mlp():
    """Megatron-style pairing: odd layers column-parallel (output dim on
    'model'), even layers row-parallel (input dim on 'model') so the
    all-reduce happens once per pair."""
    return [
        (re.compile(r".*(fc|dense)\d*[02468]_weight$"), ("model", None)),
        (re.compile(r".*(fc|dense)\d*[13579]_weight$"), (None, "model")),
        (re.compile(r".*_weight$"), ()),
        (re.compile(r".*"), ()),
    ]


def apply_rules(mesh, params, rules):
    """Map {name: array-like} -> {name: NamedSharding} via first-match
    rules.  '__largest__' shards the biggest dimension over 'fsdp' (or
    'data') — the ZeRO-style layout."""
    from jax.sharding import NamedSharding, PartitionSpec

    axis = "fsdp" if "fsdp" in mesh.shape else "data"
    out = {}
    for name, arr in params.items():
        shape = tuple(arr.shape)
        spec = ()
        for pat, s in rules:
            if pat.match(name):
                spec = s
                break
        if spec == ("__largest__",):
            if not shape:
                spec = ()
            else:
                big = max(range(len(shape)), key=lambda i: shape[i])
                lst = [None] * len(shape)
                if shape[big] % mesh.shape[axis] == 0:
                    lst[big] = axis
                spec = tuple(lst)
        out[name] = NamedSharding(mesh, PartitionSpec(*spec))
    return out


def sharding_from_spec(mesh, shape, spec):
    """NamedSharding for ``shape`` on the CURRENT mesh from a serialized
    PartitionSpec saved by a possibly-different topology (list entries:
    None, an axis name, or a list of axis names).

    The elastic-restore primitive: axes the current mesh does not have
    are dropped, and a dimension whose size no longer divides the
    surviving axes' extent falls back to replicated on that dim — so a
    checkpoint from an 8-chip fsdp mesh loads onto 4 chips (resharded)
    or 1 chip (fully replicated) without caller involvement."""
    from jax.sharding import NamedSharding, PartitionSpec

    out = []
    for i, entry in enumerate(tuple(spec or ())[:len(shape)]):
        if entry is None:
            out.append(None)
            continue
        names = [entry] if isinstance(entry, str) else list(entry)
        names = [n for n in names if n in mesh.shape]
        extent = 1
        for n in names:
            extent *= int(mesh.shape[n])
        if not names or extent <= 1 or int(shape[i]) % extent != 0:
            out.append(None)
        else:
            out.append(names[0] if len(names) == 1 else tuple(names))
    return NamedSharding(mesh, PartitionSpec(*out))
