"""ZeRO-style cross-replica sharding of optimizer state and the update.

Data-parallel replicas each hold the full parameters plus the full
optimizer state, and every replica redundantly computes the identical
weight update.  Following "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv 2004.13336), this module
partitions each parameter's *flattened* update evenly across the data
axis: gradients arrive reduce-SCATTERED instead of all-reduced, the
optimizer state exists only for the local 1/N tile, the update runs on
that tile, and the fresh parameters are all-gathered for the next
forward — cutting optimizer-state memory and update FLOPs per replica
by ~1/N at the cost of one all-gather that XLA's latency-hiding
scheduler overlaps with the next step's compute.

Layout contract: a sharded parameter's gradient, weight, and every
weight-shaped optimizer-state leaf are carried as 1-D arrays of
``padded = ceil(size / N) * N`` elements (zero-padded), sharded
``PartitionSpec(axis)`` over the data axis — even byte tiling, the same
stance as :func:`~mxnet_tpu.parallel.overlap.bucket_partition`.  Scalar
state leaves (e.g. Nadam's schedule product) stay replicated.  Padding
lanes hold zeros on entry and whatever the update writes is discarded
at the gather, so the elementwise update math is bit-identical to the
unsharded step.

Two execution paths compose:

* the PR 6 explicit-DDP path swaps each bucket's tuple ``psum`` for a
  tuple ``psum_scatter`` over the sharded members (see
  ``overlap.ddp_value_and_grad(zero_layout=...)``);
* the GSPMD fallback expresses the same thing as sharding constraints
  (flat grad → ``P(axis)``, updated flat param → replicated), and XLA
  inserts the reduce-scatter / all-gather.

Eligibility (``MXNET_ZERO=auto|on|off``): a live mesh whose data axis
has >= 2 devices and replicated parameters.  Model-parallel or fsdp
parameter sharding declines (those layouts already shard state), as do
parameters smaller than ``MXNET_ZERO_MIN_PARAM_BYTES`` (the all-gather
latency is not worth 1/N of a tiny buffer).

``MXNET_ZERO=3`` extends the stance to the parameters themselves
(ZeRO-3): sharded params live *at rest* as the same flat 1/N tiles the
optimizer state already uses, and the fused step gathers them back
layer-bucket by layer-bucket (``MXNET_ZERO_GATHER_BUCKET_MB``, the
:func:`~mxnet_tpu.parallel.overlap.bucket_partition` grouping in
forward order) just ahead of the compute that consumes them.  The
gathered copies are tagged for rematerialization, so backward re-issues
the bucket gathers in reverse order instead of keeping every full
parameter alive as a residual — live full-param memory is O(max
bucket), not O(model) — and the update runs directly on the tiles with
no trailing full all-gather (the next step gathers on demand).  The
gather is ``lax.all_gather(tiled=True)`` on the explicit-DDP path
(whose transpose IS the reduce-scatter, landing each grad already
tiled) and a sharding constraint under GSPMD; both are bit-exact vs the
replicated step for the same reason the stage-1 tiling is.
"""
from __future__ import annotations

import math

from ..base import MXNetError, get_env

__all__ = ["zero_mode", "min_param_bytes", "zero_axis", "ZeroParam",
           "layout", "plan_layout", "put", "shard_flat", "gather_param",
           "gather_bucket", "flat_sharding",
           "init_state", "pack_params", "unpack_param", "unpack_params",
           "shard_state", "unshard_state", "state_structure",
           "state_leaves", "state_unflatten", "export_states",
           "export_params", "tp_meta", "unflatten_tiles",
           "bounded_dispatch", "state_bytes_per_replica",
           "params_bytes_per_replica", "update_gather_bytes",
           "zero3_gather_bytes", "gather_bucket_bytes"]

DEFAULT_MIN_PARAM_BYTES = 1024
DEFAULT_GATHER_BUCKET_MB = 4.0


def zero_mode(mode=None):
    """Resolve the sharded-update mode: an explicit ``mode`` wins, else
    ``MXNET_ZERO`` (default ``auto``).  ``"3"`` selects ZeRO-3 (params
    sharded at rest on top of the stage-1 sharded update)."""
    raw = mode if mode is not None else get_env("MXNET_ZERO", "auto", str)
    raw = str(raw).strip().lower() or "auto"
    if raw in ("0", "off", "false", "no"):
        return "off"
    if raw in ("1", "on", "true", "yes"):
        return "on"
    if raw in ("3", "zero3", "z3"):
        return "3"
    if raw == "auto":
        return "auto"
    raise MXNetError("MXNET_ZERO/zero must be auto|on|off|3 (got %r)"
                     % (mode,))


def min_param_bytes():
    """``MXNET_ZERO_MIN_PARAM_BYTES``: parameters below this size keep
    the replicated update (default %d)."""
    return max(0, int(get_env("MXNET_ZERO_MIN_PARAM_BYTES",
                              DEFAULT_MIN_PARAM_BYTES, int)))


min_param_bytes.__doc__ %= DEFAULT_MIN_PARAM_BYTES


def gather_bucket_bytes():
    """``MXNET_ZERO_GATHER_BUCKET_MB``: target bytes per ZeRO-3 forward
    param-gather bucket (default %s MB).  Smaller buckets start the
    first layer's compute sooner and cap live gathered-param memory;
    larger ones amortize collective launch overhead."""
    mb = get_env("MXNET_ZERO_GATHER_BUCKET_MB", DEFAULT_GATHER_BUCKET_MB,
                 float)
    return max(1, int(mb * 1024 * 1024))


gather_bucket_bytes.__doc__ %= DEFAULT_GATHER_BUCKET_MB


def _blocking_param(mesh, style, param_names):
    """First parameter an explicit ``param_sharding`` style actually
    shards on THIS mesh, as ``(name, spec_tuple)`` — or None when every
    resolved spec is trivial (all named axes absent or size 1), which
    makes the layout effectively pure DP.  Feeds the decline message so
    it names the specific blocking placement instead of the generic
    fsdp/tp sentence."""
    shape = dict(getattr(mesh, "shape", {}) or {})

    def _nontrivial(axes):
        return any(int(shape.get(a, 1)) > 1 for a in axes)

    try:
        from .sharding import param_sharding_rules

        rules = (param_sharding_rules(style) if isinstance(style, str)
                 else list(style))
    except (MXNetError, TypeError, ValueError):
        # diagnostics-only helper: an unparseable style still deserves
        # a decline message, just without the per-param attribution
        return ("<params>", (str(style),))
    for name in param_names or ():
        spec = ()
        for pat, s in rules:
            if pat.match(name):
                spec = s
                break
        for entry in spec:
            if entry is None:
                continue
            if entry == "__largest__":
                axes = ["fsdp" if "fsdp" in shape else "data"]
            else:
                axes = [entry] if isinstance(entry, str) else list(entry)
            if _nontrivial(axes):
                return (name, tuple(spec))
    if not param_names:
        # no names to resolve against: only the known styles can be
        # cleared without them
        if style == "tp" and not _nontrivial(["model"]):
            return None
        if style == "replicated":
            return None
        return ("<params>", (str(style),))
    return None


def zero_axis(mesh, batch_axis, param_sharding=None, mode=None,
              warn=None, param_names=()):
    """The mesh axis the sharded update tiles over, or None (declined).

    ``warn``: optional ``warn(key, msg)`` callable (the per-TrainStep
    decline reporter) — called only when the user forced ``on`` and the
    step cannot honor it.  ``param_names``: the step's parameter names,
    so a decline over an explicit ``param_sharding`` can name the
    specific blocking parameter and its PartitionSpec.  A style whose
    every resolved spec is trivial on this mesh (e.g. ``"tp"`` with a
    size-1 or absent model axis) is pure DP and just runs — no decline,
    no warning.  Composed tp x zero layouts go through
    :class:`~mxnet_tpu.parallel.plan.ParallelPlan` /
    :func:`plan_layout` instead of this gate."""
    mode = zero_mode(mode)
    if mode == "off":
        return None

    def _decline(key, msg):
        if mode in ("on", "3") and warn is not None:
            warn(key, msg)
        return None

    if param_sharding not in (None, "replicated"):
        blocking = _blocking_param(mesh, param_sharding, param_names)
        if blocking is not None:
            name, spec = blocking
            return _decline(
                "zero-params",
                "MXNET_ZERO=%s but param_sharding=%r places %s as "
                "PartitionSpec%r — that layout carries its own state "
                "sharding, and double-tiling it over the data axis "
                "would corrupt the update; using the replicated update "
                "(compose the two with a ParallelPlan: "
                "TrainStep(..., plan=ParallelPlan(model=..., zero=...)))"
                % (mode, param_sharding, name, tuple(spec)))
        # every spec is trivial on this mesh: effectively pure DP
    if mesh is None or batch_axis not in getattr(mesh, "shape", {}):
        return _decline(
            "zero-mesh",
            "MXNET_ZERO=%s but there is no mesh with a %r axis; using "
            "the replicated update" % (mode, batch_axis))
    if int(mesh.shape[batch_axis]) < 2:
        return _decline(
            "zero-axis",
            "MXNET_ZERO=%s but mesh axis %r has a single device — "
            "nothing to shard the update over" % (mode, batch_axis))
    return batch_axis


class ZeroParam:
    """Per-parameter tiling decision: ``sharded`` params carry their
    grad/weight/state as flat ``(padded,)`` arrays tiled over the data
    axis; unsharded ones keep the replicated update.

    Under a composed plan (:func:`plan_layout`) a tensor-parallel
    parameter additionally records its model-axis split: ``model_n``
    group count, the canonical dim ``tp_dim`` the model axis shards, and
    ``shard_padded`` — the per-group flat tile length.  Its flat layout
    is SHARD-MAJOR with per-shard padding
    (``padded = model_n * shard_padded``), laid out
    ``P((model, data))`` so group ``m``'s tile occupies one contiguous
    run and the forward gather is an all-gather over the data axis
    scoped to the model group."""

    __slots__ = ("name", "shape", "dtype", "logical", "padded", "sharded",
                 "tp_dim", "model_axis", "model_n", "shard_padded")

    def __init__(self, name, shape, dtype, logical, padded, sharded,
                 tp_dim=None, model_axis=None, model_n=1,
                 shard_padded=None):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.dtype = dtype
        self.logical = int(logical)
        self.padded = int(padded)
        self.sharded = bool(sharded)
        self.tp_dim = None if tp_dim is None else int(tp_dim)
        self.model_axis = model_axis
        self.model_n = int(model_n)
        self.shard_padded = int(self.padded if shard_padded is None
                                else shard_padded)

    @property
    def tp(self):
        return self.model_n > 1

    def __repr__(self):
        tp = ("" if not self.tp else ", tp_dim=%d, model_n=%d"
              % (self.tp_dim, self.model_n))
        return ("ZeroParam(%s, shape=%r, logical=%d, padded=%d, "
                "sharded=%r%s)" % (self.name, self.shape, self.logical,
                                   self.padded, self.sharded, tp))


def layout(params, ndev, min_bytes=None, frozen=frozenset()):
    """{name: :class:`ZeroParam`} for a params dict of arrays or
    ``ShapeDtypeStruct``s.  Deterministic in shapes/dtypes only, so the
    trace-time callers and the state-allocation callers always agree."""
    import numpy as np

    if min_bytes is None:
        min_bytes = min_param_bytes()
    ndev = int(ndev)
    out = {}
    for name, arr in params.items():
        shape = tuple(int(s) for s in arr.shape)
        dtype = np.dtype(arr.dtype)
        logical = int(math.prod(shape)) if shape else 1
        padded = max(1, -(-logical // ndev)) * ndev
        sharded = (name not in frozen and ndev > 1
                   and logical * dtype.itemsize >= min_bytes)
        out[name] = ZeroParam(name, shape, dtype, logical, padded, sharded)
    return out


def plan_layout(params, mesh, axis, specs, model_axis="model",
                min_bytes=None, frozen=frozenset()):
    """{name: :class:`ZeroParam`} for a composed plan: parameters whose
    canonical spec (``specs``: {name: PartitionSpec tuple}) carries the
    model axis get group-local shard-major tiles — the flat footprint is
    ``model_n * shard_padded`` with ``shard_padded`` a multiple of the
    data-axis size, so every (model, data) device holds one contiguous
    even tile of its OWN group's shard and no collective ever crosses
    groups.  Everything else keeps the classic :func:`layout` tiling
    over the data axis (replicated across model groups, so each group
    redundantly holds the same 1/N tiles — 'tiles within each group').
    Deterministic in shapes/dtypes/specs only, like :func:`layout`."""
    import numpy as np

    if min_bytes is None:
        min_bytes = min_param_bytes()
    shape_map = dict(getattr(mesh, "shape", {}) or {})
    ndata = int(shape_map.get(axis, 1))
    nmodel = int(shape_map.get(model_axis, 1))
    out = {}
    for name, arr in params.items():
        pshape = tuple(int(s) for s in arr.shape)
        dtype = np.dtype(arr.dtype)
        logical = int(math.prod(pshape)) if pshape else 1
        spec = tuple((specs or {}).get(name) or ())
        tp_dim = None
        for i, s in enumerate(spec[:len(pshape)]):
            names = [s] if isinstance(s, str) else list(s or ())
            if model_axis in names:
                tp_dim = i
                break
        sharded = (name not in frozen and ndata > 1
                   and logical * dtype.itemsize >= min_bytes)
        if tp_dim is not None and nmodel > 1 and \
                pshape[tp_dim] % nmodel == 0:
            shard_logical = logical // nmodel
            shard_padded = max(1, -(-shard_logical // ndata)) * ndata
            out[name] = ZeroParam(
                name, pshape, dtype, logical, nmodel * shard_padded,
                sharded, tp_dim=tp_dim, model_axis=model_axis,
                model_n=nmodel, shard_padded=shard_padded)
        else:
            padded = max(1, -(-logical // ndata)) * ndata
            out[name] = ZeroParam(name, pshape, dtype, logical, padded,
                                  sharded)
    return out


def flat_sharding(mesh, axis, entry=None):
    """NamedSharding of one flat ``(padded,)`` tile: ``P(axis)`` for the
    classic layout, ``P((model, data))`` for a plan-composed TP entry —
    device (m, d) holds shard m's d-th tile, contiguously."""
    from jax.sharding import NamedSharding, PartitionSpec

    if entry is not None and getattr(entry, "model_n", 1) > 1:
        return NamedSharding(mesh,
                             PartitionSpec((entry.model_axis, axis)))
    return NamedSharding(mesh, PartitionSpec(axis))


def _axis_sharding(mesh, axis):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))


def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def put(x, sharding):
    """``jax.device_put`` onto ``sharding``, multiprocess-safe.

    ``device_put`` refuses a target sharding whose devices are not all
    addressable from this process, so on a pod the host->global
    placement goes through ``jax.make_array_from_callback``: ``x`` is
    read as the GLOBAL value and each process materializes only the
    windows it owns — the same single-controller semantics the
    single-process path gets for free."""
    import jax

    if sharding is None:
        return x
    if getattr(x, "sharding", None) == sharding:
        return x
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(x, sharding)
    import numpy as np

    host = np.asarray(x)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


def flat_pad(x, entry):
    """Flatten ``x`` to 1-D and zero-pad to ``entry.padded`` elements
    (pure reshape/pad/concat; traceable).  TP entries flatten
    SHARD-MAJOR: the canonical array splits ``model_n``-ways along
    ``tp_dim`` and each shard flattens + pads independently, so the flat
    tile laid out ``P((model, data))`` puts every group's shard on its
    own devices."""
    import jax.numpy as jnp

    if getattr(entry, "model_n", 1) > 1:
        shard_logical = entry.logical // entry.model_n
        parts = jnp.split(jnp.asarray(x), entry.model_n,
                          axis=entry.tp_dim)
        flats = [jnp.reshape(p, (-1,)) for p in parts]
        if entry.shard_padded > shard_logical:
            pad = entry.shard_padded - shard_logical
            flats = [jnp.pad(f, (0, pad)) for f in flats]
        return jnp.concatenate(flats)
    flat = jnp.reshape(x, (-1,))
    if entry.padded > entry.logical:
        flat = jnp.pad(flat, (0, entry.padded - entry.logical))
    return flat


def shard_flat(x, entry, mesh, axis):
    """Flatten+pad ``x`` and constrain it onto its flat tiling — under
    GSPMD this is the reduce-scatter (for a pending-sum gradient) or a
    local slice (for a replicated weight).  For a plan-composed TP
    entry the tiling is ``P((model, data))``: the gradient is already
    model-sharded, so the lowering is a reduce-scatter over the data
    axis WITHIN each model group — TP grads never join the cross-group
    reductions."""
    import jax

    return jax.lax.with_sharding_constraint(
        flat_pad(x, entry), flat_sharding(mesh, axis, entry))


def _gather_tp(flat, entry, mesh):
    """Group-local gather of one TP entry: all-gather the data-axis
    tiles WITHIN each model group (the ``P(model, None)`` row
    constraint — the model dim stays put), trim per-shard padding,
    rebuild the canonical shape, and land on the canonical TP sharding
    (a local relayout: each device already holds its group's shard)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    rows = jnp.reshape(flat, (entry.model_n, entry.shard_padded))
    rows = jax.lax.with_sharding_constraint(
        rows, NamedSharding(mesh, PartitionSpec(entry.model_axis, None)))
    shard_logical = entry.logical // entry.model_n
    shard_shape = list(entry.shape)
    shard_shape[entry.tp_dim] //= entry.model_n
    # Concatenating the m shard blocks along tp_dim == moving the shard
    # index next to tp_dim and merging: one reshape+transpose the
    # partitioner keeps group-local (per-i slice + concat confuses it).
    blocks = jnp.reshape(rows[:, :shard_logical],
                         [entry.model_n] + shard_shape)
    full = jnp.reshape(jnp.moveaxis(blocks, 0, entry.tp_dim), entry.shape)
    spec = [None] * len(entry.shape)
    spec[entry.tp_dim] = entry.model_axis
    return jax.lax.with_sharding_constraint(
        full, NamedSharding(mesh, PartitionSpec(*spec)))


def gather_param(flat, entry, mesh):
    """The updated flat tile back to the parameter's canonical form:
    replicate (the all-gather) + trim padding for the classic layout;
    group-local gather onto the canonical TP sharding for a
    plan-composed TP entry."""
    import jax
    import jax.numpy as jnp

    if getattr(entry, "model_n", 1) > 1:
        return _gather_tp(flat, entry, mesh)
    full = jax.lax.with_sharding_constraint(flat, _replicated(mesh))
    return jnp.reshape(full[:entry.logical], entry.shape)


def gather_bucket(flats, entries, mesh, axis, scales=None):
    """ZeRO-3 on-demand gather of one layer bucket: flat 1/N tiles back
    to full parameter shapes.  Context-aware: inside the explicit-DDP
    ``shard_map`` trace the tiles are LOCAL values and the gather is one
    tuple ``lax.all_gather(tiled=True)`` per bucket (a single
    schedulable collective whose transpose is the grad reduce-scatter);
    under GSPMD it is a replication constraint per tensor and XLA
    places/combines the gathers itself.

    ``scales``: optional per-entry sequence for weight-only quantized
    tiles (``quantize.quantize_flat_leaf`` layout) — ``None`` members
    pass through, the rest dequantize AFTER the collective, so the
    gather moves 1-byte codes (~4x fewer network bytes) and only the
    full gathered copy pays the float32 expansion."""
    import jax
    import jax.numpy as jnp

    from . import overlap as _overlap

    ctx = _overlap._ddp_ctx
    if ctx is not None:
        from jax import lax

        fulls = lax.all_gather(tuple(flats), ctx[0], axis=0, tiled=True)
    else:
        repl = _replicated(mesh)
        # plan-composed TP tiles gather group-locally below; the
        # replication constraint here would be the monolithic global
        # gather the plan exists to avoid
        fulls = tuple(
            f if getattr(e, "model_n", 1) > 1
            else jax.lax.with_sharding_constraint(f, repl)
            for f, e in zip(flats, entries))
    if scales is not None:
        from .. import quantize as _quant

        fulls = tuple(
            f if s is None else _quant.dequant_flat(f, e, s)
            for f, e, s in zip(fulls, entries, scales))
    return [_gather_tp(f, e, mesh)
            if getattr(e, "model_n", 1) > 1
            else jnp.reshape(f[:e.logical], e.shape)
            for f, e in zip(fulls, entries)]


def pack_params(params, lay, mesh, axis):
    """Canonical full params -> the ZeRO-3 at-rest layout: sharded
    entries become flat ``(padded,)`` tiles placed ``P(axis)``,
    unsharded ones pass through.  Zero padding makes the round trip
    content-preserving, so packing a restored/initialized full param is
    bit-exact."""
    import jax.numpy as jnp

    out = {}
    for name, v in params.items():
        ent = lay[name]
        if ent.sharded and tuple(getattr(v, "shape", ())) != (ent.padded,):
            out[name] = put(flat_pad(jnp.asarray(v), ent),
                            flat_sharding(mesh, axis, ent))
        else:
            out[name] = v
    return out


def tp_meta(entry):
    """JSON-able TP-layout descriptor of one entry, or None for the
    classic layout — rides checkpoint manifests so any topology can
    invert the shard-major flat order."""
    if getattr(entry, "model_n", 1) <= 1:
        return None
    return {"model_n": int(entry.model_n),
            "shard_padded": int(entry.shard_padded),
            "tp_dim": int(entry.tp_dim)}


def unflatten_tiles(flat, logical, canonical_shape, tp=None):
    """Host-numpy inverse of :func:`flat_pad` for a FULL flat array:
    trim padding and restore ``canonical_shape``.  ``tp`` is a
    :func:`tp_meta` dict for shard-major TP tiles (a plain
    ``reshape(-1)[:logical]`` would interleave the per-shard padding
    into the data); None/classic trims the single tail pad.  This is
    the checkpoint restore primitive: it only sees assembled host
    arrays, so it works on any topology including unsharded."""
    import numpy as np

    arr = np.asarray(flat).reshape(-1)
    shape = [int(s) for s in canonical_shape]
    logical = int(logical)
    if not tp or int(tp.get("model_n", 1)) <= 1:
        return arr[:logical].reshape(shape)
    m = int(tp["model_n"])
    sp = int(tp["shard_padded"])
    dim = int(tp["tp_dim"])
    shard_logical = logical // m
    sshape = list(shape)
    sshape[dim] //= m
    rows = arr.reshape(m, sp)[:, :shard_logical]
    return np.concatenate([r.reshape(sshape) for r in rows], axis=dim)


def unpack_param(flat, entry):
    """One at-rest value -> canonical host numpy (trim the padding
    lanes, restore the shape).  Requires addressability, like
    :func:`unshard_state`."""
    import numpy as np

    arr = np.asarray(flat)
    if entry.sharded and arr.shape == (entry.padded,):
        return unflatten_tiles(arr, entry.logical, entry.shape,
                               tp_meta(entry))
    return arr


def unpack_params(params, lay):
    """At-rest params dict -> canonical host numpy dict."""
    return {name: unpack_param(v, lay[name]) for name, v in params.items()}


def state_sharding(states_tree, entry, mesh, axis):
    """Pytree of ``NamedSharding`` matching one parameter's fused state:
    flat ``(padded,)`` leaves tile over ``axis`` (group-locally for a
    plan-composed TP entry), everything else (scalars, schedules)
    replicates."""
    import jax

    shard = flat_sharding(mesh, axis, entry)
    repl = _replicated(mesh)

    def _leaf(leaf):
        shape = tuple(getattr(leaf, "shape", ()))
        if entry.sharded and shape == (entry.padded,):
            return shard
        return repl

    return jax.tree.map(_leaf, states_tree)


def init_state(optimizer, weight, entry, mesh, axis):
    """Fresh fused optimizer state for one parameter under the zero
    layout: built from the flat padded weight so every weight-shaped
    leaf is born ``(padded,)``, then placed with the 1/N tiling (the
    per-replica allocation is ``padded / N`` elements per leaf)."""
    import jax

    if not entry.sharded:
        return optimizer.init_fused_state(weight)
    # build from a LOCAL flat weight (eager ops on non-addressable
    # global arrays are illegal on pods), then place each leaf onto its
    # 1/N tiling — transient full-size leaves are weight-order memory
    state = optimizer.init_fused_state(flat_pad(weight, entry))
    return jax.tree.map(
        put, state, state_sharding(state, entry, mesh, axis))


def shard_state(state, entry, mesh, axis):
    """Canonical (weight-shaped) fused state -> the zero layout.  Used
    when resuming from a checkpoint saved unsharded or by a different
    topology: zero-padding is content-preserving, so the re-tiling is
    bit-exact."""
    import jax
    import jax.numpy as jnp

    if not entry.sharded:
        return jax.tree.map(jnp.asarray, state)
    shard = flat_sharding(mesh, axis, entry)
    repl = _replicated(mesh)

    def _leaf(leaf):
        leaf = jnp.asarray(leaf)
        if tuple(leaf.shape) == entry.shape:
            return put(flat_pad(leaf, entry), shard)
        return put(leaf, repl)

    return jax.tree.map(_leaf, state)


def unshard_state(state, entry):
    """The zero layout -> canonical weight-shaped fused state (host
    numpy).  Requires the flat leaves to be addressable from this
    process — multi-process runs checkpoint through the v2 piece-window
    path instead (each rank writes its own windows)."""
    import jax
    import numpy as np

    if not entry.sharded:
        return jax.tree.map(np.asarray, state)

    def _leaf(leaf):
        arr = np.asarray(leaf)
        if arr.shape == (entry.padded,):
            return unflatten_tiles(arr, entry.logical, entry.shape,
                                   tp_meta(entry))
        return arr

    return jax.tree.map(_leaf, state)


# -- checkpoint interchange ------------------------------------------------
#
# Fused states are tuple/None/array pytrees; the v2 checkpoint stores each
# leaf as its own piece-windowed entry, so the tree shape must ride along
# as a JSON-serializable descriptor.

def state_structure(tree):
    """JSON-serializable descriptor of a fused-state pytree: ``None``,
    ``{"leaf": i}`` (i-th leaf in ``state_leaves`` order), or
    ``{"tuple": [...]}``."""
    counter = [0]

    def _enc(node):
        if node is None:
            return None
        if isinstance(node, (tuple, list)):
            return {"tuple": [_enc(e) for e in node]}
        i = counter[0]
        counter[0] += 1
        return {"leaf": i}

    return _enc(tree)


def state_leaves(tree):
    """Leaves in ``state_structure`` order."""
    out = []

    def _walk(node):
        if node is None:
            return
        if isinstance(node, (tuple, list)):
            for e in node:
                _walk(e)
            return
        out.append(node)

    _walk(tree)
    return out


def state_unflatten(desc, leaves):
    """Rebuild the fused-state pytree from its descriptor + leaves."""
    def _dec(node):
        if node is None:
            return None
        if "tuple" in node:
            return tuple(_dec(e) for e in node["tuple"])
        return leaves[int(node["leaf"])]

    return _dec(desc)


def export_states(states, lay):
    """Checkpoint export descriptor for a fused-states dict under
    ``lay`` (a :func:`layout` result): per parameter, the structure
    descriptor, the raw leaves (flat sharded arrays stay sharded — the
    v2 writer pieces them by addressable window), and the unpadding
    metadata the restore needs."""
    out = {}
    for name, tree in states.items():
        ent = lay[name]
        leaves = state_leaves(tree)
        flat = [ent.sharded and tuple(getattr(l, "shape", ())) ==
                (ent.padded,) for l in leaves]
        out[name] = {
            "structure": state_structure(tree),
            "leaves": leaves,
            "flat": flat,
            "logical": ent.logical,
            "canonical_shape": list(ent.shape),
        }
        tp = tp_meta(ent)
        if tp:
            out[name]["tp"] = tp
    return out


def export_params(params, lay):
    """Checkpoint export descriptor for a ZeRO-3 at-rest params dict:
    per parameter the raw at-rest value (flat sharded tiles stay sharded
    — the v2 writer pieces them by addressable window) plus the
    unpadding metadata the restore needs to trim back to the canonical
    shape.  Restoring trims to ``logical`` and reshapes, so an N-way
    save restores at M-way or unsharded (``zero=off``) bit-exactly."""
    out = {}
    for name, v in params.items():
        ent = lay[name]
        flat = ent.sharded and tuple(getattr(v, "shape", ())) == (
            ent.padded,)
        out[name] = {
            "leaf": v,
            "flat": bool(flat),
            "logical": ent.logical,
            "canonical_shape": list(ent.shape),
        }
        tp = tp_meta(ent)
        if tp:
            out[name]["tp"] = tp
    return out


def import_state(ent, leaves=None):
    """Inverse of one :func:`export_states` record: rebuild the
    canonical (weight-shaped) fused-state pytree from flat host tiles.
    ``leaves`` overrides the record's own (e.g. host arrays assembled
    across ranks through the checkpoint piece windows); flat leaves are
    trimmed with :func:`unflatten_tiles`, everything else passes
    through.  This is the in-memory twin of the checkpoint restore's
    ``_reassemble_zero`` — the elastic migration reshards through it
    without a disk round-trip."""
    import numpy as np

    leaves = ent["leaves"] if leaves is None else leaves
    shape = [int(s) for s in ent["canonical_shape"]]
    out = []
    for leaf, flat in zip(leaves, ent["flat"]):
        arr = np.asarray(leaf)
        if flat:
            arr = unflatten_tiles(arr.reshape(-1), int(ent["logical"]),
                                  shape, ent.get("tp"))
        out.append(arr)
    return state_unflatten(ent["structure"], out)


def import_param(ent, leaf=None):
    """Inverse of one :func:`export_params` record: flat at-rest host
    tile -> canonical full host array (or pass-through when the entry
    was never sharded)."""
    import numpy as np

    arr = np.asarray(ent["leaf"] if leaf is None else leaf)
    if ent["flat"]:
        arr = unflatten_tiles(arr.reshape(-1), int(ent["logical"]),
                              [int(s) for s in ent["canonical_shape"]],
                              ent.get("tp"))
    return arr


# -- accounting ------------------------------------------------------------

def state_bytes_per_replica(states, ndev=None):
    """Optimizer-state bytes ONE replica holds, read from the live
    arrays' shardings (a sharded leaf contributes one shard's bytes).
    This is the 1/N memory claim the bench rows report."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(states):
        shape = tuple(getattr(leaf, "shape", ()))
        itemsize = np.dtype(leaf.dtype).itemsize
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and shape:
            shape = tuple(sharding.shard_shape(shape))
        total += int(math.prod(shape) if shape else 1) * itemsize
    return total


def params_bytes_per_replica(params):
    """Parameter bytes ONE replica holds at rest, read from the live
    arrays' shardings — full model bytes when replicated (``zero=off``
    and stage-1), ~1/N under ZeRO-3 flat tiles.  Same accounting as
    :func:`state_bytes_per_replica`."""
    return state_bytes_per_replica(params)


def _gathered_elems(e):
    """Flat elements one device materializes when gathering one entry:
    the whole padded footprint for the classic layout, one group's
    shard for a plan-composed TP entry (the gather never crosses model
    groups)."""
    return e.shard_padded if getattr(e, "model_n", 1) > 1 else e.padded


def update_gather_bytes(lay):
    """Bytes of fresh parameters the trailing all-gather moves per step
    under the stage-1 update (the padded flat size of every sharded
    parameter; group-local — one shard, not the whole footprint — for
    plan-composed TP entries).  Zero under ZeRO-3 — there is no
    trailing gather; see :func:`zero3_gather_bytes`."""
    return sum(_gathered_elems(e) * e.dtype.itemsize
               for e in lay.values() if e.sharded)


def zero3_gather_bytes(lay, quant=None):
    """Bytes the ZeRO-3 bucketed gathers move per step: every sharded
    parameter is gathered once for forward and re-gathered once by the
    rematerialized backward.  ``quant`` (``"int8"``/``"fp8"``) accounts
    the weight-only quantized interchange: eligible tiles move as
    1-byte codes (scales are replicated and don't ride the gather)."""
    from .. import quantize as _quant

    mode = _quant.quant_mode(quant)
    total = 0
    for e in lay.values():
        if not e.sharded:
            continue
        itemsize = e.dtype.itemsize
        if mode and not getattr(e, "model_n", 1) > 1 and \
                _quant.eligible(e.shape, e.dtype):
            itemsize = _quant.quant_dtype(mode).itemsize
        total += _gathered_elems(e) * itemsize
    return 2 * total


# -- fault/bounded dispatch ------------------------------------------------

def bounded_dispatch(call, kvstore=None, active=None, what=None):
    """Run one sharded-update step under the kvstore's wall-clock bound.

    The reduce-scatter and the param all-gathers are collectives: one
    wedged peer stalls every healthy replica inside the device call
    forever.  When the ``zero_update`` / ``zero_gather`` fault sites are
    armed, or the run is genuinely multi-process, the step dispatch runs
    through :func:`~mxnet_tpu.kvstore._run_bounded` with the PR 3 peer
    report as the diagnosis — the same treatment the kvstore barrier
    gets.  ``what`` names the bounded operation in the timeout error
    (default: the stage-1 update description).  The single-process clean
    path stays a direct call (no watchdog thread per step)."""
    from ..testing import faults

    if active is None:
        active = (faults.active("zero_update")
                  or faults.active("zero_gather")
                  or (kvstore is not None
                      and getattr(kvstore, "_is_dist", False)))
    if not active:
        return call()
    from ..kvstore import _run_bounded

    diagnose = getattr(kvstore, "_peer_diagnose", None)
    if diagnose is None:
        def diagnose():
            import jax

            from ..health import peer_report

            return peer_report(jax.process_count())
    return _run_bounded(
        call, what or "ZeRO sharded update (gradient reduce-scatter + "
        "parameter all-gather)", diagnose=diagnose)
