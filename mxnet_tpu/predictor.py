"""Predictor — the forward-only deployment surface.

Reference: ``src/c_api/c_predict_api.cc`` + ``amalgamation/`` (SURVEY.md
§2.1 "Amalgamation / predictor ABI"): create from a symbol-JSON string +
a parameter blob, set inputs, forward, read outputs — no gradient
machinery, no optimizer, suitable for serving.

TPU-native form: the bound graph compiles to ONE inference XLA program
(cached per input shapes); ``Predictor`` never builds the vjp, so its
memory footprint is the weights plus one activation set.
"""
from __future__ import annotations

from collections import OrderedDict

from .base import MXNetError

__all__ = ["Predictor", "ExportedPredictor"]


class Predictor:
    """Forward-only executor (reference ``MXPredCreate``/``MXPredForward``
    / ``MXPredGetOutput``)."""

    def __init__(self, symbol_json, param_bytes_or_dict, input_shapes,
                 ctx=None, output_keys=None):
        from . import symbol as sym_mod
        from .ndarray import NDArray

        if isinstance(symbol_json, str):
            self._symbol = sym_mod.load_json(symbol_json)
        else:
            self._symbol = symbol_json
        if output_keys:
            # reference MXPredCreatePartialOut: expose named INTERNAL
            # outputs instead of the symbol's heads
            internals = self._symbol.get_internals()
            names = internals.list_outputs()
            picked = []
            for key in output_keys:
                matches = [i for i, n in enumerate(names)
                           if n == key or n == key + "_output"]
                if not matches:
                    raise MXNetError(
                        "output key %r not found among internals" % key)
                picked.append(internals[matches[-1]])
            self._symbol = picked[0] if len(picked) == 1 else \
                sym_mod.Group(picked)
        if isinstance(param_bytes_or_dict, (bytes, bytearray)):
            params = self._load_param_bytes(bytes(param_bytes_or_dict))
        else:
            params = dict(param_bytes_or_dict)
        # reference param files prefix keys with arg:/aux:
        arg_params, aux_params = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        self._input_shapes = dict(input_shapes)
        self._exec = self._symbol.simple_bind(
            ctx, grad_req="null", **self._input_shapes)
        # PR 4 guardrails: serving clients that churn input shapes (new
        # Predictor per shape, or hot-swapped buffers) retrace the XLA
        # program every request.  The registry guard is shared across
        # instances of the same graph so fleet-wide churn aggregates;
        # per-instance `_seen_sigs` keeps a single instance's steady
        # state free (repeat shapes observe without counting a trace).
        from .compile_cache import registry

        self._recompile_guard = registry.guard(
            "Predictor(%s)" % (getattr(self._symbol, "name", None)
                               or "graph"))
        self._seen_sigs = set()
        for name, arr in arg_params.items():
            if name in self._exec.arg_dict:
                if tuple(arr.shape) != self._exec.arg_dict[name].shape:
                    raise MXNetError(
                        "param %s shape %s != expected %s"
                        % (name, tuple(arr.shape),
                           self._exec.arg_dict[name].shape))
                arr.copyto(self._exec.arg_dict[name])
        for name, arr in aux_params.items():
            if name in self._exec.aux_dict:
                arr.copyto(self._exec.aux_dict[name])
        # label variables are not needed for inference (loss heads ignore
        # them at is_train=False); they stay zero-filled
        missing = [n for n in self._exec.arg_dict
                   if n not in arg_params and n not in self._input_shapes
                   and not n.endswith("_label")]
        if missing:
            raise MXNetError("predictor missing parameters: %s" % missing)

    @staticmethod
    def _load_param_bytes(blob):
        import io as _io
        import zipfile

        import numpy as np

        from .ndarray import array

        with zipfile.ZipFile(_io.BytesIO(blob)) as zf:
            data = {k: np.load(_io.BytesIO(zf.read(k))) for k in
                    zf.namelist()}
        return {(k[:-4] if k.endswith(".npy") else k): array(v)
                for k, v in data.items()}

    @classmethod
    def load(cls, prefix, epoch, input_shapes, ctx=None):
        """Create from checkpoint files (reference ``MXPredCreate`` over
        ``prefix-symbol.json`` + ``prefix-%04d.params``)."""
        from .model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        params = {"arg:" + k: v for k, v in arg.items()}
        params.update({"aux:" + k: v for k, v in aux.items()})
        return cls(sym, params, input_shapes, ctx=ctx)

    def set_input(self, name, value):
        """Reference ``MXPredSetInput``."""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %r (inputs: %s)"
                             % (name, sorted(self._input_shapes)))
        from .ndarray import NDArray, array

        arr = value if isinstance(value, NDArray) else array(value)
        arr.copyto(self._exec.arg_dict[name])

    def forward(self, **inputs):
        """Run inference; optional inputs as kwargs (reference
        ``MXPredForward``)."""
        for k, v in inputs.items():
            self.set_input(k, v)
        from .compile_cache import signature_of

        sig = signature_of({k: self._exec.arg_dict[k]._data
                            for k in sorted(self._input_shapes)})
        self._recompile_guard.observe(sig, force=sig not in self._seen_sigs)
        self._seen_sigs.add(sig)
        self._exec.forward(is_train=False)
        return self._exec.outputs

    def get_output(self, index=0):
        """Reference ``MXPredGetOutput`` — returns a numpy array."""
        outs = self._exec.outputs
        if not outs:
            raise MXNetError("call forward() before get_output()")
        return outs[index].asnumpy()

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    # -- AOT deployment bundle (the amalgamation analogue) ---------------
    def export(self, path):
        """Serialize this predictor into ONE self-contained file: the
        forward graph ahead-of-time lowered to serialized StableHLO via
        ``jax.export``, plus the parameters and IO metadata.

        This is the TPU-native answer to the reference's ``amalgamation/``
        single-file deployment build (``amalgamation/mxnet_predict0.cc``,
        ``c_predict_api.cc``): instead of compiling the C++ predictor into
        one translation unit, the *model* is compiled into one portable
        artifact that any JAX runtime can execute — no symbol machinery,
        no op registry, no framework graph code needed at serving time
        (``load_exported`` only touches ``jax.export`` + numpy).
        """
        import io as _io
        import json
        import zipfile

        import jax
        import numpy as np
        from jax import export as jexport

        from .executor import _trace_fn

        fn = _trace_fn(self._symbol, False)[0]
        args = {n: a._data for n, a in self._exec.arg_dict.items()}
        aux = {n: a._data for n, a in self._exec.aux_dict.items()}
        rng = jax.random.PRNGKey(0)
        spec = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (args, aux, rng))
        # lower for both backends so one bundle serves TPU pods and CPU
        # hosts (jax.export multi-platform lowering)
        exp = jexport.export(jax.jit(fn),
                             platforms=("tpu", "cpu"))(*spec)

        meta = {
            "inputs": {k: list(v) for k, v in self._input_shapes.items()},
            "outputs": self._symbol.list_outputs(),
            "label_inputs": [n for n in args if n.endswith("_label")],
        }
        # input/label buffers hold whatever batch was last fed through
        # forward(); store zeros so the bundle never bakes in user data
        data_keys = set(meta["inputs"]) | set(meta["label_inputs"])
        args = {k: (jax.numpy.zeros_like(v) if k in data_keys else v)
                for k, v in args.items()}
        with zipfile.ZipFile(path, "w") as zf:
            zf.writestr("model.stablehlo", bytes(exp.serialize()))
            zf.writestr("meta.json", json.dumps(meta))
            buf = _io.BytesIO()
            np.savez(buf,
                     **{"arg:" + k: np.asarray(v) for k, v in args.items()},
                     **{"aux:" + k: np.asarray(v) for k, v in aux.items()})
            zf.writestr("params.npz", buf.getvalue())
        return path

    @staticmethod
    def load_exported(path):
        """Load an :meth:`export` bundle.  Returns an
        :class:`ExportedPredictor` — same ``forward``/``get_output``
        surface, zero framework graph machinery."""
        return ExportedPredictor(path)


class ExportedPredictor:
    """Serving-side half of the deployment bundle: deserialized StableHLO
    + a parameter dict.  Depends only on ``jax.export`` and numpy."""

    def __init__(self, path):
        import io as _io
        import json
        import zipfile

        import jax
        import numpy as np
        from jax import export as jexport

        with zipfile.ZipFile(path) as zf:
            self._exported = jexport.deserialize(
                bytearray(zf.read("model.stablehlo")))
            meta = json.loads(zf.read("meta.json"))
            blob = np.load(_io.BytesIO(zf.read("params.npz")))
        self._meta = meta
        self._input_shapes = {k: tuple(v)
                              for k, v in meta["inputs"].items()}
        self._args = {k[4:]: np.asarray(v) for k, v in blob.items()
                      if k.startswith("arg:")}
        self._aux = {k[4:]: np.asarray(v) for k, v in blob.items()
                     if k.startswith("aux:")}
        self._rng = jax.random.PRNGKey(0)
        self._outputs = None
        # same PR 4 accounting as Predictor: an exported bundle has ONE
        # legal input signature (jax.export enforces exact shapes), so
        # any drift a client feeds it is surfaced as a named recompile
        # storm instead of an opaque serialization error.
        import os

        from .compile_cache import registry

        self._recompile_guard = registry.guard(
            "ExportedPredictor(%s)"
            % os.path.splitext(os.path.basename(str(path)))[0])
        self._seen_sigs = set()

    @property
    def output_names(self):
        return list(self._meta["outputs"])

    def forward(self, **inputs):
        import numpy as np

        from .ndarray import NDArray

        args = dict(self._args)
        for k, v in inputs.items():
            if k not in self._input_shapes:
                raise MXNetError("unknown input %r (inputs: %s)"
                                 % (k, sorted(self._input_shapes)))
            args[k] = np.asarray(v.asnumpy() if isinstance(v, NDArray)
                                 else v, dtype=args[k].dtype)
        from .compile_cache import signature_of

        sig = signature_of({k: args[k] for k in sorted(self._input_shapes)})
        self._recompile_guard.observe(sig, force=sig not in self._seen_sigs)
        self._seen_sigs.add(sig)
        outs, _new_aux = self._exported.call(args, self._aux, self._rng)
        self._outputs = [np.asarray(o) for o in outs]
        return self._outputs

    def get_output(self, index=0):
        if self._outputs is None:
            raise MXNetError("call forward() before get_output()")
        return self._outputs[index]


def _load_nd_list_bytes(blob):
    """C-ABI helper (MXNDListCreate): parse an ``nd.save`` container
    blob into [(name, shape_tuple, float32_bytes), ...] — the
    deployment mean-image artifact the reference's NDList carries.
    Data rides as raw bytes (one memcpy on the C side, no per-element
    boxing); container parsing delegates to ``nd.load`` so the two
    paths can never drift."""
    import io

    import numpy as np

    import tempfile

    from .ndarray import load as nd_load

    # nd.load owns the container format ('__list_N' vs dict keys); feed
    # it through a temp file since np.load-on-path is its contract
    with tempfile.NamedTemporaryFile(suffix=".npz") as tf:
        tf.write(blob)
        tf.flush()
        loaded = nd_load(tf.name)
    if isinstance(loaded, dict):
        items = list(loaded.items())
    else:
        items = [("", v) for v in loaded]
    out = []
    for name, nd in items:
        arr = np.ascontiguousarray(nd.asnumpy(), np.float32)
        out.append((name, tuple(int(d) for d in arr.shape),
                    arr.tobytes()))
    return out
