"""Predictor — the forward-only deployment surface.

Reference: ``src/c_api/c_predict_api.cc`` + ``amalgamation/`` (SURVEY.md
§2.1 "Amalgamation / predictor ABI"): create from a symbol-JSON string +
a parameter blob, set inputs, forward, read outputs — no gradient
machinery, no optimizer, suitable for serving.

TPU-native form: the bound graph compiles to ONE inference XLA program
(cached per input shapes); ``Predictor`` never builds the vjp, so its
memory footprint is the weights plus one activation set.
"""
from __future__ import annotations

from collections import OrderedDict

from .base import MXNetError

__all__ = ["Predictor"]


class Predictor:
    """Forward-only executor (reference ``MXPredCreate``/``MXPredForward``
    / ``MXPredGetOutput``)."""

    def __init__(self, symbol_json, param_bytes_or_dict, input_shapes,
                 ctx=None):
        from . import symbol as sym_mod
        from .ndarray import NDArray

        if isinstance(symbol_json, str):
            self._symbol = sym_mod.load_json(symbol_json)
        else:
            self._symbol = symbol_json
        if isinstance(param_bytes_or_dict, (bytes, bytearray)):
            params = self._load_param_bytes(bytes(param_bytes_or_dict))
        else:
            params = dict(param_bytes_or_dict)
        # reference param files prefix keys with arg:/aux:
        arg_params, aux_params = {}, {}
        for k, v in params.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        self._input_shapes = dict(input_shapes)
        self._exec = self._symbol.simple_bind(
            ctx, grad_req="null", **self._input_shapes)
        for name, arr in arg_params.items():
            if name in self._exec.arg_dict:
                if tuple(arr.shape) != self._exec.arg_dict[name].shape:
                    raise MXNetError(
                        "param %s shape %s != expected %s"
                        % (name, tuple(arr.shape),
                           self._exec.arg_dict[name].shape))
                arr.copyto(self._exec.arg_dict[name])
        for name, arr in aux_params.items():
            if name in self._exec.aux_dict:
                arr.copyto(self._exec.aux_dict[name])
        # label variables are not needed for inference (loss heads ignore
        # them at is_train=False); they stay zero-filled
        missing = [n for n in self._exec.arg_dict
                   if n not in arg_params and n not in self._input_shapes
                   and not n.endswith("_label")]
        if missing:
            raise MXNetError("predictor missing parameters: %s" % missing)

    @staticmethod
    def _load_param_bytes(blob):
        import io as _io
        import zipfile

        import numpy as np

        from .ndarray import array

        with zipfile.ZipFile(_io.BytesIO(blob)) as zf:
            data = {k: np.load(_io.BytesIO(zf.read(k))) for k in
                    zf.namelist()}
        return {(k[:-4] if k.endswith(".npy") else k): array(v)
                for k, v in data.items()}

    @classmethod
    def load(cls, prefix, epoch, input_shapes, ctx=None):
        """Create from checkpoint files (reference ``MXPredCreate`` over
        ``prefix-symbol.json`` + ``prefix-%04d.params``)."""
        from .model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        params = {"arg:" + k: v for k, v in arg.items()}
        params.update({"aux:" + k: v for k, v in aux.items()})
        return cls(sym, params, input_shapes, ctx=ctx)

    def set_input(self, name, value):
        """Reference ``MXPredSetInput``."""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %r (inputs: %s)"
                             % (name, sorted(self._input_shapes)))
        from .ndarray import NDArray, array

        arr = value if isinstance(value, NDArray) else array(value)
        arr.copyto(self._exec.arg_dict[name])

    def forward(self, **inputs):
        """Run inference; optional inputs as kwargs (reference
        ``MXPredForward``)."""
        for k, v in inputs.items():
            self.set_input(k, v)
        self._exec.forward(is_train=False)
        return self._exec.outputs

    def get_output(self, index=0):
        """Reference ``MXPredGetOutput`` — returns a numpy array."""
        outs = self._exec.outputs
        if not outs:
            raise MXNetError("call forward() before get_output()")
        return outs[index].asnumpy()

    @property
    def output_names(self):
        return self._symbol.list_outputs()
