"""Profiler — chrome-trace output via the XLA/JAX profiler.

Reference: ``python/mxnet/profiler.py`` over the engine profiler
(``src/engine/profiler.cc:152`` writes chrome://tracing JSON;
SURVEY.md §5 "Tracing/profiling").  Here the device timeline comes from
``jax.profiler`` (XLA's own op-level trace — strictly richer than the
reference's per-engine-op stat slabs) and ``dump()`` extracts the
chrome-trace JSON so the output opens in chrome://tracing / Perfetto
exactly like the reference's.

API surface: ``profiler_set_config(filename=...)``,
``profiler_set_state('run'|'stop')`` (aliases ``set_config``/
``set_state``), ``dump()``; env ``MXNET_PROFILER_AUTOSTART=1`` starts
tracing at import (reference ``env_var.md`` autostart contract).
"""
from __future__ import annotations

import glob
import gzip
import os
import shutil
import tempfile

from .base import MXNetError, get_env

__all__ = ["profiler_set_config", "profiler_set_state", "set_config",
           "set_state", "dump", "dump_profile", "state"]

_config = {"filename": "profile.json", "profile_all": False}
_state = {"running": False, "tmpdir": None, "dumped": False}


def profiler_set_config(mode="symbolic", filename="profile.json", **kwargs):
    """Configure output (reference ``profiler_set_config``; ``mode`` is
    accepted for API parity — the XLA trace always covers everything)."""
    _config["filename"] = filename
    _config["mode"] = mode
    _config.update(kwargs)


def profiler_set_state(state="stop"):
    """Start/stop tracing (reference ``profiler_set_state``)."""
    import jax

    if state == "run":
        if _state["running"]:
            return
        _state["tmpdir"] = tempfile.mkdtemp(prefix="mxtpu_profile_")
        _state["dumped"] = False
        jax.profiler.start_trace(_state["tmpdir"])
        _state["running"] = True
    elif state == "stop":
        if not _state["running"]:
            return
        jax.profiler.stop_trace()
        _state["running"] = False
    else:
        raise MXNetError("profiler state must be 'run' or 'stop', got %r"
                         % state)


set_config = profiler_set_config
set_state = profiler_set_state


def state():
    return "run" if _state["running"] else "stop"


def dump(finished=True):
    """Write the chrome-trace JSON to the configured filename (reference
    ``dump_profile`` → ``Profiler::DumpProfile``)."""
    if _state["running"] and finished:
        profiler_set_state("stop")
    tmpdir = _state["tmpdir"]
    if tmpdir is None:
        raise MXNetError("nothing profiled: call "
                         "profiler_set_state('run') first")
    traces = sorted(glob.glob(
        os.path.join(tmpdir, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        raise MXNetError("profiler produced no trace under %s" % tmpdir)
    with gzip.open(traces[-1], "rb") as src, \
            open(_config["filename"], "wb") as dst:
        shutil.copyfileobj(src, dst)
    _state["dumped"] = True
    return _config["filename"]


dump_profile = dump

if get_env("MXNET_PROFILER_AUTOSTART", False, bool):
    profiler_set_state("run")
