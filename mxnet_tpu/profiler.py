"""Profiler — chrome-trace output via the XLA/JAX profiler.

Reference: ``python/mxnet/profiler.py`` over the engine profiler
(``src/engine/profiler.cc:152`` writes chrome://tracing JSON;
SURVEY.md §5 "Tracing/profiling").  Here the device timeline comes from
``jax.profiler`` (XLA's own op-level trace — strictly richer than the
reference's per-engine-op stat slabs) and ``dump()`` extracts the
chrome-trace JSON so the output opens in chrome://tracing / Perfetto
exactly like the reference's.

API surface: ``profiler_set_config(filename=...)``,
``profiler_set_state('run'|'stop')`` (aliases ``set_config``/
``set_state``), ``dump()``; env ``MXNET_PROFILER_AUTOSTART=1`` starts
tracing at import (reference ``env_var.md`` autostart contract).

Compile-time events are first-class here too: XLA compilation dominates
time-to-first-step on this platform, so every AOT/JIT compile the
framework performs is recorded via :func:`compile_event` (wall seconds,
FLOPs estimate, executable size) and retrievable with
:func:`compile_events` / summed with :func:`total_compile_s` — the
numbers ``TrainStep.compile_stats`` and the bench scripts' ``compile_s``
field surface (see docs/compilation.md).
"""
from __future__ import annotations

import glob
import gzip
import os
import shutil
import tempfile
import threading
import time as _time

from .base import MXNetError, get_env

__all__ = ["profiler_set_config", "profiler_set_state", "set_config",
           "set_state", "dump", "dump_profile", "state",
           "compile_event", "compile_events", "total_compile_s"]

_config = {"filename": "profile.json", "profile_all": False}
_state = {"running": False, "tmpdir": None, "dumped": False}
_compile_events = []
_compile_lock = threading.Lock()


def profiler_set_config(mode="symbolic", filename="profile.json", **kwargs):
    """Configure output (reference ``profiler_set_config``; ``mode`` is
    accepted for API parity — the XLA trace always covers everything)."""
    _config["filename"] = filename
    _config["mode"] = mode
    _config.update(kwargs)


def profiler_set_state(state="stop"):
    """Start/stop tracing (reference ``profiler_set_state``)."""
    import jax

    if state == "run":
        if _state["running"]:
            return
        _state["tmpdir"] = tempfile.mkdtemp(prefix="mxtpu_profile_")
        _state["dumped"] = False
        jax.profiler.start_trace(_state["tmpdir"])
        _state["running"] = True
    elif state == "stop":
        if not _state["running"]:
            return
        jax.profiler.stop_trace()
        _state["running"] = False
    else:
        raise MXNetError("profiler state must be 'run' or 'stop', got %r"
                         % state)


set_config = profiler_set_config
set_state = profiler_set_state


def state():
    return "run" if _state["running"] else "stop"


def dump(finished=True):
    """Write the chrome-trace JSON to the configured filename (reference
    ``dump_profile`` → ``Profiler::DumpProfile``)."""
    if _state["running"] and finished:
        profiler_set_state("stop")
    tmpdir = _state["tmpdir"]
    if tmpdir is None:
        raise MXNetError("nothing profiled: call "
                         "profiler_set_state('run') first")
    traces = sorted(glob.glob(
        os.path.join(tmpdir, "**", "*.trace.json.gz"), recursive=True))
    if not traces:
        raise MXNetError("profiler produced no trace under %s" % tmpdir)
    with gzip.open(traces[-1], "rb") as src, \
            open(_config["filename"], "wb") as dst:
        shutil.copyfileobj(src, dst)
    _state["dumped"] = True
    return _config["filename"]


dump_profile = dump


# -- compile-time events ----------------------------------------------------

def compile_event(name, duration_s, flops=None, executable_bytes=None,
                  cache_hit=None, **extra):
    """Record one compilation: ``name`` identifies the callable (e.g.
    ``TrainStep(softmax)``), ``duration_s`` the end-to-end lower+compile
    wall time; ``flops`` (XLA cost analysis), ``executable_bytes``
    (generated code size), and ``cache_hit`` (persistent-cache) are
    best-effort.  Returns the recorded event dict."""
    event = {"name": name, "duration_s": float(duration_s),
             "time": _time.time()}
    if flops is not None:
        event["flops"] = float(flops)
    if executable_bytes is not None:
        event["executable_bytes"] = int(executable_bytes)
    if cache_hit is not None:
        event["cache_hit"] = bool(cache_hit)
    event.update(extra)
    with _compile_lock:
        _compile_events.append(event)
    return event


def compile_events():
    """All compile events recorded in this process (copies)."""
    with _compile_lock:
        return [dict(e) for e in _compile_events]


def total_compile_s():
    """Total wall seconds this process spent in recorded compilations."""
    with _compile_lock:
        return sum(e["duration_s"] for e in _compile_events)

if get_env("MXNET_PROFILER_AUTOSTART", False, bool):
    profiler_set_state("run")
