"""Weight-only quantization of at-rest parameters: int8 and fp8 (e4m3).

Serving and the ZeRO-3 interchange spend their bytes on parameters at
rest — executable arguments, gather collectives, checkpoint tiles — not
on matmul math.  This module quantizes exactly that at-rest form:
weights are stored as int8 (symmetric, scale = amax / 127) or fp8
e4m3 (scale = amax / 448) with per-output-channel float32 scales, and
dequantized back to float32 right where compute needs them — inside the
traced serving functions (``serve/model.py``), inside the ZeRO-3 gather
bucket (``parallel/zero.py``), or at checkpoint restore
(``checkpoint._load_epoch``).  Matmuls always run full precision; only
storage and movement shrink (~4x for int8/fp8 vs float32).

Scale layout: for a canonical weight of shape ``(F, ...)`` (the
``FullyConnected`` ``(out, in)`` convention) the scale vector has one
entry per output channel ``F``, computed from the amax over the
remaining axes.  For the ZeRO-3 flat tiles the channel of flat index
``i`` is ``min(i // prod(shape[1:]), F - 1)`` — a pure function of the
CANONICAL shape, not of the tiling, so quantization commutes with the
flat-pad interchange: an N-way quantized tile save restores on M
replicas or unsharded bit-exactly (same stored codes, same scales).

Determinism contract: quantization is numpy ``rint``/cast arithmetic in
float32 (bit-stable across processes and runs), and dequantization is
an elementwise convert + multiply — the same IEEE ops whether executed
by numpy on the host or fused into an XLA executable.  That is what
lets the serving bit-exactness oracle work *per precision*: a quantized
session's decode step and its batched verify step dequantize to
identical weight values, so the M-invariant exact mode's guarantees
carry over unchanged (see ``serve/model.py``).

Eligibility: floating weights with ``ndim >= 2`` and at least
``MIN_QUANT_BYTES`` of storage.  Biases, LayerNorm vectors, and scalars
stay float32 — quantizing them saves nothing and costs accuracy.
"""
from __future__ import annotations

import contextlib
import functools
import math

from .base import MXNetError, get_env

__all__ = ["MODES", "quant_mode", "quant_dtype", "eligible",
           "quantize_array", "dequantize_array", "quantize_params",
           "dequantize_params", "is_quantized", "at_rest_bytes",
           "quantize_flat_leaf", "dequant_flat", "quantize_export",
           "dequantize_with_meta", "kv_quantize_rows", "kv_dequantize",
           "fp8_mode", "fp8_enabled", "fp8_layer_allowed", "fp8_trace",
           "fp8_tracing", "fp8_apply_dot", "fp8_hist_init",
           "fp8_realize_scales", "fp8_update_hist"]

MODES = ("int8", "fp8")
INT8_MAX = 127.0
FP8_MAX = 448.0  # float8_e4m3fn largest finite
FP8_E5M2_MAX = 57344.0  # float8_e5m2 largest finite (gradient format)
FP8_AMAX_HISTORY = 16  # delayed-scaling window (steps) per fp8 tensor
MIN_QUANT_BYTES = 1024


def quant_mode(mode):
    """Normalize a quant-mode spec: ``""``/``"off"``/``"none"``/``"0"``
    -> ``""`` (disabled), else one of :data:`MODES`."""
    raw = str(mode or "").strip().lower()
    if raw in ("", "off", "none", "0", "false", "fp32", "float32"):
        return ""
    if raw in ("int8", "i8"):
        return "int8"
    if raw in ("fp8", "f8", "e4m3", "float8", "float8_e4m3fn"):
        return "fp8"
    raise MXNetError("quant mode must be off|int8|fp8 (got %r)" % (mode,))


def quant_dtype(mode):
    """The storage numpy dtype for ``mode`` (``ml_dtypes`` supplies the
    fp8 e4m3 type, same as the PR 5 checkpoint dtype support)."""
    import numpy as np

    mode = quant_mode(mode)
    if mode == "int8":
        return np.dtype(np.int8)
    if mode == "fp8":
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn)
    raise MXNetError("quant_dtype: mode is off")


def _qmax(mode):
    return INT8_MAX if mode == "int8" else FP8_MAX


def eligible(shape, dtype, min_bytes=MIN_QUANT_BYTES):
    """Whether a canonical weight of ``shape``/``dtype`` is worth
    quantizing: floating, matrix-or-higher rank, and at least
    ``min_bytes`` of storage."""
    import numpy as np

    dtype = np.dtype(dtype)
    if dtype.kind != "f" or len(shape) < 2:
        return False
    size = int(math.prod(int(s) for s in shape)) if shape else 1
    return size * dtype.itemsize >= min_bytes


def _scales(amax, mode):
    """amax per channel -> float32 scales; all-zero channels get scale
    1.0 so dequantization never divides by (or multiplies into) zero."""
    import numpy as np

    qmax = _qmax(mode)
    amax = np.asarray(amax, np.float32)
    return np.where(amax > 0, amax / np.float32(qmax),
                    np.float32(1.0)).astype(np.float32)


def quantize_array(arr, mode):
    """Symmetric weight-only quantization of one canonical array.

    Returns ``(q, scale)``: ``q`` has the storage dtype and ``scale``
    is a broadcast-ready float32 array — per-output-channel (axis 0,
    shape ``(F, 1, ..., 1)``) for ``ndim >= 2``, per-tensor (shape
    ``()``) for vectors.  Pure numpy in float32: bit-stable across
    processes.
    """
    import numpy as np

    mode = quant_mode(mode)
    if not mode:
        raise MXNetError("quantize_array: mode is off")
    x = np.asarray(arr, np.float32)
    if x.ndim >= 2:
        axes = tuple(range(1, x.ndim))
        amax = np.max(np.abs(x), axis=axes, keepdims=True)
    else:
        amax = np.max(np.abs(x), keepdims=False) if x.size else 0.0
    scale = _scales(amax, mode)
    y = x / scale
    if mode == "int8":
        q = np.clip(np.rint(y), -INT8_MAX, INT8_MAX).astype(np.int8)
    else:
        q = np.clip(y, -FP8_MAX, FP8_MAX).astype(quant_dtype(mode))
    return q, scale


def dequantize_array(q, scale):
    """Elementwise convert + multiply back to float32.  Works on host
    numpy arrays and on jax values/tracers alike — the math is the same
    IEEE float32 ops either way, which is what keeps the host oracle
    and the in-graph dequantization bit-identical."""
    import numpy as np

    if isinstance(q, np.ndarray):
        return q.astype(np.float32) * np.asarray(scale, np.float32)
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale


def is_quantized(value):
    """Whether one params-tree value is a quantized ``{"q", "s"}``
    record rather than a plain array."""
    return isinstance(value, dict) and "q" in value and "s" in value


def quantize_params(params, mode, min_bytes=MIN_QUANT_BYTES):
    """Quantize a flat name->array params dict for serving: eligible
    weights become ``{"q": codes, "s": scales}`` sub-dicts (a plain
    pytree — avals, jit signatures, and donation all see through it),
    the rest pass through unchanged.  Leaves come back as jax arrays."""
    import jax.numpy as jnp

    mode = quant_mode(mode)
    if not mode:
        return dict(params)
    out = {}
    for name, v in params.items():
        if is_quantized(v):
            out[name] = v
            continue
        shape = tuple(getattr(v, "shape", ()))
        if eligible(shape, v.dtype, min_bytes):
            q, s = quantize_array(v, mode)
            out[name] = {"q": jnp.asarray(q), "s": jnp.asarray(s)}
        else:
            out[name] = v
    return out


def dequantize_params(params):
    """Resolve a (possibly quantized) params tree to plain float32
    arrays.  Traceable — the serving functions call this at the top so
    dequantization fuses into each executable; calling it eagerly gives
    the host-side oracle view, bit-identical by the determinism
    contract above."""
    out = {}
    for name, v in params.items():
        out[name] = dequantize_array(v["q"], v["s"]) if is_quantized(v) \
            else v
    return out


def at_rest_bytes(params):
    """Storage bytes of a params tree as held (codes + scales for
    quantized entries, full precision otherwise) — the at-rest memory
    claim the bench shrink ratios report."""
    import numpy as np

    total = 0
    for v in params.values():
        leaves = (v["q"], v["s"]) if is_quantized(v) else (v,)
        for leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            size = int(math.prod(int(s) for s in shape)) if shape else 1
            total += size * np.dtype(leaf.dtype).itemsize
    return total


# -- ZeRO-3 flat-tile interchange ------------------------------------------
#
# Flat tiles are canonical weights reshaped (-1,) and zero-padded to
# ``entry.padded`` (see parallel/zero.py).  The per-channel scale of flat
# index i is scale[min(i // row, F - 1)] with row = prod(shape[1:]) —
# padding lanes read the last channel's scale and hold zeros, so they
# quantize to 0 and dequantize to 0.0 regardless.

def _channel_index(entry):
    """Traceable (padded,) int32 channel index for one layout entry."""
    import jax.numpy as jnp

    shape = entry.shape
    row = max(1, int(math.prod(shape[1:])))
    channels = max(1, int(shape[0]) if shape else 1)
    idx = jnp.arange(entry.padded, dtype=jnp.int32) // row
    return jnp.minimum(idx, channels - 1)


def quantize_flat_leaf(leaf, entry, mode):
    """Quantize one at-rest flat tile (``(padded,)``, canonical order)
    with scales computed from the CANONICAL shape, so the result is
    independent of the save topology's padding.  Runs as jax ops (the
    leaf may be a sharded global array whose shards this process cannot
    np.asarray).  Returns ``(q_flat, scales)`` with ``scales`` a
    ``(F,)`` float32 vector."""
    import jax.numpy as jnp

    mode = quant_mode(mode)
    if not mode:
        raise MXNetError("quantize_flat_leaf: mode is off")
    canonical = jnp.reshape(leaf[:entry.logical], entry.shape)
    axes = tuple(range(1, len(entry.shape)))
    amax = jnp.max(jnp.abs(canonical.astype(jnp.float32)), axis=axes)
    qmax = _qmax(mode)
    scales = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    sflat = scales[_channel_index(entry)]
    y = leaf.astype(jnp.float32) / sflat
    if mode == "int8":
        q = jnp.clip(jnp.round(y), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    else:
        q = jnp.clip(y, -FP8_MAX, FP8_MAX).astype(quant_dtype(mode))
    return q, scales


def dequant_flat(flat, entry, scales):
    """Traceable dequantization of one full (gathered) flat tile —
    the ``gather_bucket`` hook: the collective moved 1-byte codes, this
    multiplies the replicated per-channel scales back in."""
    import jax.numpy as jnp

    sflat = jnp.asarray(scales, jnp.float32).reshape(-1)[
        _channel_index(entry)]
    return flat.astype(jnp.float32) * sflat


def quantize_export(zparams, mode, min_bytes=MIN_QUANT_BYTES):
    """Quantize a :func:`zero.export_params` descriptor for checkpoint
    save: eligible flat tiles swap their ``leaf`` for quantized codes
    and grow a ``quant`` record (mode + scales as a JSON-exact float
    list — float32 -> float64 -> float32 round-trips bitwise).  The
    restore path (:func:`dequantize_with_meta`) reverses it after the
    standard flat->canonical trim, so any topology — M replicas or
    unsharded — sees identical full-precision values."""
    import numpy as np

    mode = quant_mode(mode)
    if not mode:
        return zparams

    class _Ent(object):
        __slots__ = ("shape", "logical", "padded")

    out = {}
    for name, ent in zparams.items():
        shape = tuple(int(s) for s in ent["canonical_shape"])
        leaf = ent["leaf"]
        if not (ent.get("flat") and eligible(shape, leaf.dtype, min_bytes)):
            out[name] = ent
            continue
        e = _Ent()
        e.shape = shape
        e.logical = int(ent["logical"])
        e.padded = int(leaf.shape[0])
        q, scales = quantize_flat_leaf(leaf, e, mode)
        rec = dict(ent)
        rec["leaf"] = q
        rec["quant"] = {
            "mode": mode,
            "scales": [float(s) for s in
                       np.asarray(scales, np.float32).reshape(-1)],
        }
        out[name] = rec
    return out


def dequantize_with_meta(arr, qmeta):
    """Restore-side inverse of :func:`quantize_export`: ``arr`` is the
    trimmed canonical-shape array of codes, ``qmeta`` the manifest's
    ``quant`` record.  Host numpy, float32 out."""
    import numpy as np

    scales = np.asarray(qmeta["scales"], np.float32)
    scale = scales.reshape((scales.size,) + (1,) * (arr.ndim - 1))
    return np.asarray(arr).astype(np.float32) * scale


# -- quantized KV-cache pages ----------------------------------------------
#
# The serving KV pools store int8/e4m3 codes with ONE float32 scale per
# (layer, token) row, held in a parallel scale pool indexed by the same
# (page, offset) the codes are.  Row granularity is what keeps the
# per-precision bit-exactness oracle alive: a token's codes and scale
# are a pure elementwise function of that token's k/v values, so the
# prefill scatter, the serial decode append, and the batched verify
# append produce byte-identical pages for the same token — and a
# prefix-cache hit or a preempt/re-prefill replays them exactly.

def kv_quantize_rows(x, mode):
    """Traceable per-row KV quantization.

    ``x``: (..., H, D) float k or v rows.  Returns ``(codes, scales)``
    where ``codes`` has the storage dtype of ``mode`` and ``scales`` is
    float32 with the leading shape of ``x`` (amax over the trailing
    (H, D) axes; all-zero rows get scale 1.0).  Runs as jax ops so the
    quantize fuses into the append executable.
    """
    import jax.numpy as jnp

    mode = quant_mode(mode)
    if not mode:
        raise MXNetError("kv_quantize_rows: mode is off")
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=(-2, -1))
    qmax = _qmax(mode)
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    y = x32 / scale[..., None, None]
    if mode == "int8":
        q = jnp.clip(jnp.round(y), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    else:
        q = jnp.clip(y, -FP8_MAX, FP8_MAX).astype(quant_dtype(mode))
    return q, scale


def kv_dequantize(q, scale):
    """Inverse of :func:`kv_quantize_rows` on gathered context rows:
    ``q`` (..., T, H, D) codes, ``scale`` (..., T) float32.  Elementwise
    convert + multiply, so XLA fuses it into the attention consumer."""
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale[..., None, None]


# -- fp8 training compute (delayed scaling) --------------------------------
#
# Forward matmul operands cast to e4m3, backward cotangents to e5m2, as
# quantize-dequantize pairs in the compute dtype (portable across
# backends; on fp8-native hardware XLA folds the pair into a real fp8
# operand — tools/fusion_audit.py --expect-fp8 checks the converts
# stayed fused either way).  Activation/weight scales are DELAYED: each
# fp8 site keeps a per-tensor amax history that rides the TrainStep
# hstate (carried scan state, exactly like the dynamic loss scaler) and
# realizes its scale lazily as max(history)/FP8_MAX.  Gradient
# cotangents use per-call current-tensor scaling instead — their amax
# is consumed in the same custom-VJP backward that produces it, so no
# history round-trip (and no side channel out of the transpose trace)
# is needed, and e5m2's range makes the one-step lag moot.
#
# Sites are claimed in trace order from a trace-local context
# (:func:`fp8_trace`); the op registry's deterministic execution order
# makes the i-th claim the same tensor every trace, which is what lets
# the history live as one stacked (n_sites, 2, HISTORY) array in
# hstate.  New amaxes leave the trace as explicit aux outputs (never
# via Python side effects, which would leak tracers out of the grad
# transform).

_FP8_TRACE = None


class _Fp8Trace(object):
    """Per-trace fp8 site registry: realized scales in, amaxes out."""

    __slots__ = ("scales", "amax", "names")

    def __init__(self, scales=None):
        self.scales = scales  # (n_sites, 2) f32, or None (discovery)
        self.amax = []        # per-site (2,) f32 amax, trace order
        self.names = []       # site labels, trace order


@contextlib.contextmanager
def fp8_trace(scales=None):
    """Activate the fp8 fast path for ops traced inside the block.

    ``scales``: (n_sites, 2) float32 of realized (x, w) scales, or None
    for discovery / first step (sites run with scale 1.0).  Yields the
    context; read ``.amax`` (list of (2,) arrays, trace order) and
    ``.names`` after the forward ran and return them as aux outputs.
    """
    global _FP8_TRACE
    prev, _FP8_TRACE = _FP8_TRACE, _Fp8Trace(scales)
    try:
        yield _FP8_TRACE
    finally:
        _FP8_TRACE = prev


def fp8_tracing():
    """Whether an :func:`fp8_trace` context is active on this thread —
    the executor uses this to decide whether to thread node names into
    op attrs (clean traces keep their attrs, and jit cache keys,
    byte-identical to an fp8-free build)."""
    return _FP8_TRACE is not None


def fp8_mode():
    """Resolve ``MXNET_FP8`` to ``auto`` | ``on`` | ``off``."""
    raw = str(get_env("MXNET_FP8", "off") or "off").strip().lower()
    if raw in ("off", "0", "false", "no", ""):
        return "off"
    if raw in ("on", "1", "true", "yes"):
        return "on"
    if raw == "auto":
        return "auto"
    raise MXNetError("MXNET_FP8 must be auto|on|off (got %r)" % (raw,))


def fp8_enabled():
    """Whether the fp8 matmul route is armed for this trace: ``on``
    forces, ``off`` disables, ``auto`` arms only on backends with
    native fp8 matmul units (TPU/GPU) — CPU keeps the clean path."""
    mode = fp8_mode()
    if mode == "on":
        return True
    if mode == "off":
        return False
    import jax

    return jax.default_backend() in ("tpu", "gpu")


def fp8_layer_allowed(name):
    """Per-layer opt-out: ``MXNET_FP8_LAYERS`` empty allows every
    eligible site; a comma-separated list allows only sites whose label
    matches an entry exactly or by prefix (how the autotuner pins
    chosen layers to bf16 — see autotune.py's ``fp8_layers`` knob)."""
    spec = str(get_env("MXNET_FP8_LAYERS", "") or "").strip()
    if not spec:
        return True
    if not name:
        return False
    allowed = [t.strip() for t in spec.split(",") if t.strip()]
    return any(name == a or name.startswith(a) for a in allowed)


def fp8_hist_init(n_sites):
    """Zero-filled (n_sites, 2, FP8_AMAX_HISTORY) float32 amax history
    — the hstate leaf.  Zero history realizes scale 1.0 (the safe
    first-step default; real amaxes take over from step 2)."""
    import jax.numpy as jnp

    return jnp.zeros((int(n_sites), 2, FP8_AMAX_HISTORY), jnp.float32)


def fp8_realize_scales(hist):
    """Lazily realize per-tensor scales from the amax history:
    ``max(history) / FP8_MAX`` per (site, operand), 1.0 where the
    history is still empty."""
    import jax.numpy as jnp

    hmax = jnp.max(hist, axis=-1)
    return jnp.where(hmax > 0, hmax / FP8_MAX, 1.0).astype(jnp.float32)


def fp8_update_hist(hist, new_amax):
    """Roll the history one step: the fresh (n_sites, 2) amaxes enter
    at slot 0, the oldest falls off."""
    import jax.numpy as jnp

    new = jnp.asarray(new_amax, jnp.float32)[..., None]
    return jnp.concatenate([new, hist[..., :-1]], axis=-1)


def _fake_cast(x, scale, qmax, dtype):
    """Quantize-dequantize ``x`` through ``dtype`` at ``scale``: the
    numerics of an fp8 tensor without leaving float32."""
    import jax.numpy as jnp

    y = jnp.clip(x.astype(jnp.float32) / scale, -qmax, qmax)
    return y.astype(dtype).astype(jnp.float32) * scale


@functools.lru_cache(maxsize=None)
def _fp8_dot_fn(w_dim):
    """Custom-VJP fp8 contraction of ``x`` (..., C) with 2-D ``w``
    along ``w``'s axis ``w_dim``.  Forward: both operands fake-cast to
    e4m3 at the delayed scales.  Backward: the cotangent fake-casts to
    e5m2 at its own current amax, then contracts against the SAVED
    e4m3 operands (the standard fp8 training recipe).  Scale args get
    zero cotangents — they are statistics, not parameters."""
    import jax
    import jax.numpy as jnp
    import ml_dtypes

    e4m3 = quant_dtype("fp8")
    e5m2 = ml_dtypes.float8_e5m2

    def _cast_pair(x, w, sx, sw):
        xq = _fake_cast(x, sx, FP8_MAX, e4m3).astype(x.dtype)
        wq = _fake_cast(w, sw, FP8_MAX, e4m3).astype(w.dtype)
        return xq, wq

    def _contract(x, w):
        return jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (w_dim,)), ((), ())))

    @jax.custom_vjp
    def fp8_dot(x, w, sx, sw):
        xq, wq = _cast_pair(x, w, sx, sw)
        return _contract(xq, wq)

    def fwd(x, w, sx, sw):
        xq, wq = _cast_pair(x, w, sx, sw)
        return _contract(xq, wq), (xq, wq)

    def bwd(res, g):
        xq, wq = res
        amax_g = jnp.max(jnp.abs(g.astype(jnp.float32)))
        sg = jnp.where(amax_g > 0, amax_g / FP8_E5M2_MAX, 1.0)
        gq = _fake_cast(g, sg, FP8_E5M2_MAX, e5m2).astype(g.dtype)
        # dx: contract g's output axis with w's other axis
        dx = jax.lax.dot_general(
            gq, wq, (((gq.ndim - 1,), (1 - w_dim,)), ((), ()))
        ).astype(xq.dtype)
        g2 = gq.reshape(-1, gq.shape[-1])
        x2 = xq.reshape(-1, xq.shape[-1])
        dw = jax.lax.dot_general(g2, x2, (((0,), (0,)), ((), ())))
        if w_dim == 0:  # w is (C, F): dw above is (F, C) — transpose
            dw = dw.T
        return dx, dw.astype(wq.dtype), jnp.zeros_like(sg), \
            jnp.zeros_like(sg)

    fp8_dot.defvjp(fwd, bwd)
    return fp8_dot


def fp8_apply_dot(x, w, label=None, w_dim=1):
    """The fp8 matmul route for one op site, or ``None`` to keep the
    full-precision path (fp8 inactive for this trace, the layer opted
    out, or the shapes do not fit the 2-D weight contraction).

    Claims the next site in trace order, records the operands' current
    amaxes into the context (they leave the trace as aux outputs and
    roll the hstate history), and contracts ``x`` (..., C) against the
    2-D ``w`` along ``w_dim`` through the custom-VJP fp8 kernel.
    """
    t = _FP8_TRACE
    if t is None:
        return None
    if not fp8_layer_allowed(label):
        return None
    if getattr(w, "ndim", 0) != 2 or getattr(x, "ndim", 0) < 1:
        return None
    if x.shape[-1] != w.shape[w_dim]:
        return None
    import jax.numpy as jnp

    i = len(t.names)
    t.names.append(label or ("site%d" % i))
    ax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    aw = jnp.max(jnp.abs(w.astype(jnp.float32)))
    t.amax.append(jnp.stack([ax, aw]))
    if t.scales is None:
        sx = sw = jnp.float32(1.0)
    else:
        sx, sw = t.scales[i, 0], t.scales[i, 1]
    return _fp8_dot_fn(int(w_dim))(x, w, sx, sw)
