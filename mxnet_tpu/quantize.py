"""Weight-only quantization of at-rest parameters: int8 and fp8 (e4m3).

Serving and the ZeRO-3 interchange spend their bytes on parameters at
rest — executable arguments, gather collectives, checkpoint tiles — not
on matmul math.  This module quantizes exactly that at-rest form:
weights are stored as int8 (symmetric, scale = amax / 127) or fp8
e4m3 (scale = amax / 448) with per-output-channel float32 scales, and
dequantized back to float32 right where compute needs them — inside the
traced serving functions (``serve/model.py``), inside the ZeRO-3 gather
bucket (``parallel/zero.py``), or at checkpoint restore
(``checkpoint._load_epoch``).  Matmuls always run full precision; only
storage and movement shrink (~4x for int8/fp8 vs float32).

Scale layout: for a canonical weight of shape ``(F, ...)`` (the
``FullyConnected`` ``(out, in)`` convention) the scale vector has one
entry per output channel ``F``, computed from the amax over the
remaining axes.  For the ZeRO-3 flat tiles the channel of flat index
``i`` is ``min(i // prod(shape[1:]), F - 1)`` — a pure function of the
CANONICAL shape, not of the tiling, so quantization commutes with the
flat-pad interchange: an N-way quantized tile save restores on M
replicas or unsharded bit-exactly (same stored codes, same scales).

Determinism contract: quantization is numpy ``rint``/cast arithmetic in
float32 (bit-stable across processes and runs), and dequantization is
an elementwise convert + multiply — the same IEEE ops whether executed
by numpy on the host or fused into an XLA executable.  That is what
lets the serving bit-exactness oracle work *per precision*: a quantized
session's decode step and its batched verify step dequantize to
identical weight values, so the M-invariant exact mode's guarantees
carry over unchanged (see ``serve/model.py``).

Eligibility: floating weights with ``ndim >= 2`` and at least
``MIN_QUANT_BYTES`` of storage.  Biases, LayerNorm vectors, and scalars
stay float32 — quantizing them saves nothing and costs accuracy.
"""
from __future__ import annotations

import math

from .base import MXNetError

__all__ = ["MODES", "quant_mode", "quant_dtype", "eligible",
           "quantize_array", "dequantize_array", "quantize_params",
           "dequantize_params", "is_quantized", "at_rest_bytes",
           "quantize_flat_leaf", "dequant_flat", "quantize_export",
           "dequantize_with_meta"]

MODES = ("int8", "fp8")
INT8_MAX = 127.0
FP8_MAX = 448.0  # float8_e4m3fn largest finite
MIN_QUANT_BYTES = 1024


def quant_mode(mode):
    """Normalize a quant-mode spec: ``""``/``"off"``/``"none"``/``"0"``
    -> ``""`` (disabled), else one of :data:`MODES`."""
    raw = str(mode or "").strip().lower()
    if raw in ("", "off", "none", "0", "false", "fp32", "float32"):
        return ""
    if raw in ("int8", "i8"):
        return "int8"
    if raw in ("fp8", "f8", "e4m3", "float8", "float8_e4m3fn"):
        return "fp8"
    raise MXNetError("quant mode must be off|int8|fp8 (got %r)" % (mode,))


def quant_dtype(mode):
    """The storage numpy dtype for ``mode`` (``ml_dtypes`` supplies the
    fp8 e4m3 type, same as the PR 5 checkpoint dtype support)."""
    import numpy as np

    mode = quant_mode(mode)
    if mode == "int8":
        return np.dtype(np.int8)
    if mode == "fp8":
        import ml_dtypes

        return np.dtype(ml_dtypes.float8_e4m3fn)
    raise MXNetError("quant_dtype: mode is off")


def _qmax(mode):
    return INT8_MAX if mode == "int8" else FP8_MAX


def eligible(shape, dtype, min_bytes=MIN_QUANT_BYTES):
    """Whether a canonical weight of ``shape``/``dtype`` is worth
    quantizing: floating, matrix-or-higher rank, and at least
    ``min_bytes`` of storage."""
    import numpy as np

    dtype = np.dtype(dtype)
    if dtype.kind != "f" or len(shape) < 2:
        return False
    size = int(math.prod(int(s) for s in shape)) if shape else 1
    return size * dtype.itemsize >= min_bytes


def _scales(amax, mode):
    """amax per channel -> float32 scales; all-zero channels get scale
    1.0 so dequantization never divides by (or multiplies into) zero."""
    import numpy as np

    qmax = _qmax(mode)
    amax = np.asarray(amax, np.float32)
    return np.where(amax > 0, amax / np.float32(qmax),
                    np.float32(1.0)).astype(np.float32)


def quantize_array(arr, mode):
    """Symmetric weight-only quantization of one canonical array.

    Returns ``(q, scale)``: ``q`` has the storage dtype and ``scale``
    is a broadcast-ready float32 array — per-output-channel (axis 0,
    shape ``(F, 1, ..., 1)``) for ``ndim >= 2``, per-tensor (shape
    ``()``) for vectors.  Pure numpy in float32: bit-stable across
    processes.
    """
    import numpy as np

    mode = quant_mode(mode)
    if not mode:
        raise MXNetError("quantize_array: mode is off")
    x = np.asarray(arr, np.float32)
    if x.ndim >= 2:
        axes = tuple(range(1, x.ndim))
        amax = np.max(np.abs(x), axis=axes, keepdims=True)
    else:
        amax = np.max(np.abs(x), keepdims=False) if x.size else 0.0
    scale = _scales(amax, mode)
    y = x / scale
    if mode == "int8":
        q = np.clip(np.rint(y), -INT8_MAX, INT8_MAX).astype(np.int8)
    else:
        q = np.clip(y, -FP8_MAX, FP8_MAX).astype(quant_dtype(mode))
    return q, scale


def dequantize_array(q, scale):
    """Elementwise convert + multiply back to float32.  Works on host
    numpy arrays and on jax values/tracers alike — the math is the same
    IEEE float32 ops either way, which is what keeps the host oracle
    and the in-graph dequantization bit-identical."""
    import numpy as np

    if isinstance(q, np.ndarray):
        return q.astype(np.float32) * np.asarray(scale, np.float32)
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale


def is_quantized(value):
    """Whether one params-tree value is a quantized ``{"q", "s"}``
    record rather than a plain array."""
    return isinstance(value, dict) and "q" in value and "s" in value


def quantize_params(params, mode, min_bytes=MIN_QUANT_BYTES):
    """Quantize a flat name->array params dict for serving: eligible
    weights become ``{"q": codes, "s": scales}`` sub-dicts (a plain
    pytree — avals, jit signatures, and donation all see through it),
    the rest pass through unchanged.  Leaves come back as jax arrays."""
    import jax.numpy as jnp

    mode = quant_mode(mode)
    if not mode:
        return dict(params)
    out = {}
    for name, v in params.items():
        if is_quantized(v):
            out[name] = v
            continue
        shape = tuple(getattr(v, "shape", ()))
        if eligible(shape, v.dtype, min_bytes):
            q, s = quantize_array(v, mode)
            out[name] = {"q": jnp.asarray(q), "s": jnp.asarray(s)}
        else:
            out[name] = v
    return out


def dequantize_params(params):
    """Resolve a (possibly quantized) params tree to plain float32
    arrays.  Traceable — the serving functions call this at the top so
    dequantization fuses into each executable; calling it eagerly gives
    the host-side oracle view, bit-identical by the determinism
    contract above."""
    out = {}
    for name, v in params.items():
        out[name] = dequantize_array(v["q"], v["s"]) if is_quantized(v) \
            else v
    return out


def at_rest_bytes(params):
    """Storage bytes of a params tree as held (codes + scales for
    quantized entries, full precision otherwise) — the at-rest memory
    claim the bench shrink ratios report."""
    import numpy as np

    total = 0
    for v in params.values():
        leaves = (v["q"], v["s"]) if is_quantized(v) else (v,)
        for leaf in leaves:
            shape = tuple(getattr(leaf, "shape", ()))
            size = int(math.prod(int(s) for s in shape)) if shape else 1
            total += size * np.dtype(leaf.dtype).itemsize
    return total


# -- ZeRO-3 flat-tile interchange ------------------------------------------
#
# Flat tiles are canonical weights reshaped (-1,) and zero-padded to
# ``entry.padded`` (see parallel/zero.py).  The per-channel scale of flat
# index i is scale[min(i // row, F - 1)] with row = prod(shape[1:]) —
# padding lanes read the last channel's scale and hold zeros, so they
# quantize to 0 and dequantize to 0.0 regardless.

def _channel_index(entry):
    """Traceable (padded,) int32 channel index for one layout entry."""
    import jax.numpy as jnp

    shape = entry.shape
    row = max(1, int(math.prod(shape[1:])))
    channels = max(1, int(shape[0]) if shape else 1)
    idx = jnp.arange(entry.padded, dtype=jnp.int32) // row
    return jnp.minimum(idx, channels - 1)


def quantize_flat_leaf(leaf, entry, mode):
    """Quantize one at-rest flat tile (``(padded,)``, canonical order)
    with scales computed from the CANONICAL shape, so the result is
    independent of the save topology's padding.  Runs as jax ops (the
    leaf may be a sharded global array whose shards this process cannot
    np.asarray).  Returns ``(q_flat, scales)`` with ``scales`` a
    ``(F,)`` float32 vector."""
    import jax.numpy as jnp

    mode = quant_mode(mode)
    if not mode:
        raise MXNetError("quantize_flat_leaf: mode is off")
    canonical = jnp.reshape(leaf[:entry.logical], entry.shape)
    axes = tuple(range(1, len(entry.shape)))
    amax = jnp.max(jnp.abs(canonical.astype(jnp.float32)), axis=axes)
    qmax = _qmax(mode)
    scales = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    sflat = scales[_channel_index(entry)]
    y = leaf.astype(jnp.float32) / sflat
    if mode == "int8":
        q = jnp.clip(jnp.round(y), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    else:
        q = jnp.clip(y, -FP8_MAX, FP8_MAX).astype(quant_dtype(mode))
    return q, scales


def dequant_flat(flat, entry, scales):
    """Traceable dequantization of one full (gathered) flat tile —
    the ``gather_bucket`` hook: the collective moved 1-byte codes, this
    multiplies the replicated per-channel scales back in."""
    import jax.numpy as jnp

    sflat = jnp.asarray(scales, jnp.float32).reshape(-1)[
        _channel_index(entry)]
    return flat.astype(jnp.float32) * sflat


def quantize_export(zparams, mode, min_bytes=MIN_QUANT_BYTES):
    """Quantize a :func:`zero.export_params` descriptor for checkpoint
    save: eligible flat tiles swap their ``leaf`` for quantized codes
    and grow a ``quant`` record (mode + scales as a JSON-exact float
    list — float32 -> float64 -> float32 round-trips bitwise).  The
    restore path (:func:`dequantize_with_meta`) reverses it after the
    standard flat->canonical trim, so any topology — M replicas or
    unsharded — sees identical full-precision values."""
    import numpy as np

    mode = quant_mode(mode)
    if not mode:
        return zparams

    class _Ent(object):
        __slots__ = ("shape", "logical", "padded")

    out = {}
    for name, ent in zparams.items():
        shape = tuple(int(s) for s in ent["canonical_shape"])
        leaf = ent["leaf"]
        if not (ent.get("flat") and eligible(shape, leaf.dtype, min_bytes)):
            out[name] = ent
            continue
        e = _Ent()
        e.shape = shape
        e.logical = int(ent["logical"])
        e.padded = int(leaf.shape[0])
        q, scales = quantize_flat_leaf(leaf, e, mode)
        rec = dict(ent)
        rec["leaf"] = q
        rec["quant"] = {
            "mode": mode,
            "scales": [float(s) for s in
                       np.asarray(scales, np.float32).reshape(-1)],
        }
        out[name] = rec
    return out


def dequantize_with_meta(arr, qmeta):
    """Restore-side inverse of :func:`quantize_export`: ``arr`` is the
    trimmed canonical-shape array of codes, ``qmeta`` the manifest's
    ``quant`` record.  Host numpy, float32 out."""
    import numpy as np

    scales = np.asarray(qmeta["scales"], np.float32)
    scale = scales.reshape((scales.size,) + (1,) * (arr.ndim - 1))
    return np.asarray(arr).astype(np.float32) * scale
