"""Global random state.

Replaces the reference's per-device PRNG resource
(``ResourceManagerImpl``/``ResourceRequest::kRandom``, ``src/resource.cc``;
Python ``mxnet/random.py`` ``mx.random.seed``).  A single counter-split
``jax.random`` key chain provides deterministic, replayable streams: every
consumer takes a fresh key via :func:`next_key`, and the autograd tape
records the key it used so backward replay is bit-identical.
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "current_seed"]

_state = threading.local()
_DEFAULT_SEED = 0


def _get():
    if not hasattr(_state, "key"):
        import jax

        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
        _state.seed_value = _DEFAULT_SEED
    return _state


def seed(seed_state):
    """Seed all random generators (reference ``mx.random.seed``)."""
    import jax

    st = _get()
    st.key = jax.random.PRNGKey(int(seed_state))
    st.seed_value = int(seed_state)


def current_seed():
    return _get().seed_value


def next_key():
    """Split one fresh PRNG key off the global chain."""
    import jax

    st = _get()
    st.key, sub = jax.random.split(st.key)
    return sub
