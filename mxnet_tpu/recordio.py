"""RecordIO — binary record pack format + sequential/indexed readers.

Reference: ``python/mxnet/recordio.py`` over dmlc-core's recordio
(``dmlc::RecordIOWriter/Reader``; SURVEY.md §2.1 Data IO).  The wire
format is dmlc's: each record is framed as

    uint32 magic = 0xced7230a
    uint32 lrec  = (cflag << 29) | payload_length
    payload bytes, zero-padded to a 4-byte boundary

``cflag`` marks continuation pieces of records that contain the magic in
their payload (dmlc splits those); this implementation writes complete
records (cflag 0) and reassembles split records (1=start/2=middle/3=end)
on read, so files produced by the reference C++ writer load correctly.

``MXIndexedRecordIO`` adds the reference's ``.idx`` sidecar ("key\\toffset"
per line) for O(1) ``read_idx`` — the random-access substrate for
shuffled/sharded ``ImageRecordIter`` epochs.

The image payload convention (``IRHeader`` + ``pack``/``unpack``) matches
the reference exactly: a little-endian ``IfQQ`` header (flag, label, id,
id2); when ``flag > 0`` the label is a float vector of that length stored
after the header.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_KMAGIC = 0xced7230a
_STRUCT_U32 = struct.Struct("<I")

IRHeader = namedtuple("HEADER", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential reader/writer (reference ``MXRecordIO``)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self._fp = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self._fp = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self._fp.close()
            self.is_open = False

    def reset(self):
        """Reset the read head to the start (reference semantics: close +
        reopen)."""
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if isinstance(buf, str):
            buf = buf.encode("utf-8")
        lrec = len(buf)  # cflag 0: complete record
        self._fp.write(_STRUCT_U32.pack(_KMAGIC))
        self._fp.write(_STRUCT_U32.pack(lrec))
        self._fp.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self._fp.write(b"\x00" * pad)

    def _read_one(self):
        head = self._fp.read(4)
        if len(head) < 4:
            return None, None
        magic = _STRUCT_U32.unpack(head)[0]
        if magic != _KMAGIC:
            raise MXNetError("invalid RecordIO magic 0x%08x in %s"
                             % (magic, self.uri))
        lrec = _STRUCT_U32.unpack(self._fp.read(4))[0]
        cflag, length = lrec >> 29, lrec & ((1 << 29) - 1)
        data = self._fp.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self._fp.read(pad)
        return cflag, data

    def read(self):
        """Read one logical record; returns bytes or None at EOF."""
        assert not self.writable
        cflag, data = self._read_one()
        if cflag is None:
            return None
        if cflag == 0:
            return data
        if cflag != 1:
            raise MXNetError("corrupt RecordIO: unexpected cflag %d" % cflag)
        parts = [data]
        while True:
            cflag, data = self._read_one()
            if cflag is None:
                raise MXNetError("corrupt RecordIO: truncated split record")
            parts.append(data)
            if cflag == 3:
                return b"".join(parts)
            if cflag != 2:
                raise MXNetError("corrupt RecordIO: unexpected cflag %d"
                                 % cflag)

    def tell(self):
        assert self.writable
        return self._fp.tell()

    def __del__(self):
        self.close()

    def _reopen_read(self, offset=0):
        """(Re)open the underlying file read-only at ``offset``, bypassing
        :meth:`open` so subclass index state survives.  Two callers: the
        unpickle path below, and post-fork re-arm — a forked decode worker
        inherits the parent's open file *description*, so seeks in the
        child would race the parent's reads until the child re-opens
        privately."""
        if self.is_open:
            self._fp.close()
        self._fp = open(self.uri, "rb")
        if offset:
            self._fp.seek(offset)
        self.writable = False
        self.is_open = True

    # -- pickling: read handles survive the trip into decode worker
    # processes, resuming at the byte offset they were pickled at.
    def __getstate__(self):
        if self.is_open and self.writable:
            raise MXNetError(
                "cannot pickle a writable MXRecordIO handle for %s: the "
                "restored copy would have to reopen with 'w' and truncate "
                "the file; close() it first" % self.uri)
        d = dict(self.__dict__)
        d["_pickle_offset"] = self._fp.tell() if self.is_open else 0
        d["is_open"] = False
        d.pop("_fp", None)
        return d

    def __setstate__(self, d):
        offset = d.pop("_pickle_offset", 0)
        self.__dict__.update(d)
        # readers reopen in place at the saved offset; a pickled *closed*
        # writer stays closed (the old behavior of calling open() here
        # would have truncated the file on restore)
        if self.flag == "r":
            self._reopen_read(offset)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access reader/writer with an index sidecar (reference
    ``MXIndexedRecordIO``).

    Read handles pickle like the base class, and the in-memory index
    (``idx``/``keys``) travels inside the pickle — the restored reader is
    immediately ``read_idx``-able with no sidecar re-read or frame
    rescan, even if the ``.idx`` file has since disappeared."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if self.writable:
            self.fidx = open(self.idx_path, "w")
        else:
            self.fidx = None
            if os.path.exists(self.idx_path):
                with open(self.idx_path) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) != 2:
                            continue
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
            else:
                # no sidecar: index the frames with the native C++
                # scanner (src/recordio.cc); keys become 0..n-1 — the
                # im2rec convention.  Pure-Python fallback scans via
                # sequential read().
                self._build_index_by_scan()

    def _build_index_by_scan(self):
        from ._native import scan_recordio

        scanned = scan_recordio(self.uri)
        if scanned is not None:
            offsets, _lengths = scanned
            for i, off in enumerate(offsets):
                key = self.key_type(i)
                self.idx[key] = off
                self.keys.append(key)
            return
        # fallback: one sequential pass with the Python reader
        i = 0
        while True:
            pos = self._fp.tell()
            if self.read() is None:
                break
            key = self.key_type(i)
            self.idx[key] = pos
            self.keys.append(key)
            i += 1
        self.reset()

    def close(self):
        if self.is_open:
            super().close()
            if getattr(self, "fidx", None) is not None:
                self.fidx.close()
                self.fidx = None

    def seek(self, idx):
        assert not self.writable
        self._fp.seek(self.idx[idx])

    def tell(self):
        return self._fp.tell()

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


# -- image-record payloads (reference pack/unpack) --------------------------

def pack(header, s):
    """Prepend an IRHeader to raw bytes (reference ``recordio.pack``)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        out = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        out = struct.pack(_IR_FORMAT, label.size, 0.0,
                          header.id, header.id2) + label.tobytes()
    if isinstance(s, str):
        s = s.encode("utf-8")
    return out + s


def unpack(s):
    """Split an image record into (IRHeader, payload bytes)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Encode an HWC uint8 RGB array and pack it (reference ``pack_img``;
    divergence: encoding uses PIL, arrays are RGB — the reference's cv2
    path stores BGR.  Files written and read by THIS library round-trip;
    reading reference-written records through ``unpack_img`` yields
    channel-swapped data unless the caller flips)."""
    import io as _io

    from PIL import Image

    img = np.asarray(img, dtype=np.uint8)
    if img.ndim == 3 and img.shape[2] == 1:
        img = img[:, :, 0]  # PIL cannot handle (H, W, 1)
    fmt = img_fmt.lower().lstrip(".")
    fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}.get(fmt)
    if fmt is None:
        raise MXNetError("unsupported image format %r" % img_fmt)
    buf = _io.BytesIO()
    Image.fromarray(img).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def unpack_img(s, iscolor=1):
    """Unpack to (IRHeader, HWC uint8 array) (reference ``unpack_img``)."""
    import io as _io

    from PIL import Image

    header, payload = unpack(s)
    img = Image.open(_io.BytesIO(payload))
    img = img.convert("RGB" if iscolor else "L")
    return header, np.asarray(img)
