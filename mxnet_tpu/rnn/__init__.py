"""Symbolic RNN toolkit (reference ``python/mxnet/rnn/``)."""
from .rnn_cell import (RNNParams, BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       DropoutCell, ModifierCell, ZoneoutCell, ResidualCell)
from .io import encode_sentences, BucketSentenceIter
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)
