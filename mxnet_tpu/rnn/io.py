"""Bucketed sequence iterators (reference ``python/mxnet/rnn/io.py``)."""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["encode_sentences", "BucketSentenceIter"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Map token sequences to integer ids, building a vocab (reference
    ``encode_sentences``)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    coded.append(invalid_label)
                    continue
                if idx == invalid_label:
                    idx += 1
                vocab[word] = idx
                idx += 1
            if word in vocab:
                coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Bucketed iterator over variable-length sequences (reference
    ``BucketSentenceIter``): each batch comes from one bucket, padded to
    the bucket length, with ``batch.bucket_key`` driving
    ``BucketingModule``'s per-length executor selection."""

    def __init__(self, sentences, batch_size, buckets=None,
                 invalid_label=-1, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 layout="NT", seed=0):
        super().__init__(batch_size)
        # per-instance stream: shuffle order is a pure function of
        # (seed, reset count), independent of global-RNG call order
        self._rng = np.random.RandomState(seed)
        if not buckets:
            counts = np.bincount([len(s) for s in sentences])
            buckets = [i for i, n in enumerate(counts)
                       if n >= batch_size]
        buckets.sort()
        if not buckets:
            raise MXNetError("no bucket holds >= batch_size sentences; "
                             "pass buckets explicitly")
        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        self.data = [np.asarray(x, dtype=dtype).reshape(-1, buckets[i])
                     for i, x in enumerate(self.data)]
        if ndiscard:
            import logging

            logging.getLogger(__name__).warning(
                "discarded %d sentences longer than the largest bucket",
                ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.invalid_label = invalid_label
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.layout = layout
        self.default_bucket_key = max(buckets)

        shape = (batch_size, self.default_bucket_key) if layout == "NT" \
            else (self.default_bucket_key, batch_size)
        self.provide_data = [DataDesc(data_name, shape, dtype,
                                      layout=layout)]
        self.provide_label = [DataDesc(label_name, shape, dtype,
                                       layout=layout)]
        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in
                             range(0, len(buck) - batch_size + 1,
                                   batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        self._rng.shuffle(self.idx)
        for buck in self.data:
            self._rng.shuffle(buck)
        # label = data shifted left by one (next-token prediction)
        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(buck)
            self.ndlabel.append(label)

    def next(self):
        from ..ndarray import array

        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1
        data = self.nddata[i][j:j + self.batch_size]
        label = self.ndlabel[i][j:j + self.batch_size]
        if self.layout == "TN":
            data, label = data.T, label.T
        shape = data.shape
        return DataBatch([array(data)], [array(label)], pad=0,
                         bucket_key=self.buckets[i],
                         provide_data=[DataDesc(self.data_name, shape,
                                                self.dtype,
                                                layout=self.layout)],
                         provide_label=[DataDesc(self.label_name, shape,
                                                 self.dtype,
                                                 layout=self.layout)])
