"""RNN checkpoint helpers (reference ``python/mxnet/rnn/rnn.py``):
save/load fused-cell checkpoints with weights unpacked to per-gate
matrices so unfused and fused models interoperate."""
from __future__ import annotations

from .. import model
from ..base import MXNetError

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint",
           "do_rnn_checkpoint"]


def _as_cells(cells):
    return cells if isinstance(cells, (list, tuple)) else [cells]


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params,
                        aux_params):
    """Save a checkpoint with RNN weights unpacked (reference
    ``save_rnn_checkpoint``)."""
    for cell in _as_cells(cells):
        arg_params = cell.unpack_weights(arg_params)
    model.save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Load a checkpoint and re-pack RNN weights for the given cells."""
    sym, arg, aux = model.load_checkpoint(prefix, epoch)
    for cell in _as_cells(cells):
        arg = cell.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback variant (reference ``do_rnn_checkpoint``)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
