"""Symbolic RNN cells (reference ``python/mxnet/rnn/rnn_cell.py``).

The toolkit the reference's LSTM-PTB / bucketing examples are written
against: composable cells with shared :class:`RNNParams`, ``unroll`` into
a symbol graph, ``FusedRNNCell`` over the fused ``RNN`` op (one
``lax.scan`` per layer/direction on TPU — ``ops/rnn_ops.py``), and
``unfuse()``/``unpack_weights``/``pack_weights`` for moving parameters
between the fused blob and per-cell matrices.

Divergence from the reference: ``begin_state``'s deferred-shape
``sym.zeros(shape=(0, h))`` idiom needs dynamic shape inference that XLA
does not do; instead, default initial states are built with the
``_state_zeros`` op, which takes its batch dimension from the input
symbol at bind time.  ``begin_state()`` therefore needs an input symbol
(``unroll`` passes one automatically) or an explicit ``batch_size``.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import symbol as sym
from ..ops.rnn_ops import rnn_gates, rnn_param_size

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "DropoutCell", "ModifierCell", "ZoneoutCell", "ResidualCell"]


class RNNParams:
    """Container for cell parameter symbols, shared by name (reference
    ``RNNParams``)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell (reference ``BaseRNNCell``)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        """List of dicts: [{'shape': (0, h), '__layout__': 'NC'}, ...]
        (0 = batch, filled at bind)."""
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, batch_ref=None, batch_size=0,
                    **kwargs):
        """Initial states.  ``batch_ref`` (a symbol whose dim 0 is the
        batch) or ``batch_size`` supplies the batch dimension; ``func``
        overrides the zero-fill (signature ``func(name=..., shape=...)``,
        requires batch_size)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called "\
            "directly. Call the modifier cell instead."
        states = []
        for info in self.state_info:
            self._init_counter += 1
            shape = info["shape"]
            if func is not None:
                if batch_size <= 0:
                    raise MXNetError(
                        "begin_state with a custom func needs batch_size")
                states.append(func(
                    name="%sbegin_state_%d" % (self._prefix,
                                               self._init_counter),
                    shape=(batch_size,) + tuple(shape[1:])))
                continue
            if batch_ref is None:
                if batch_size <= 0:
                    raise MXNetError(
                        "begin_state needs batch_ref or batch_size (the "
                        "reference's shape=(0,...) deferred inference is "
                        "not available under static shapes)")
                states.append(sym.zeros(
                    shape=(batch_size,) + tuple(shape[1:]),
                    name="%sbegin_state_%d" % (self._prefix,
                                               self._init_counter)))
                continue
            if len(shape) == 3:  # fused stacked state (L*D, N, H)
                states.append(sym._state_zeros(
                    batch_ref, num_hidden=shape[2], leading=shape[0],
                    batch_axis1=kwargs.get("batch_axis1", False)))
            else:
                states.append(sym._state_zeros(
                    batch_ref, num_hidden=shape[-1],
                    batch_axis1=kwargs.get("batch_axis1", False)))
        return states

    def unpack_weights(self, args):
        """Split concatenated gate weights into per-gate entries
        (reference contract: '{prefix}{i2h|h2h}_{gate}_{weight|bias}')."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group in ("i2h", "h2h"):
            weight = args.pop("%s%s_weight" % (self._prefix, group))
            bias = args.pop("%s%s_bias" % (self._prefix, group))
            for j, gate in enumerate(self._gate_names):
                wname = "%s%s%s_weight" % (self._prefix, group, gate)
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = "%s%s%s_bias" % (self._prefix, group, gate)
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        args = dict(args)
        if not self._gate_names:
            return args
        from ..ndarray import concat

        for group in ("i2h", "h2h"):
            weight = []
            bias = []
            for gate in self._gate_names:
                weight.append(args.pop("%s%s%s_weight"
                                       % (self._prefix, group, gate)))
                bias.append(args.pop("%s%s%s_bias"
                                     % (self._prefix, group, gate)))
            args["%s%s_weight" % (self._prefix, group)] = concat(
                *weight, dim=0)
            args["%s%s_bias" % (self._prefix, group)] = concat(
                *bias, dim=0)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell for ``length`` steps (reference
        ``BaseRNNCell.unroll``).

        ``inputs``: one (N,T,C)/(T,N,C) symbol or a list of ``length``
        (N,C) symbols.  Returns (outputs, states) with outputs merged to
        one symbol when ``merge_outputs`` is True.
        """
        self.reset()
        inputs, batch_ref, batch_axis1 = _normalize_sequence(
            length, inputs, layout, merge=False)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=batch_ref,
                                           batch_axis1=batch_axis1)
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs:
            outputs = _merge_sequence(outputs, layout)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return sym.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """Split a merged sequence symbol into per-step symbols (or keep a
    list).  Returns (step_symbols, batch_ref_symbol, batch_axis1) where
    ``batch_axis1`` says the batch rides axis 1 of ``batch_ref`` (TNC
    merged inputs)."""
    axis = layout.find("T")
    if isinstance(inputs, sym.Symbol):
        steps = sym.SliceChannel(inputs, num_outputs=length, axis=axis,
                                 squeeze_axis=1)
        return [steps[i] for i in range(length)], inputs, \
            layout.find("N") == 1
    if len(inputs) != length:
        raise MXNetError("unroll doesn't support dynamic lengths: got %d "
                         "inputs for length %d" % (len(inputs), length))
    return list(inputs), inputs[0], False


def _merge_sequence(outputs, layout):
    axis = layout.find("T")
    expanded = [sym.expand_dims(o, axis=axis) for o in outputs]
    return sym.Concat(*expanded, dim=axis)


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (reference ``RNNCell``): h' = act(W x + U h + b)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("",)

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden,
                                 name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference ``LSTMCell``), gate order i, f, c, o."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias

        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            init=LSTMBias(forget_bias=forget_bias) if forget_bias
            else None)
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(states[0], weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 4,
                                 name="%sh2h" % name)
        gates = i2h + h2h
        split = sym.SliceChannel(gates, num_outputs=4, axis=1,
                                 name="%sslice" % name)
        in_gate = sym.Activation(split[0], act_type="sigmoid")
        forget_gate = sym.Activation(split[1], act_type="sigmoid")
        in_transform = sym.Activation(split[2], act_type="tanh")
        out_gate = sym.Activation(split[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference ``GRUCell``), gate order r, z, n."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%si2h" % name)
        h2h = sym.FullyConnected(prev_h, weight=self._hW, bias=self._hB,
                                 num_hidden=self._num_hidden * 3,
                                 name="%sh2h" % name)
        i2h_s = sym.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = sym.SliceChannel(h2h, num_outputs=3, axis=1)
        reset = sym.Activation(i2h_s[0] + h2h_s[0], act_type="sigmoid")
        update = sym.Activation(i2h_s[1] + h2h_s[1], act_type="sigmoid")
        next_h_tmp = sym.Activation(i2h_s[2] + reset * h2h_s[2],
                                    act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Whole-network fused cell over the ``RNN`` op (reference
    ``FusedRNNCell`` / cuDNN; here one ``lax.scan`` per layer/direction)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._forget_bias = forget_bias
        # the packed blob is 1-D, which shape-based initializers (Xavier
        # etc.) cannot dispatch on; default it to small-uniform via the
        # attr-driven path (the reference ships an init.FusedRNN that
        # unpacks and applies a sub-initializer per matrix — divergence:
        # here all slices draw from one uniform)
        self._parameter = self.params.get("parameters", init="uniform")
        rnn_gates(mode)  # validate

    @property
    def _num_gates(self):
        return rnn_gates(self._mode)

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _directions(self):
        return ["l", "r"] if self._bidirectional else ["l"]

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    def param_size(self, input_size):
        return rnn_param_size(input_size, self._num_hidden,
                              self._num_layers, self._mode,
                              self._bidirectional)

    def _slice_bounds(self, input_size):
        """[(name, start, shape)] for every logical weight/bias in the
        packed blob, using the unfused cells' naming scheme."""
        g = self._num_gates
        h = self._num_hidden
        d = len(self._directions)
        out = []
        off = 0
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 else h * d
            for direction in self._directions:
                for part, cols in (("i2h", in_sz), ("h2h", h)):
                    name = "%s%s%d_%s_weight" % (self._prefix, direction,
                                                 layer, part)
                    out.append((name, off, (g * h, cols)))
                    off += g * h * cols
        for layer in range(self._num_layers):
            for direction in self._directions:
                for part in ("i2h", "h2h"):
                    name = "%s%s%d_%s_bias" % (self._prefix, direction,
                                               layer, part)
                    out.append((name, off, (g * h,)))
                    off += g * h
        return out

    def unpack_weights(self, args):
        import numpy as np

        from ..ndarray import array

        args = dict(args)
        arr = args.pop(self._parameter.name).asnumpy()
        # infer input size from blob length
        input_size = self._infer_input_size(arr.size)
        for name, off, shape in self._slice_bounds(input_size):
            size = int(np.prod(shape))
            args[name] = array(arr[off:off + size].reshape(shape))
        return args

    def pack_weights(self, args):
        import numpy as np

        from ..ndarray import array

        args = dict(args)
        first = "%s%s0_i2h_weight" % (self._prefix, self._directions[0])
        input_size = args[first].shape[1]
        total = self.param_size(input_size)
        blob = np.zeros(total, "float32")
        for name, off, shape in self._slice_bounds(input_size):
            val = args.pop(name).asnumpy().reshape(-1)
            blob[off:off + val.size] = val
        args[self._parameter.name] = array(blob)
        return args

    def _infer_input_size(self, blob_size):
        g, h = self._num_gates, self._num_hidden
        d = len(self._directions)
        rest = blob_size
        # solve blob_size = d*(g*h*(in+h) + 2*g*h) + (L-1)*d*(g*h*(h*d+h)+2*g*h)
        deeper = (self._num_layers - 1) * d * (g * h * (h * d + h)
                                               + 2 * g * h)
        first = rest - deeper
        return first // (d * g * h) - h - 2

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped one timestep at a "
                         "time; use unroll() (reference behavior)")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        # fused op wants TNC
        if isinstance(inputs, (list, tuple)):
            steps = [sym.expand_dims(x, axis=0) for x in inputs]
            data = sym.Concat(*steps, dim=0)
            batch_ref = inputs[0]   # (N, C): batch on axis 0
            batch_axis1 = False
        else:
            data = inputs
            batch_ref = inputs      # the UN-swapped merged input
            if layout == "NTC":
                data = sym.SwapAxis(data, dim1=0, dim2=1)
                batch_axis1 = False  # NTC: batch on axis 0 of batch_ref
            else:
                batch_axis1 = True   # TNC: batch on axis 1
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=batch_ref,
                                           batch_axis1=batch_axis1)
        rnn_args = dict(state_size=self._num_hidden,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._bidirectional,
                        p=self._dropout,
                        state_outputs=self._get_next_state,
                        name="%srnn" % self._prefix)
        if self._mode == "lstm":
            rnn = sym.RNN(data=data, parameters=self._parameter,
                          state=begin_state[0], state_cell=begin_state[1],
                          **rnn_args)
        else:
            rnn = sym.RNN(data=data, parameters=self._parameter,
                          state=begin_state[0], **rnn_args)
        if self._get_next_state:
            outputs = rnn[0]
            states = [rnn[1], rnn[2]] if self._mode == "lstm" else [rnn[1]]
        else:
            outputs, states = rnn, []
        if layout == "NTC":
            outputs = sym.SwapAxis(outputs, dim1=0, dim2=1)
        if merge_outputs is False:
            axis = layout.find("T")
            steps = sym.SliceChannel(outputs, num_outputs=length,
                                     axis=axis, squeeze_axis=1)
            outputs = [steps[i] for i in range(length)]
        return outputs, states

    def unfuse(self):
        """Equivalent stack of unfused cells (reference ``unfuse``)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda p: RNNCell(self._num_hidden,
                                          activation="relu", prefix=p),
            "rnn_tanh": lambda p: RNNCell(self._num_hidden,
                                          activation="tanh", prefix=p),
            "lstm": lambda p: LSTMCell(self._num_hidden, prefix=p),
            "gru": lambda p: GRUCell(self._num_hidden, prefix=p),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell("%sl%d_" % (self._prefix, i)),
                    get_cell("%sr%d_" % (self._prefix, i)),
                    output_prefix="%sbi_l%d_" % (self._prefix, i)))
            else:
                stack.add(get_cell("%sl%d_" % (self._prefix, i)))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(
                    self._dropout, prefix="%s_dropout%d_" % (self._prefix,
                                                             i)))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied layer-wise (reference
    ``SequentialRNNCell``)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        pos = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[pos:pos + n]
            pos += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        # unroll layer by layer so FusedRNNCell members keep their fused
        # whole-sequence form (reference does the same)
        num_cells = len(self._cells)
        if begin_state is None:
            begin_states = None
        else:
            begin_states = []
            pos = 0
            for cell in self._cells:
                n = len(cell.state_info)
                begin_states.append(begin_state[pos:pos + n])
                pos += n
        states = []
        for i, cell in enumerate(self._cells):
            bs = None if begin_states is None else begin_states[i]
            last = i == num_cells - 1
            inputs, st = cell.unroll(
                length, inputs=inputs, begin_state=bs, layout=layout,
                merge_outputs=None if not last else merge_outputs)
            states.extend(st)
        return inputs, states


class BidirectionalCell(BaseRNNCell):
    """Run two cells over the sequence in opposite directions (reference
    ``BidirectionalCell``); only usable through ``unroll``."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._cells = [l_cell, r_cell]
        self._output_prefix = output_prefix

    @property
    def state_info(self):
        return sum([c.state_info for c in self._cells], [])

    def begin_state(self, **kwargs):
        return sum([c.begin_state(**kwargs) for c in self._cells], [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, batch_ref, batch_axis1 = _normalize_sequence(
            length, inputs, layout, merge=False)
        if begin_state is None:
            begin_state = self.begin_state(batch_ref=batch_ref,
                                           batch_axis1=batch_axis1)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=begin_state[n_l:], layout=layout,
            merge_outputs=False)
        outputs = [sym.Concat(l_o, r_o, dim=1,
                              name="%st%d" % (self._output_prefix, i))
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        if merge_outputs:
            outputs = _merge_sequence(outputs, layout)
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference ``ModifierCell``)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(**kwargs)
        self.base_cell._modified = True
        return begin

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    """Apply dropout on output (reference ``DropoutCell``)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym.Dropout(inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference ``ZoneoutCell``): with prob p,
    keep the previous state instead of the new one."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        if isinstance(base_cell, (FusedRNNCell, BidirectionalCell)):
            raise MXNetError("ZoneoutCell cannot wrap %s"
                             % type(base_cell).__name__)
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        next_output, next_states = self.base_cell(inputs, states)

        def mask(p, like):
            return sym.Dropout(sym.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else sym.zeros_like(next_output)
        if self.zoneout_outputs > 0:
            output = sym.where(mask(self.zoneout_outputs, next_output),
                               next_output, prev_output)
        else:
            output = next_output
        if self.zoneout_states > 0:
            states = [sym.where(mask(self.zoneout_states, ns), ns, s)
                      for ns, s in zip(next_states, states)]
        else:
            states = next_states
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the output (reference ``ResidualCell``)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=merge_outputs)
        self.base_cell._modified = True
        if isinstance(outputs, list):
            ins, _, _ = _normalize_sequence(length, inputs, layout, False)
            outputs = [o + i for o, i in zip(outputs, ins)]
        else:
            merged_in = inputs if isinstance(inputs, sym.Symbol) else \
                _merge_sequence(list(inputs), layout)
            outputs = outputs + merged_in
        return outputs, states
