"""Runtime custom kernels.

Reference: ``MXRtc`` (``include/mxnet/mxrtc.h:44``, Python
``mxnet/rtc.py``) — compile CUDA source strings at runtime into callable
kernels.  The TPU-native counterpart compiles PALLAS kernels: the user
writes a Python kernel body against ``pl``/``pltpu`` refs and gets a
callable over NDArrays.  ``ops/pallas_bn.py`` is the in-tree example of
the same facility used for a framework op.

Differences from the reference, by design: kernels are Python (traced,
compiled by Mosaic/XLA), not source strings; grid/block specs follow
Pallas conventions (see /opt/skills/guides/pallas_guide.md).
"""
from __future__ import annotations

from .base import MXNetError

__all__ = ["PallasKernel"]


class PallasKernel:
    """A compiled custom kernel (the ``mx.rtc.Rtc`` analogue).

    ``kernel(*refs)``: a Pallas kernel body taking input refs then
    output refs.  ``out_shapes``: list of (shape, dtype) for outputs.
    Optional ``grid``/``in_specs``/``out_specs`` pass through to
    ``pl.pallas_call``; by default whole arrays land in VMEM.

    Example::

        def body(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2.0

        k = PallasKernel(body, [((128, 128), "float32")])
        (y,) = k(x)
    """

    def __init__(self, kernel, out_shapes, grid=None, in_specs=None,
                 out_specs=None, interpret="auto"):
        import jax

        self._kernel = kernel
        self._out_shapes = [(tuple(s), d) for (s, d) in out_shapes]
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        if interpret == "auto":
            # Mosaic compiles only on real TPU backends; everywhere else
            # (CPU tests) the interpreter runs the same kernel
            interpret = jax.default_backend() != "tpu"
        self._interpret = bool(interpret)
        self._compiled = None

    def _build(self):
        import jax
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        out_shape = [jax.ShapeDtypeStruct(s, d)
                     for (s, d) in self._out_shapes]
        kwargs = {}
        if self._grid is not None:
            kwargs["grid"] = self._grid
        if self._in_specs is not None:
            kwargs["in_specs"] = self._in_specs
        if self._out_specs is not None:
            kwargs["out_specs"] = self._out_specs
        call = pl.pallas_call(self._kernel, out_shape=out_shape,
                              interpret=self._interpret, **kwargs)
        self._compiled = jax.jit(call)

    def __call__(self, *inputs):
        """Run on NDArrays (or raw arrays); returns a tuple of
        NDArrays."""
        from .ndarray import NDArray, array

        if self._compiled is None:
            self._build()
        raw = [x._data if isinstance(x, NDArray) else array(x)._data
               for x in inputs]
        out = self._compiled(*raw)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(NDArray(o) for o in out)

    def push(self, inputs, outputs=None, grid_dims=None, block_dims=None):
        """Reference ``Rtc.push`` signature adapter: runs the kernel and
        copies into ``outputs`` when given."""
        results = self(*inputs)
        if outputs:
            for res, dst in zip(results, outputs):
                res.copyto(dst)
            return outputs
        return list(results)
