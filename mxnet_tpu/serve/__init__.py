"""mxnet_tpu.serve — production inference runtime.

Continuous batching over bucketed AOT executables plus an O(1) paged
KV decode cache.  See docs/serving.md for the architecture and
bench_serve.py for the serial/static/continuous comparison.
"""
from .gateway import Gateway
from .kv_cache import PagedKVCache
from .model import ModelConfig, config_from_params, decode_step, \
    full_forward, init_params, prefill_forward, reference_last_logits
from .scheduler import Request, Scheduler, ServeCancelled, summarize
from .session import InferenceSession, ServeConfig
from .supervisor import ReplicaSet, ServeOverloaded, ServeUnavailable

__all__ = [
    "Gateway",
    "InferenceSession",
    "ModelConfig",
    "PagedKVCache",
    "ReplicaSet",
    "Request",
    "Scheduler",
    "ServeCancelled",
    "ServeConfig",
    "ServeOverloaded",
    "ServeUnavailable",
    "config_from_params",
    "decode_step",
    "full_forward",
    "init_params",
    "prefill_forward",
    "reference_last_logits",
    "summarize",
]
