"""The network edge: a fault-hardened streaming HTTP gateway.

ROADMAP item 1's front door — a stdlib-``asyncio`` HTTP/1.1 server that
fronts a :class:`~mxnet_tpu.serve.ReplicaSet` (or a single
:class:`~mxnet_tpu.serve.InferenceSession` behind a
:class:`~mxnet_tpu.serve.Scheduler`) and streams tokens as they commit,
designed failure-first: every failure mode a real socket brings that an
in-process harness never exercises has an explicit, typed, *asserted*
behavior.

Wire protocol (one request per connection, ``Connection: close``):

* ``POST /v1/generate`` — JSON body ``{"prompt": [ints],
  "max_new": N, "rid": int?, "stream": bool?, "eos_id": int?,
  "deadline_ms": float?, "idempotency_key": str?}``.  With
  ``stream`` (the default) the response is chunked-transfer SSE:
  one ``data: {"rid": R, "token": T}`` event per committed token and a
  final ``data: {"rid": R, "done": true, "tokens": [...]}`` event, so a
  client holds the full stream AND a checksummable final transcript.
  Errors mid-stream arrive as a terminal ``data: {..., "error": ...,
  "status": S}`` event; errors before the first byte use plain HTTP
  statuses.  ``stream: false`` waits and returns one JSON body.
* ``GET /healthz`` — liveness: 200 while the process serves at all.
* ``GET /readyz`` — readiness: 200 only while accepting new work;
  flips to 503 the moment a drain begins or the backend goes
  unavailable (the rolling-restart / load-balancer contract).

The failure-first contract:

* **Cancellation.** A client disconnect or a lapsed per-request
  ``deadline_ms`` propagates to the backend's ``cancel(rid)`` —
  :meth:`~mxnet_tpu.serve.Scheduler.cancel` releases the slot and its
  refcount-aware pages at the next decode boundary, so shared prefix
  pages survive and pool occupancy returns to its pre-request baseline
  (the tests assert the session ``state_report()`` round-trips).
* **Graceful drain.** SIGTERM (or :meth:`drain`) flips ``/readyz``
  *first*, stops admitting work, lets in-flight streams finish for up
  to ``MXNET_GW_DRAIN_S`` seconds, then force-cancels the stragglers
  with a typed :class:`~mxnet_tpu.serve.ServeCancelled` — a rolling
  restart never truncates a stream silently.  A second SIGTERM
  force-exits immediately, after writing the incident artifact.
* **Overload.** A typed :class:`~mxnet_tpu.serve.ServeOverloaded` from
  the dispatcher surfaces as ``429`` + ``Retry-After``;
  :class:`~mxnet_tpu.serve.ServeUnavailable` (every replica dead) as
  ``503``.  Reads and writes carry per-connection timeouts
  (``MXNET_GW_READ_TIMEOUT_S``) and each connection's kernel write
  buffer is capped at ``MXNET_GW_WRITE_BUF_KB`` — a reader that stops
  draining its socket is shed typed (its request cancelled, its state
  freed) instead of wedging anything: the ReplicaSet tick runs in its
  own worker thread and never touches a socket, so the slowest reader
  cannot block another stream's decode.
* **Exactly-once retries.** A request carrying an idempotency key that
  completes after its client vanished parks its transcript for
  ``MXNET_GW_IDEMPOTENCY_S`` seconds; a retry under the same key
  replays the completed response byte-for-byte instead of re-decoding
  (and a retry racing the original simply waits for it).  Keyless
  disconnects cancel instead — the key is the client's declaration
  that it will retry.
* **Incidents.** Abnormal exits (force drain, backend outage, a second
  SIGTERM) write ``gateway-incident-<pid>-<ms>.json`` under
  ``MXNET_HEALTH_DIR`` — counters, open connections, drain outcome,
  full timeline; pretty-print with ``tools/diagnose.py``.

Threading model: the asyncio event loop runs in one worker thread and
owns every socket; the dispatch loop runs in a second thread and owns
the backend (``tick()`` / ``submit()`` / ``cancel()`` under one lock).
Committed tokens cross from the dispatch thread to the loop via
``call_soon_threadsafe`` — the loop never blocks on the model and the
model never blocks on a socket.

Chaos sites (``testing/faults.py``): ``gateway_read`` (post-read,
pre-parse — fails that connection typed), ``gateway_write`` (before
each streamed chunk — treated as the client vanishing), and
``gateway_cancel`` / ``gateway_drain`` on the two control paths.
"""
from __future__ import annotations

import asyncio
import json
import os
import signal
import tempfile
import threading
import time

from ..base import MXNetError, get_env, logger
from ..testing import faults
from .scheduler import Scheduler, Request, mark_cancelled
from .session import InferenceSession
from .supervisor import ReplicaSet, ServeUnavailable

__all__ = ["Gateway"]

_REASONS = {200: "OK", 400: "Bad Request", 404: "Not Found",
            405: "Method Not Allowed", 409: "Conflict",
            429: "Too Many Requests", 500: "Internal Server Error",
            503: "Service Unavailable"}

GATEWAY_THREAD_PREFIX = "mxtpu-gw-"


class _SchedulerBackend(object):
    """A single session (or pre-built scheduler) behind the gateway.
    No admission queue, so nothing sheds — overload waits in the
    scheduler's pending list."""

    def __init__(self, target):
        self.sched = target if isinstance(target, Scheduler) \
            else Scheduler(target)
        self.sched.begin([])

    def now(self):
        return self.sched.now()

    def submit(self, req):
        self.sched.submit(req)

    def tick(self):
        return self.sched.tick(wait=False)

    def cancel(self, rid, reason):
        return self.sched.cancel(rid, reason)

    def ready(self):
        return True

    @property
    def outstanding(self):
        return self.sched.outstanding

    def finish(self):
        pass


class _ReplicaSetBackend(object):
    """A full :class:`ReplicaSet` behind the gateway: bounded admission
    queue, deadline shedding, breaker, failover — the gateway only adds
    the sockets."""

    def __init__(self, rs):
        self.rs = rs
        rs.begin()

    def now(self):
        return self.rs.now()

    def submit(self, req):
        self.rs.submit(req)

    def tick(self):
        return self.rs.tick()

    def cancel(self, rid, reason):
        return self.rs.cancel(rid, reason)

    def ready(self):
        return bool(self.rs.live_replicas())

    @property
    def outstanding(self):
        return self.rs.outstanding

    def finish(self):
        self.rs.finish()


class _Stream(object):
    """Loop-side view of one in-flight request: the dispatch thread
    pushes committed tokens in; the handler coroutine writes them out."""

    __slots__ = ("req", "key", "peer", "loop", "pushed", "flushed",
                 "tokens", "done", "event", "orphaned")

    def __init__(self, req, key, peer, loop):
        self.req = req
        self.key = key
        self.peer = peer
        self.loop = loop
        self.pushed = 0      # dispatch-side: req.tokens consumed so far
        self.flushed = False  # dispatch-side: terminal push sent
        self.tokens = []     # loop-side: tokens awaiting the writer
        self.done = False    # loop-side: terminal state arrived
        self.event = asyncio.Event()
        self.orphaned = False  # client vanished; decode continues

    def push_threadsafe(self, toks, done):
        self.loop.call_soon_threadsafe(self._push, toks, done)

    def _push(self, toks, done):
        self.tokens.extend(toks)
        self.done = self.done or done
        self.event.set()


class Gateway(object):
    """Serve a backend over real sockets; see the module docstring for
    the failure contract.  ``backend`` is a :class:`ReplicaSet`, an
    :class:`InferenceSession`, or a pre-armed :class:`Scheduler`.
    ``start()`` binds and returns self; ``stop()`` tears everything
    down (joining both worker threads); ``drain()`` is the rolling-
    restart path.  Knob defaults come from ``MXNET_GW_*`` env vars,
    each overridable per instance."""

    def __init__(self, backend, host="127.0.0.1", port=None,
                 drain_s=None, read_timeout_s=None, write_buf_kb=None,
                 idempotency_s=None, incident_dir=None,
                 on_force_exit=None):
        if isinstance(backend, ReplicaSet):
            self._backend = _ReplicaSetBackend(backend)
        elif isinstance(backend, (InferenceSession, Scheduler)):
            self._backend = _SchedulerBackend(backend)
        else:
            raise MXNetError(
                "Gateway fronts a ReplicaSet, InferenceSession, or "
                "Scheduler (got %r)" % type(backend).__name__)
        self.host = host
        self.port = int(port) if port is not None \
            else get_env("MXNET_GW_PORT", 0, int)
        self.drain_s = float(drain_s) if drain_s is not None \
            else get_env("MXNET_GW_DRAIN_S", 5.0, float)
        self.read_timeout_s = float(read_timeout_s) \
            if read_timeout_s is not None \
            else get_env("MXNET_GW_READ_TIMEOUT_S", 30.0, float)
        self.write_buf_kb = int(write_buf_kb) \
            if write_buf_kb is not None \
            else get_env("MXNET_GW_WRITE_BUF_KB", 64, int)
        self.idempotency_s = float(idempotency_s) \
            if idempotency_s is not None \
            else get_env("MXNET_GW_IDEMPOTENCY_S", 30.0, float)
        self._incident_dir = incident_dir or get_env(
            "MXNET_HEALTH_DIR", tempfile.gettempdir(), str)
        self._on_force_exit = on_force_exit
        self.counters = {
            "connections": 0, "requests": 0, "streams_completed": 0,
            "cancelled": 0, "cancel_faults": 0, "disconnects": 0,
            "shed_429": 0, "unavailable_503": 0, "draining_503": 0,
            "slow_reader_sheds": 0, "deadline_cancels": 0,
            "idempotent_replays": 0, "read_timeouts": 0,
            "read_faults": 0, "force_cancelled": 0}
        self.events = []
        self.incident_path = None
        self._t0 = time.monotonic()
        self._tick_lock = threading.Lock()
        self._streams = {}   # rid -> _Stream (open server-side)
        self._idem = {}      # key -> replay record (loop thread only)
        self._rid_seq = [1 << 40]
        self._ready = False
        self._draining = False
        self._drain_fut = None
        self._drain_clean = None
        self._unavailable = None
        self._abnormal = False
        self._stop_evt = threading.Event()
        self._work_evt = threading.Event()
        self._loop = None
        self._server = None
        self._boot_err = None
        self._loop_thread = None
        self._dispatch_thread = None
        self._prev_sigterm = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Bind the listener, start the loop + dispatch threads; the
        actual port (ephemeral with port 0) is in ``self.port``."""
        booted = threading.Event()
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop_main, args=(booted,),
            name=GATEWAY_THREAD_PREFIX + "loop", daemon=True)
        self._loop_thread.start()
        if not booted.wait(timeout=30):
            raise MXNetError("gateway event loop failed to start")
        if self._boot_err is not None:
            self._loop_thread.join(timeout=5)
            raise MXNetError("gateway bind failed on %s:%d: %s"
                             % (self.host, self.port, self._boot_err))
        self._dispatch_thread = threading.Thread(
            target=self._dispatch_loop,
            name=GATEWAY_THREAD_PREFIX + "dispatch", daemon=True)
        self._dispatch_thread.start()
        self._ready = True
        self._event("start", port=self.port)
        return self

    def _loop_main(self, booted):
        asyncio.set_event_loop(self._loop)

        async def _boot():
            try:
                self._server = await asyncio.start_server(
                    self._handle, self.host, self.port)
                self.port = self._server.sockets[0].getsockname()[1]
            except OSError as exc:
                self._boot_err = exc

        self._loop.run_until_complete(_boot())
        booted.set()
        if self._boot_err is not None:
            self._loop.close()
            return
        try:
            self._loop.run_forever()
        finally:
            try:
                self._loop.run_until_complete(
                    self._loop.shutdown_asyncgens())
            finally:
                self._loop.close()

    def stop(self):
        """Tear down: cancel whatever is still streaming (typed), close
        the listener and every connection, stop both threads (joined
        with timeouts), finish the backend, and write the incident
        artifact when anything abnormal happened."""
        if self._loop is None:
            return
        with self._tick_lock:
            leftovers = [rid for rid, st in self._streams.items()
                         if not st.req.finished]
            for rid in leftovers:
                self._backend.cancel(rid, "gateway stopped")
                self.counters["cancelled"] += 1
        self._ready = False
        self._stop_evt.set()
        self._work_evt.set()
        if self._dispatch_thread is not None:
            self._dispatch_thread.join(timeout=10)
        if self._loop.is_running():
            fut = asyncio.run_coroutine_threadsafe(
                self._shutdown_async(), self._loop)
            try:
                fut.result(timeout=10)
            except (asyncio.TimeoutError, OSError,
                    RuntimeError) as exc:
                logger.warning("gateway shutdown incomplete: %s", exc)
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=10)
        self._backend.finish()
        self._event("stop")
        if self._abnormal:
            self._write_incident()
        self._loop = None

    async def _shutdown_async(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        me = asyncio.current_task()
        pending = [t for t in asyncio.all_tasks(self._loop)
                   if t is not me and not t.done()]
        for t in pending:
            t.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)

    # -- drain + signals ---------------------------------------------------
    def drain(self, wait=True):
        """Begin a graceful drain: readiness flips immediately (before
        anything else — the load balancer must see it first), new work
        is refused 503, in-flight streams get up to ``drain_s`` seconds
        to finish, stragglers are force-cancelled typed."""
        if self._loop is None:
            return
        if self._drain_fut is None:
            self._ready = False
            self._draining = True
            self._drain_fut = asyncio.run_coroutine_threadsafe(
                self._drain_async(), self._loop)
        if wait:
            return self._drain_fut.result(timeout=self.drain_s + 30)
        return None

    async def _drain_async(self):
        self._event("drain_begin", deadline_s=self.drain_s)
        grace = self.drain_s
        try:
            faults.inject("gateway_drain")
        except (MXNetError, faults.WorkerKilled) as exc:
            # a fault here collapses the grace window: straight to the
            # typed force-cancel, never a silent truncation
            grace = 0.0
            self._abnormal = True
            self._event("drain_fault",
                        detail="%s: %s" % (type(exc).__name__, exc))
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            with self._tick_lock:
                open_streams = any(not st.req.finished
                                   for st in self._streams.values())
            if not open_streams:
                break
            self._work_evt.set()
            await asyncio.sleep(0.01)
        with self._tick_lock:
            leftovers = [rid for rid, st in self._streams.items()
                         if not st.req.finished]
            for rid in leftovers:
                self._backend.cancel(rid, "gateway drain deadline "
                                          "lapsed")
        self.counters["force_cancelled"] += len(leftovers)
        self._drain_clean = not leftovers
        if leftovers:
            self._abnormal = True
        self._event("drain_end", clean=self._drain_clean,
                    force_cancelled=len(leftovers))
        # let the dispatch thread flush the terminal events out
        self._work_evt.set()
        return self._drain_clean

    def install_signal_handlers(self):
        """Route SIGTERM to :meth:`handle_sigterm` (first: drain;
        second: force-exit with an incident artifact).  Main thread
        only, per the signal module; returns the previous handler."""
        self._prev_sigterm = signal.signal(
            signal.SIGTERM, lambda signum, frame: self.handle_sigterm())
        return self._prev_sigterm

    def handle_sigterm(self):
        """First SIGTERM: begin the graceful drain in the background.
        Second SIGTERM: force — cancel everything typed, write the
        incident artifact, and exit (``on_force_exit(path)`` when
        injected, else ``os._exit(1)``)."""
        if not self._draining:
            self._event("sigterm")
            self.drain(wait=False)
            return None
        self._event("sigterm_force")
        self._abnormal = True
        with self._tick_lock:
            for rid, st in list(self._streams.items()):
                if not st.req.finished:
                    self._backend.cancel(rid, "gateway force exit")
                    self.counters["force_cancelled"] += 1
        path = self._write_incident()
        if self._on_force_exit is not None:
            self._on_force_exit(path)
            return path
        os._exit(1)

    # -- the dispatch thread ----------------------------------------------
    def _dispatch_loop(self):
        """Owns the backend: one tick per iteration, then pump every
        open stream's newly committed tokens to the event loop.  No
        socket is ever touched here, so no reader can stall a tick."""
        while not self._stop_evt.is_set():
            progressed = False
            try:
                with self._tick_lock:
                    if self._backend.outstanding:
                        progressed = bool(self._backend.tick())
                    self._pump_locked()
            except ServeUnavailable as exc:
                with self._tick_lock:
                    self._pump_locked()
                self._note_unavailable(exc)
                continue
            except MXNetError as exc:
                self._note_unavailable(exc)
                continue
            except Exception as exc:  # mxlint: disable=MX008 — the
                # dispatch thread dying silently would wedge every open
                # stream; convert to a typed outage instead
                self._note_unavailable(MXNetError(
                    "gateway dispatch loop crashed: %s: %s"
                    % (type(exc).__name__, exc)))
                continue
            if not progressed:
                self._work_evt.wait(timeout=0.005)
                self._work_evt.clear()

    def _pump_locked(self):
        """Move newly committed tokens (and terminal states) from each
        request to its loop-side stream.  Caller holds the tick lock."""
        for rid in list(self._streams):
            st = self._streams[rid]
            req = st.req
            n = len(req.tokens)
            fin = req.finished
            if n > st.pushed or (fin and not st.flushed):
                new = list(req.tokens[st.pushed:n])
                st.pushed = n
                if fin:
                    st.flushed = True
                st.push_threadsafe(new, fin)
            if fin:
                del self._streams[rid]
                if st.key:
                    self._loop.call_soon_threadsafe(
                        self._park_idempotent, st)

    def _note_unavailable(self, exc):
        if self._unavailable is None:
            self._unavailable = "%s: %s" % (type(exc).__name__, exc)
            self._ready = False
            self._abnormal = True
            self._event("unavailable", detail=self._unavailable)
            logger.warning("gateway backend unavailable: %s",
                           self._unavailable)

    # -- cancel propagation -----------------------------------------------
    def _cancel_backend(self, rid, reason, counter="cancelled"):
        """Propagate one cancel to the backend across the
        ``gateway_cancel`` chaos site.  A fault here fails the *cancel*
        alone: the request keeps decoding and its normal completion
        still frees the slot — a lost cancel must never leak state."""
        try:
            faults.inject("gateway_cancel")
        except (MXNetError, faults.WorkerKilled) as exc:
            self.counters["cancel_faults"] += 1
            self._event("cancel_fault", rid=rid,
                        detail="%s: %s" % (type(exc).__name__, exc))
            return False
        with self._tick_lock:
            ok = self._backend.cancel(rid, reason)
        if ok:
            self.counters[counter] += 1
            self._event("cancel", rid=rid, detail=reason)
            self._work_evt.set()
        return ok

    # -- idempotency window -----------------------------------------------
    def _purge_idem(self):
        now = time.monotonic()
        for key in [k for k, rec in self._idem.items()
                    if rec["expires"] <= now]:
            del self._idem[key]

    def _park_idempotent(self, st):
        """Completion of a keyed request (loop thread): park the
        transcript for replay — only successes; a failed original lets
        the retry decode fresh."""
        rec = self._idem.get(st.key)
        if rec is None:
            return
        if st.req.failed:
            del self._idem[st.key]
        else:
            rec["tokens"] = list(st.req.tokens)
            rec["expires"] = time.monotonic() + self.idempotency_s
        rec["evt"].set()

    # -- the connection handler -------------------------------------------
    async def _handle(self, reader, writer):
        self.counters["connections"] += 1
        transport = writer.transport
        try:
            transport.set_write_buffer_limits(
                high=self.write_buf_kb * 1024)
        except (RuntimeError, AttributeError):
            pass  # transport flavors without watermarks
        try:
            try:
                parsed = await self._read_request(reader)
            except asyncio.TimeoutError:
                self.counters["read_timeouts"] += 1
                self._event("read_timeout")
                return
            except (asyncio.IncompleteReadError, ConnectionError,
                    OSError):
                self.counters["disconnects"] += 1
                return
            if parsed is None:
                return
            method, path, headers, body = parsed
            try:
                faults.inject("gateway_read")
            except faults.WorkerKilled:
                return  # abrupt close, like a dying proxy hop
            except MXNetError as exc:
                self.counters["read_faults"] += 1
                self._event("read_fault", detail="%s: %s"
                            % (type(exc).__name__, exc))
                await self._respond(writer, 500, {
                    "error": "%s: %s" % (type(exc).__name__, exc)})
                return
            if method == "GET" and path == "/healthz":
                await self._respond(writer, 200, {
                    "ok": True, "state": self._state()})
                return
            if method == "GET" and path == "/readyz":
                ready = self._ready and self._backend.ready()
                await self._respond(
                    writer, 200 if ready else 503,
                    {"ready": ready, "state": self._state(),
                     "error": self._unavailable})
                return
            if path != "/v1/generate":
                await self._respond(writer, 404,
                                    {"error": "no route %r" % path})
                return
            if method != "POST":
                await self._respond(writer, 405,
                                    {"error": "POST required"})
                return
            await self._generate(writer, headers, body)
        except (ConnectionError, OSError):
            self.counters["disconnects"] += 1
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionError, OSError, RuntimeError):
                pass

    async def _read_request(self, reader):
        line = await asyncio.wait_for(reader.readline(),
                                      self.read_timeout_s)
        if not line:
            return None
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        method, path = parts[0], parts[1]
        headers = {}
        while True:
            raw = await asyncio.wait_for(reader.readline(),
                                         self.read_timeout_s)
            if raw in (b"\r\n", b"\n", b""):
                break
            name, _, value = raw.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        length = int(headers.get("content-length", 0) or 0)
        if length > 0:
            body = await asyncio.wait_for(reader.readexactly(length),
                                          self.read_timeout_s)
        return method, path, headers, body

    async def _generate(self, writer, headers, body):
        self.counters["requests"] += 1
        try:
            spec = json.loads(body.decode() or "{}")
            prompt = [int(t) for t in spec["prompt"]]
        except (ValueError, KeyError, TypeError) as exc:
            await self._respond(writer, 400, {
                "error": "bad request body: %s" % exc})
            return
        if self._unavailable is not None:
            self.counters["unavailable_503"] += 1
            await self._respond(writer, 503, {
                "error": self._unavailable}, retry_after=5)
            return
        if self._draining or not self._ready \
                or not self._backend.ready():
            self.counters["draining_503"] += 1
            await self._respond(writer, 503, {
                "error": "ServeUnavailable: gateway is %s"
                         % self._state()}, retry_after=2)
            return
        key = spec.get("idempotency_key") \
            or headers.get("idempotency-key")
        self._purge_idem()
        if key and key in self._idem:
            await self._replay_idempotent(writer, key,
                                          bool(spec.get("stream", True)))
            return
        rid = int(spec["rid"]) if "rid" in spec else self._next_rid()
        req = Request(rid=rid, prompt=prompt,
                      max_new=int(spec.get("max_new", 16)),
                      eos_id=int(spec.get("eos_id", -1)))
        deadline_ms = float(spec.get("deadline_ms", 0.0) or 0.0)
        if deadline_ms > 0:
            req.deadline_ms = deadline_ms  # the dispatcher's shed rule
        st = _Stream(req, key, self._peer(writer), self._loop)
        with self._tick_lock:
            if rid in self._streams:
                dup = True
            else:
                dup = False
                req.arrival_s = self._backend.now()
                self._backend.submit(req)
                if not (req.failed and req.shed):
                    self._streams[rid] = st
        if dup:
            await self._respond(writer, 409, {
                "error": "rid %d is already in flight" % rid})
            return
        self._work_evt.set()
        if req.failed and req.shed:  # synchronous queue-cap shed
            self.counters["shed_429"] += 1
            await self._respond(writer, 429, {"error": req.error},
                                retry_after=1)
            return
        if key:
            self._idem[key] = {
                "expires": time.monotonic() + self.idempotency_s,
                "tokens": None, "rid": rid, "evt": asyncio.Event()}
        if bool(spec.get("stream", True)):
            await self._stream_sse(writer, st, deadline_ms)
        else:
            await self._respond_whole(writer, st, deadline_ms)

    def _next_rid(self):
        self._rid_seq[0] += 1
        return self._rid_seq[0]

    async def _wait_stream(self, st, deadline):
        """Wait for new stream data or the request deadline; returns
        True on deadline expiry (after cancelling the request)."""
        while not st.tokens and not st.done:
            timeout = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.counters["deadline_cancels"] += 1
                    self._cancel_backend(
                        st.req.rid, "per-request deadline of %.0f ms "
                        "lapsed mid-stream" % st.req.deadline_ms,
                        counter="cancelled")
                    return True
                timeout = min(timeout, remaining)
            try:
                await asyncio.wait_for(st.event.wait(), timeout)
            except asyncio.TimeoutError:
                continue
            st.event.clear()
        return False

    async def _stream_sse(self, writer, st, deadline_ms):
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n"
                     b"Connection: close\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        deadline = time.monotonic() + deadline_ms / 1e3 \
            if deadline_ms > 0 else None
        req = st.req
        try:
            while True:
                lapsed = await self._wait_stream(st, deadline)
                while st.tokens:
                    tok = st.tokens.pop(0)
                    await self._write_event(writer, {
                        "rid": req.rid, "token": tok})
                if st.done or lapsed:
                    if lapsed and not st.done:
                        # terminal event for a deadline cancel whose
                        # pump hasn't flushed yet
                        await self._write_event(writer, {
                            "rid": req.rid, "done": True,
                            "error": "ServeCancelled: per-request "
                                     "deadline lapsed", "status": 499})
                    elif req.failed:
                        await self._write_event(writer, {
                            "rid": req.rid, "done": True,
                            "error": req.error,
                            "status": self._fail_status(req)})
                    else:
                        await self._write_event(writer, {
                            "rid": req.rid, "done": True,
                            "tokens": list(req.tokens),
                            "n": len(req.tokens)})
                        self.counters["streams_completed"] += 1
                    writer.write(b"0\r\n\r\n")
                    await asyncio.wait_for(writer.drain(),
                                           self.read_timeout_s)
                    return
        except asyncio.TimeoutError:
            # the bounded write buffer stayed full past the timeout:
            # this reader is too slow to keep — shed it typed
            self.counters["slow_reader_sheds"] += 1
            self._event("slow_reader_shed", rid=req.rid,
                        peer=str(st.peer))
            self._cancel_backend(req.rid, "slow reader shed: write "
                                 "buffer full past %.1fs"
                                 % self.read_timeout_s)
            self._abort(writer)
        except (ConnectionError, OSError, MXNetError,
                faults.WorkerKilled):
            # the client vanished (or gateway_write said to pretend it
            # did): keyed requests decode on for the retry window;
            # keyless ones cancel and free their state now
            self.counters["disconnects"] += 1
            if st.key:
                st.orphaned = True
                self._event("orphaned", rid=req.rid, detail="client "
                            "vanished; decoding on for idempotent "
                            "retry")
            else:
                self._cancel_backend(req.rid, "client disconnected "
                                     "mid-stream")
            self._abort(writer)

    async def _respond_whole(self, writer, st, deadline_ms):
        deadline = time.monotonic() + deadline_ms / 1e3 \
            if deadline_ms > 0 else None
        req = st.req
        try:
            while not st.done:
                if await self._wait_stream(st, deadline):
                    break
                st.tokens.clear()
            if req.failed or not req.finished:
                status = self._fail_status(req) if req.failed else 499
                await self._respond(writer, status, {
                    "rid": req.rid,
                    "error": req.error or "ServeCancelled: deadline"})
            else:
                await self._respond(writer, 200, {
                    "rid": req.rid, "tokens": list(req.tokens)})
                self.counters["streams_completed"] += 1
        except (ConnectionError, OSError, asyncio.TimeoutError):
            self.counters["disconnects"] += 1
            if st.key:
                st.orphaned = True
            else:
                self._cancel_backend(req.rid, "client disconnected")
            self._abort(writer)

    async def _replay_idempotent(self, writer, key, stream):
        """Exactly-once retries: wait for the original if it is still
        decoding, then replay its completed transcript byte-for-byte —
        never a second decode."""
        rec = self._idem[key]
        if rec["tokens"] is None:
            try:
                await asyncio.wait_for(rec["evt"].wait(),
                                       self.read_timeout_s)
            except asyncio.TimeoutError:
                await self._respond(writer, 503, {
                    "error": "ServeUnavailable: original request for "
                             "this idempotency key is still running"},
                    retry_after=2)
                return
        rec = self._idem.get(key)
        if rec is None or rec["tokens"] is None:
            # the original failed: nothing completed to replay
            await self._respond(writer, 409, {
                "error": "original request for this idempotency key "
                         "did not complete; retry without the race"})
            return
        self.counters["idempotent_replays"] += 1
        self._event("idempotent_replay", rid=rec["rid"])
        if not stream:
            await self._respond(writer, 200, {
                "rid": rec["rid"], "tokens": list(rec["tokens"]),
                "replayed": True})
            return
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Cache-Control: no-store\r\n"
                     b"Connection: close\r\n"
                     b"Transfer-Encoding: chunked\r\n\r\n")
        try:
            for tok in rec["tokens"]:
                await self._write_event(writer, {
                    "rid": rec["rid"], "token": tok})
            await self._write_event(writer, {
                "rid": rec["rid"], "done": True,
                "tokens": list(rec["tokens"]),
                "n": len(rec["tokens"])})
            writer.write(b"0\r\n\r\n")
            await asyncio.wait_for(writer.drain(), self.read_timeout_s)
        except (ConnectionError, OSError, asyncio.TimeoutError,
                MXNetError, faults.WorkerKilled):
            # replays hold no backend state, so a vanished retryer (or
            # a gateway_write fault mid-replay) just closes the socket
            self.counters["disconnects"] += 1
            self._abort(writer)

    # -- wire helpers ------------------------------------------------------
    async def _write_event(self, writer, payload):
        """One SSE event as one HTTP chunk, across the
        ``gateway_write`` chaos site; the awaited drain is where the
        bounded write buffer pushes back on a slow reader."""
        faults.inject("gateway_write")
        data = b"data: " + json.dumps(payload).encode() + b"\n\n"
        writer.write(b"%x\r\n" % len(data) + data + b"\r\n")
        await asyncio.wait_for(writer.drain(), self.read_timeout_s)

    async def _respond(self, writer, status, payload, retry_after=None):
        body = json.dumps(payload).encode()
        head = ["HTTP/1.1 %d %s" % (status,
                                    _REASONS.get(status, "OK")),
                "Content-Type: application/json",
                "Content-Length: %d" % len(body),
                "Connection: close"]
        if retry_after is not None:
            head.append("Retry-After: %d" % retry_after)
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + body)
        await asyncio.wait_for(writer.drain(), self.read_timeout_s)

    def _abort(self, writer):
        try:
            writer.transport.abort()
        except (RuntimeError, AttributeError, OSError):
            pass

    def _peer(self, writer):
        try:
            return writer.get_extra_info("peername")
        except (RuntimeError, OSError):
            return None

    @staticmethod
    def _fail_status(req):
        if getattr(req, "shed", False):
            return 429
        if getattr(req, "cancelled", False):
            return 499  # nginx's "client closed request"
        if "ServeUnavailable" in (req.error or ""):
            return 503
        return 500

    # -- introspection + incident artifact ---------------------------------
    def _state(self):
        if self._unavailable is not None:
            return "unavailable"
        if self._draining:
            return "draining"
        return "serving" if self._ready else "stopped"

    def _event(self, event, **detail):
        rec = {"t": round(time.monotonic() - self._t0, 4),
               "event": event}
        rec.update(detail)
        self.events.append(rec)

    def open_streams(self):
        with self._tick_lock:
            return sorted(self._streams)

    def incident_report(self):
        """JSON-able incident summary: counters, open connections, and
        the drain outcome — ``tools/diagnose.py`` renders it."""
        with self._tick_lock:
            open_conns = [
                {"rid": rid, "peer": str(st.peer),
                 "tokens_sent": st.pushed, "keyed": bool(st.key),
                 "orphaned": st.orphaned}
                for rid, st in sorted(self._streams.items())]
        return {
            "kind": "mxnet_tpu-gateway-incident",
            "pid": os.getpid(),
            "time": time.time(),
            "host": self.host,
            "port": self.port,
            "state": self._state(),
            "counters": dict(self.counters),
            "open_connections": open_conns,
            "drain": {"requested": self._draining,
                      "deadline_s": self.drain_s,
                      "clean": self._drain_clean},
            "timeline": list(self.events),
        }

    def _write_incident(self):
        payload = self.incident_report()
        try:
            os.makedirs(self._incident_dir, exist_ok=True)
            path = os.path.join(
                self._incident_dir, "gateway-incident-%d-%d.json"
                % (os.getpid(), int(time.time() * 1e3)))
            with open(path, "w") as f:
                json.dump(payload, f, indent=2)
            self.incident_path = path
            return path
        except OSError as e:  # diagnostics must never mask the exit
            logger.warning("gateway incident artifact write failed: %s",
                           e)
            return None
