"""Paged KV cache: fixed-capacity device pools + host-side page tables.

The decode-side memory design (PAPERS.md "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching"): all KV state lives
in two fixed-shape device pools

    k_pool, v_pool : (num_layers, num_pages + 1, page_size, H, D)

so every prefill/decode executable sees one unchanging buffer shape —
no per-request allocation, no growing tensors, no recompiles.  Requests
own *pages* (rows of the pool), recorded in a per-slot page table the
executables consume as a plain (slots, max_pages) int32 array.

Two deliberate simplifications vs a vLLM-style pager:

* **Reservation admission** — a request is admitted only when pages for
  its whole worst case (prompt + max_new tokens) are free, so an
  admitted request can never stall mid-decode waiting for a page and no
  preemption/swap machinery is needed.  The cost is lower pool
  utilization when requests finish early; the scheduler's continuous
  admission backfills freed pages at the next step boundary.
* **The trash page** — pool row ``num_pages`` is a write-only dump.
  Unreserved page-table entries and inactive slots point at it, so the
  fixed-shape executables can always scatter (padded prefill positions,
  idle slots) without conditionals; nothing ever reads it through a
  validity mask.

Page-table/length bookkeeping is host-side numpy (the scheduler mutates
it between steps); :meth:`device_tables` re-uploads only after a
mutation.  The pools themselves live on device and flow through the
donated executable arguments.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["PagedKVCache"]


class PagedKVCache:
    """Fixed-pool paged KV cache for ``slots`` concurrent requests."""

    def __init__(self, num_layers, num_heads, head_dim, page_size,
                 num_pages, slots, max_pages_per_slot, dtype=None,
                 table_pad=0):
        import jax.numpy as jnp
        import numpy as np

        if min(num_layers, num_heads, head_dim, page_size, num_pages,
               slots, max_pages_per_slot) < 1:
            raise MXNetError("PagedKVCache: all dimensions must be >= 1")
        if table_pad < 0:
            raise MXNetError("PagedKVCache: table_pad must be >= 0")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.slots = int(slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        # extra always-trash table columns past the reservable range, so
        # executables that clip a past-the-reservation write position
        # (the speculative verify's overflow rows) land on the trash
        # page instead of aliasing the slot's last real page
        self.table_pad = int(table_pad)
        self.trash_page = self.num_pages  # reserved last pool row
        dtype = dtype or jnp.float32
        pool_shape = (self.num_layers, self.num_pages + 1, self.page_size,
                      self.num_heads, self.head_dim)
        self.k_pool = jnp.zeros(pool_shape, dtype)
        self.v_pool = jnp.zeros(pool_shape, dtype)
        self._free_pages = list(range(self.num_pages - 1, -1, -1))
        self._free_slots = list(range(self.slots - 1, -1, -1))
        self._tables = np.full((self.slots, self.table_width),
                               self.trash_page, np.int32)
        self._pages_of = {}  # slot -> [page, ...]
        self.lengths = np.zeros((self.slots,), np.int32)
        self._tables_dev = None  # upload cache, invalidated on mutation

    @property
    def table_width(self):
        """Page-table columns: reservable pages + the all-trash pad."""
        return self.max_pages_per_slot + self.table_pad

    # -- capacity ---------------------------------------------------------
    @property
    def free_pages(self):
        return len(self._free_pages)

    @property
    def free_slots(self):
        return len(self._free_slots)

    def pages_needed(self, prompt_len, max_new):
        """Worst-case page reservation for one request."""
        total = int(prompt_len) + int(max_new)
        return -(-total // self.page_size)

    def can_admit(self, prompt_len, max_new):
        need = self.pages_needed(prompt_len, max_new)
        if need > self.max_pages_per_slot:
            raise MXNetError(
                "request needs %d pages (prompt %d + max_new %d at page "
                "size %d) but slots hold at most %d — raise the session's "
                "max context" % (need, prompt_len, max_new,
                                 self.page_size, self.max_pages_per_slot))
        return self._free_slots and len(self._free_pages) >= need

    # -- slot lifecycle ---------------------------------------------------
    def alloc(self, prompt_len, max_new):
        """Reserve a slot + its worst-case pages; returns the slot id or
        ``None`` when either resource is exhausted (the scheduler keeps
        the request queued)."""
        if not self.can_admit(prompt_len, max_new):
            return None
        need = self.pages_needed(prompt_len, max_new)
        slot = self._free_slots.pop()
        pages = [self._free_pages.pop() for _ in range(need)]
        self._pages_of[slot] = pages
        self._tables[slot, :] = self.trash_page
        self._tables[slot, :need] = pages
        self.lengths[slot] = 0
        self._tables_dev = None
        return slot

    def release(self, slot):
        """Return the slot's pages to the free pool (request finished,
        evicted, or failed)."""
        pages = self._pages_of.pop(slot, None)
        if pages is None:
            raise MXNetError("release of unallocated slot %r" % (slot,))
        # keep free lists sorted (descending, pop() takes the end) so the
        # lowest id is always reused first — allocation order stays
        # deterministic no matter the order requests finished in
        self._free_pages.extend(pages)
        self._free_pages.sort(reverse=True)
        self._free_slots.append(slot)
        self._free_slots.sort(reverse=True)
        self._tables[slot, :] = self.trash_page
        self.lengths[slot] = 0
        self._tables_dev = None

    def truncate(self, slot, n_tokens):
        """Roll back the slot's last ``n_tokens`` KV rows (speculative-
        decode rejection).  Host-side O(1): only ``lengths`` shrinks —
        the slot's page reservation is untouched (pages were reserved
        worst-case at admission, so there is nothing to return to the
        free pool) and the vacated rows are invalidated deterministically
        by the length mask every executable applies: positions >= the
        new length are never read, and the next append overwrites them.
        The device page-table upload cache is deliberately NOT touched
        (the invalidate-only-on-alloc/release contract holds): tables do
        not change here, and lengths re-upload every step anyway."""
        if slot not in self._pages_of:
            raise MXNetError("truncate of unallocated slot %r" % (slot,))
        n = int(n_tokens)
        if n < 0:
            raise MXNetError("truncate(%r, %d): negative rollback"
                             % (slot, n))
        if n > int(self.lengths[slot]):
            raise MXNetError(
                "truncate(%r, %d): slot only holds %d tokens"
                % (slot, n, int(self.lengths[slot])))
        self.lengths[slot] -= n

    def active_slots(self):
        return sorted(self._pages_of)

    # -- executable-facing views -----------------------------------------
    def device_tables(self):
        """The (slots, max_pages) int32 page-table array, uploaded only
        when the host copy changed since the last call."""
        import jax.numpy as jnp

        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables)
        return self._tables_dev

    def device_lengths(self):
        import jax.numpy as jnp

        return jnp.asarray(self.lengths)

    def table_row(self, slot):
        import jax.numpy as jnp

        return jnp.asarray(self._tables[slot])

    # -- accounting -------------------------------------------------------
    def pool_bytes(self):
        """Total device bytes held by the two pools — constant for the
        session's lifetime, which IS the O(1) decode-memory story."""
        return int(self.k_pool.nbytes) + int(self.v_pool.nbytes)

    def utilization(self):
        used = self.num_pages - len(self._free_pages)
        return used / float(self.num_pages)
