"""Paged KV cache: fixed-capacity device pools + host-side page tables.

The decode-side memory design (PAPERS.md "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching"): all KV state lives
in two fixed-shape device pools

    k_pool, v_pool : (num_layers, num_pages + 1, page_size, H, D)

so every prefill/decode executable sees one unchanging buffer shape —
no per-request allocation, no growing tensors, no recompiles.  Requests
own *pages* (rows of the pool), recorded in a per-slot page table the
executables consume as a plain (slots, max_pages) int32 array.

Two admission modes (vs the original reservation-only pager):

* **Reservation admission** (default) — a request is admitted only when
  pages for its whole worst case (prompt + max_new tokens) are free, so
  an admitted request can never stall mid-decode waiting for a page and
  no preemption machinery is needed.  The cost is lower pool
  utilization when requests finish early.
* **Oversubscription** (``alloc(..., oversub=True)``, driven by
  ``MXNET_SERVE_OVERSUB``) — admit by *current* need (the prompt pages
  only) and grow on demand at decode boundaries via
  :meth:`append_pages`.  The scheduler watches
  :attr:`reclaimable_pages` against a watermark and preempts requests
  when the pool runs dry; preempted requests re-prefill
  deterministically on resume, so oversubscription changes capacity,
  never content.

**Prefix cache** (``prefix_pages != 0``): a page-aligned token-hash
index over the pool.  :meth:`alloc` matches the prompt's full pages
against a chain hash (page ``i``'s key folds page ``i-1``'s key, so a
hit certifies the whole transcript prefix, not just one page's tokens)
and maps hits read-only into the new slot's table with a reference
count; prefill then runs only on the uncached suffix.
:meth:`register_prefix` publishes a slot's full prompt pages after
prefill so later requests (and preempted-then-resumed ones) hit them.
Pages whose refcount drops to zero are *retained* in LRU order (up to
``prefix_pages`` when positive) and reclaimed lazily — the free heap is
always preferred, so retention never costs an admission.  Shared or
published pages are never written in place: :meth:`ensure_writable` is
the copy-on-write guard every write path crosses.

**The trash page** — pool row ``num_pages`` is a write-only dump.
Unreserved page-table entries and inactive slots point at it, so the
fixed-shape executables can always scatter (padded prefill positions,
idle slots) without conditionals; nothing ever reads it through a
validity mask.

Page-table/length bookkeeping is host-side numpy (the scheduler mutates
it between steps); :meth:`device_tables` re-uploads only after a
mutation.  The pools themselves live on device and flow through the
donated executable arguments.  Free slots and pages are min-heaps
popped lowest-id-first, so allocation order stays deterministic no
matter the order requests finished in (the old implementation re-sorted
a list on every release; the heap keeps the same reuse contract at
O(log n) per op).
"""
from __future__ import annotations

import hashlib
import heapq
from collections import OrderedDict

from ..base import MXNetError

__all__ = ["PagedKVCache"]


def _chain_key(prev_key, page_tokens):
    """Chain hash over one page of prompt tokens: folds the previous
    page's key so equal keys certify equal *transcripts*, not just
    equal final pages.  Content-addressed and deterministic."""
    import numpy as np

    h = hashlib.sha256(prev_key)
    h.update(np.asarray(page_tokens, np.int64).tobytes())
    return h.digest()


class PagedKVCache:
    """Fixed-pool paged KV cache for ``slots`` concurrent requests."""

    def __init__(self, num_layers, num_heads, head_dim, page_size,
                 num_pages, slots, max_pages_per_slot, dtype=None,
                 table_pad=0, prefix_pages=0, kv_quant="",
                 layer_kinds=(), window=0, ring_pages=0):
        import jax.numpy as jnp
        import numpy as np

        from .. import quantize as _quantize

        if min(num_layers, num_heads, head_dim, page_size, num_pages,
               slots, max_pages_per_slot) < 1:
            raise MXNetError("PagedKVCache: all dimensions must be >= 1")
        if table_pad < 0:
            raise MXNetError("PagedKVCache: table_pad must be >= 0")
        if prefix_pages < -1:
            raise MXNetError("PagedKVCache: prefix_pages must be >= -1 "
                             "(-1 = unbounded retention, 0 = off)")
        self.num_layers = int(num_layers)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.slots = int(slots)
        self.max_pages_per_slot = int(max_pages_per_slot)
        # -- hybrid-stack layout ------------------------------------------
        # layer_kinds: per-layer "full" | "window" | "ssm" (empty = all
        # full-attention).  Only FULL layers occupy the paged pools —
        # the pool's layer axis is the full-layer count, so a hybrid
        # stack's page costs proportionally less and a fixed pool budget
        # admits proportionally more slots.  Windowed layers get a fixed
        # ring of ``ring_pages`` pages per slot (``kw_pool``/``vw_pool``,
        # slot-indexed: ring append overwrites the oldest page's rows in
        # place, and the attention mask saturates visibility at the
        # window).  SSM layers get one (H, D, D) fp32 recurrence state
        # per slot (``ssm_state``), the per-layer state pool beside the
        # KV pools.
        self.layer_kinds = tuple(layer_kinds) or ("full",) * self.num_layers
        if len(self.layer_kinds) != self.num_layers:
            raise MXNetError(
                "PagedKVCache: layer_kinds %r does not cover %d layers"
                % (self.layer_kinds, self.num_layers))
        bad = set(self.layer_kinds) - {"full", "window", "ssm"}
        if bad:
            raise MXNetError("PagedKVCache: unknown layer kinds %r"
                             % sorted(bad))
        self.n_full = self.layer_kinds.count("full")
        self.n_window = self.layer_kinds.count("window")
        self.n_ssm = self.layer_kinds.count("ssm")
        self.window = int(window)
        self.ring_pages = int(ring_pages)
        if self.n_window and (self.window < 1 or self.ring_pages < 1):
            raise MXNetError(
                "PagedKVCache: windowed layers need window >= 1 and "
                "ring_pages >= 1 (got window=%d, ring_pages=%d)"
                % (self.window, self.ring_pages))
        self.ring_tokens = self.ring_pages * self.page_size
        # extra always-trash table columns past the reservable range, so
        # executables that clip a past-the-reservation write position
        # (the speculative verify's overflow rows) land on the trash
        # page instead of aliasing the slot's last real page
        self.table_pad = int(table_pad)
        # prefix-cache retention cap: 0 disables the token-hash index
        # entirely, -1 retains refcount-0 pages without bound (the pool
        # size is the real bound), > 0 caps retained pages LRU-first
        self.prefix_pages = int(prefix_pages)
        self.trash_page = self.num_pages  # reserved last pool row
        # quantized pages: pools store 1-byte int8/e4m3 codes and a
        # parallel (L, pages + 1, page_size) float32 scale pool holds
        # one scale per (layer, token) row — indexed by the SAME
        # (page, offset) the codes are, so the page tables, COW, and
        # preempt/resume machinery never know quantization exists
        self.kv_quant = _quantize.quant_mode(kv_quant)
        if self.kv_quant:
            dtype = jnp.dtype(_quantize.quant_dtype(self.kv_quant))
        else:
            dtype = dtype or jnp.float32
        pool_shape = (max(self.n_full, 1), self.num_pages + 1,
                      self.page_size, self.num_heads, self.head_dim)
        self.k_pool = jnp.zeros(pool_shape, dtype)
        self.v_pool = jnp.zeros(pool_shape, dtype)
        if self.kv_quant:
            scale_shape = pool_shape[:3]
            self.k_scale = jnp.ones(scale_shape, jnp.float32)
            self.v_scale = jnp.ones(scale_shape, jnp.float32)
        else:
            self.k_scale = self.v_scale = None
        # windowed-layer rings: slot-indexed, no page table — every slot
        # owns exactly ring_pages pages for each windowed layer, for the
        # session's whole lifetime (that is the O(1)-per-slot story)
        if self.n_window:
            ring_shape = (self.n_window, self.slots, self.ring_tokens,
                          self.num_heads, self.head_dim)
            self.kw_pool = jnp.zeros(ring_shape, dtype)
            self.vw_pool = jnp.zeros(ring_shape, dtype)
            if self.kv_quant:
                self.kw_scale = jnp.ones(ring_shape[:3], jnp.float32)
                self.vw_scale = jnp.ones(ring_shape[:3], jnp.float32)
            else:
                self.kw_scale = self.vw_scale = None
        else:
            self.kw_pool = self.vw_pool = None
            self.kw_scale = self.vw_scale = None
        # SSM state pool: fp32 regardless of kv_quant — the state is a
        # running accumulator, not content-addressed KV rows; quantizing
        # it would break the chunked-prefill == serial-decode contract
        if self.n_ssm:
            self.ssm_state = jnp.zeros(
                (self.n_ssm, self.slots, self.num_heads, self.head_dim,
                 self.head_dim), jnp.float32)
        else:
            self.ssm_state = None
        # min-heaps: heappop yields the lowest free id, preserving the
        # deterministic lowest-first reuse contract (a sorted range is
        # already a valid heap)
        self._free_pages = list(range(self.num_pages))
        self._free_slots = list(range(self.slots))
        self._tables = np.full((self.slots, self.table_width),
                               self.trash_page, np.int32)
        self._pages_of = {}    # slot -> [page, ...] (prefix hits first)
        self._cached_len = {}  # slot -> tokens covered by mapped hits
        self.lengths = np.zeros((self.slots,), np.int32)
        self._tables_dev = None  # upload cache, invalidated on mutation
        # -- prefix-cache state ------------------------------------------
        self._refcount = {}  # page -> count of slots currently mapping it
        self._index = {}     # chain key -> page (published prefix pages)
        self._key_of = {}    # page -> chain key (reverse of _index)
        self._retained = OrderedDict()  # refcount-0 published pages, LRU
        self.prefix_stats = {"lookups": 0, "hits": 0, "hit_pages": 0,
                             "hit_tokens": 0, "published_pages": 0,
                             "evicted_pages": 0, "cow_copies": 0}

    @property
    def table_width(self):
        """Page-table columns: reservable pages + the all-trash pad."""
        return self.max_pages_per_slot + self.table_pad

    # -- capacity ---------------------------------------------------------
    @property
    def free_pages(self):
        return len(self._free_pages)

    @property
    def free_slots(self):
        return len(self._free_slots)

    @property
    def retained_pages(self):
        """Published prefix pages no live request maps (reclaimable)."""
        return len(self._retained)

    @property
    def reclaimable_pages(self):
        """Pages an allocation could obtain right now: the free heap
        plus retained prefix pages it may lazily evict.  This is the
        quantity the scheduler's oversubscription watermark watches."""
        return len(self._free_pages) + len(self._retained)

    @property
    def hybrid(self):
        """True when the stack holds any windowed or SSM layer."""
        return bool(self.n_window or self.n_ssm)

    def pages_needed(self, prompt_len, max_new):
        """Worst-case page reservation for one request.  Pool pages hold
        FULL-attention layers only — a stack with none needs no pages at
        all (ring and state buffers are per-slot and pre-reserved), so
        admission is bounded by slots alone."""
        if not self.n_full:
            return 0
        total = int(prompt_len) + int(max_new)
        return -(-total // self.page_size)

    def can_admit(self, prompt_len, max_new, tokens=None, oversub=False):
        need = self.pages_needed(prompt_len, max_new)
        if need > self.max_pages_per_slot:
            raise MXNetError(
                "request needs %d pages (prompt %d + max_new %d at page "
                "size %d) but slots hold at most %d — raise the session's "
                "max context" % (need, prompt_len, max_new,
                                 self.page_size, self.max_pages_per_slot))
        if not self._free_slots:
            return False
        hit = self._usable_hit(tokens, prompt_len)
        fresh = self._fresh_needed(prompt_len, max_new, hit, oversub)
        return self._available_for(hit) >= fresh

    def _usable_hit(self, tokens, prompt_len):
        """Longest mapped-page chain the prompt may reuse: full pages
        whose chain key is published, capped so at least one prompt
        token is always left for prefill (the suffix computes the
        request's first logits, and suffix offsets stay page-aligned).

        Hybrid stacks: a usable hit must restore EVERY layer kind's
        state at the resume boundary.  Published pool pages restore the
        full-attention layers, but window rings and SSM states are
        slot-private — the only window-aligned boundary at which they
        are reconstructible without recomputation is offset 0, so hits
        cap at zero pages and hybrid prompts always prefill cold (see
        :meth:`register_prefix`)."""
        if tokens is None or not self.prefix_pages or self.hybrid:
            return []
        hit = self.match_prefix(tokens)
        cap = (int(prompt_len) - 1) // self.page_size
        return hit[:cap]

    def _fresh_needed(self, prompt_len, max_new, hit, oversub):
        if oversub:
            now = -(-int(prompt_len) // self.page_size)
        else:
            now = self.pages_needed(prompt_len, max_new)
        return max(now - len(hit), 0)

    def _available_for(self, hit):
        """Pages obtainable without touching the hit set (hit pages may
        themselves sit in the retained LRU; they are about to be
        re-activated, not evicted)."""
        hits = set(hit)
        avail = len(self._free_pages)
        avail += sum(1 for p in self._retained if p not in hits)
        return avail

    # -- prefix index -----------------------------------------------------
    def match_prefix(self, tokens):
        """Pages of the longest published chain prefix of ``tokens``
        (full pages only; stops at the first unpublished page)."""
        if not self.prefix_pages:
            return []
        pages = []
        key = b""
        n_full = len(tokens) // self.page_size
        for i in range(n_full):
            key = _chain_key(
                key, tokens[i * self.page_size:(i + 1) * self.page_size])
            page = self._index.get(key)
            if page is None:
                break
            pages.append(page)
        return pages

    def register_prefix(self, slot, tokens):
        """Publish the slot's full prompt pages into the token-hash
        index (called after prefill, when their KV is final — positions
        below the committed length are never rewritten).  Pages already
        published under the same chain (the slot's own hits) are left
        alone; a chain another slot published concurrently wins and this
        slot's duplicate page stays private.  Returns pages published.

        Hybrid stacks publish nothing: :meth:`_usable_hit` can never map
        the pages (window rings / SSM states cannot ride along), so
        publishing would only pin pool pages in the retained LRU."""
        if not self.prefix_pages or self.hybrid:
            return 0
        pages = self._pages_of.get(slot)
        if pages is None:
            raise MXNetError("register_prefix of unallocated slot %r"
                             % (slot,))
        key = b""
        published = 0
        n_full = min(len(tokens) // self.page_size, len(pages))
        for i in range(n_full):
            key = _chain_key(
                key, tokens[i * self.page_size:(i + 1) * self.page_size])
            page = pages[i]
            if key in self._index or page in self._key_of:
                continue
            self._index[key] = page
            self._key_of[page] = key
            published += 1
        self.prefix_stats["published_pages"] += published
        return published

    def cached_len(self, slot):
        """Prompt tokens covered by mapped prefix hits at admission —
        the position prefill starts from."""
        return self._cached_len.get(slot, 0)

    def _take_page(self):
        """Lowest free page, or — free heap empty — the least-recently
        retained prefix page, unpublished and recycled."""
        if self._free_pages:
            return heapq.heappop(self._free_pages)
        if not self._retained:
            raise MXNetError("page pool exhausted (no free or retained "
                             "pages) — preempt or release a request first")
        page, key = self._retained.popitem(last=False)
        del self._index[key]
        del self._key_of[page]
        self.prefix_stats["evicted_pages"] += 1
        return page

    def _drop_ref(self, page):
        """Release one slot's hold on ``page``; a published page whose
        count hits zero is retained (evictable), others go back to the
        free heap."""
        rc = self._refcount.get(page, 0) - 1
        if rc > 0:
            self._refcount[page] = rc
            return
        self._refcount.pop(page, None)
        key = self._key_of.get(page)
        if key is not None and self.prefix_pages:
            self._retained[page] = key
        else:
            heapq.heappush(self._free_pages, page)

    def _enforce_retention_cap(self):
        if self.prefix_pages <= 0:
            return
        while len(self._retained) > self.prefix_pages:
            page, key = self._retained.popitem(last=False)
            del self._index[key]
            del self._key_of[page]
            self.prefix_stats["evicted_pages"] += 1
            heapq.heappush(self._free_pages, page)

    # -- slot lifecycle ---------------------------------------------------
    def alloc(self, prompt_len, max_new, tokens=None, oversub=False):
        """Admit a request: reserve a slot plus its pages — the worst
        case by default, the *current* need (prompt pages only) under
        ``oversub`` — mapping published prefix pages first when
        ``tokens`` is given and the index hits.  Returns the slot id or
        ``None`` when either resource is exhausted (the scheduler keeps
        the request queued); :meth:`cached_len` reports how many prompt
        tokens the mapped hits already cover."""
        if not self.can_admit(prompt_len, max_new, tokens=tokens,
                              oversub=oversub):
            return None
        hit = self._usable_hit(tokens, prompt_len)
        fresh = self._fresh_needed(prompt_len, max_new, hit, oversub)
        slot = heapq.heappop(self._free_slots)
        for page in hit:
            self._retained.pop(page, None)  # re-activated, not evictable
            self._refcount[page] = self._refcount.get(page, 0) + 1
        pages = list(hit)
        for _ in range(fresh):
            page = self._take_page()
            self._refcount[page] = 1
            pages.append(page)
        self._pages_of[slot] = pages
        self._tables[slot, :] = self.trash_page
        self._tables[slot, :len(pages)] = pages
        self._cached_len[slot] = len(hit) * self.page_size
        # lengths starts AT the cached prefix, not 0: fixed-shape
        # executables write junk rows for every slot at its current
        # length, and those must land in the slot's private fresh pages
        # (suffix prefill overwrites them), never inside a shared hit
        # page
        self.lengths[slot] = self._cached_len[slot]
        self._tables_dev = None
        # SSM recurrence starts from a zero state at offset 0; ring
        # rows need no scrub — the position labels the windowed gather
        # computes for a fresh request exclude every row the request has
        # not itself written (stale rows label as position < 0)
        if self.ssm_state is not None:
            self.ssm_state = self.ssm_state.at[:, slot].set(0.0)
        if tokens is not None and self.prefix_pages:
            self.prefix_stats["lookups"] += 1
            if hit:
                self.prefix_stats["hits"] += 1
                self.prefix_stats["hit_pages"] += len(hit)
                self.prefix_stats["hit_tokens"] += \
                    len(hit) * self.page_size
        return slot

    def append_pages(self, slot, new_len):
        """Grow the slot's mapped pages to cover ``new_len`` token
        positions (capped at the reservable range — speculative rows
        past it land on the trash pad by design).  On-demand growth for
        oversubscribed admission; a no-op when the slot already covers
        the range (always, under reservation).  Returns pages appended;
        raises when the pool cannot supply — the scheduler's watermark
        preemption runs first precisely so this never fires."""
        pages = self._pages_of.get(slot)
        if pages is None:
            raise MXNetError("append_pages of unallocated slot %r"
                             % (slot,))
        need = min(-(-int(new_len) // self.page_size),
                   self.max_pages_per_slot)
        added = 0
        while len(pages) < need:
            page = self._take_page()
            self._refcount[page] = 1
            self._tables[slot, len(pages)] = page
            pages.append(page)
            added += 1
        if added:
            self._tables_dev = None
        return added

    def pages_short(self, slot, new_len):
        """Pages :meth:`append_pages` would have to obtain to cover
        ``new_len`` positions — the scheduler's per-step need probe."""
        pages = self._pages_of.get(slot)
        if pages is None:
            raise MXNetError("pages_short of unallocated slot %r"
                             % (slot,))
        need = min(-(-int(new_len) // self.page_size),
                   self.max_pages_per_slot)
        return max(need - len(pages), 0)

    def ensure_writable(self, slot, start_pos, n_rows=1):
        """Copy-on-write guard: before a dispatch writes KV rows
        [``start_pos``, ``start_pos + n_rows``) for ``slot``, make every
        mapped page in that range private.  A page other slots also map
        (refcount > 1) is copied device-side into a fresh page and the
        table repointed, so readers of the shared page never observe the
        write; a page only *published* (refcount 1 but in the index) is
        cheaper — it is unpublished in place, since no one else reads
        it yet.  The natural write paths (suffix prefill, decode,
        verify) only ever touch positions past the shared prefix, so
        this is a no-op there; it exists so that no future write path
        can corrupt a shared page by construction.  Returns pages
        copied."""
        pages = self._pages_of.get(slot)
        if pages is None:
            raise MXNetError("ensure_writable of unallocated slot %r"
                             % (slot,))
        if n_rows < 1:
            return 0
        first = max(int(start_pos), 0) // self.page_size
        last = (int(start_pos) + int(n_rows) - 1) // self.page_size
        copied = 0
        for idx in range(first, min(last + 1, len(pages))):
            page = pages[idx]
            shared = self._refcount.get(page, 0) > 1
            published = page in self._key_of
            if not shared and not published:
                continue
            if not shared:
                # sole holder: unpublish and write in place (chains
                # beyond this page become unreachable and age out of
                # the retained LRU like any cold entry)
                key = self._key_of.pop(page)
                self._index.pop(key, None)
                self._retained.pop(page, None)
                continue
            new = self._take_page()
            # device-side page copy across all layers in one op; pure
            # copy, so the private page is bit-identical to the shared
            # one and the stream stays exact
            self.k_pool = self.k_pool.at[:, new].set(self.k_pool[:, page])
            self.v_pool = self.v_pool.at[:, new].set(self.v_pool[:, page])
            if self.kv_quant:  # scale rows travel with their codes
                self.k_scale = self.k_scale.at[:, new].set(
                    self.k_scale[:, page])
                self.v_scale = self.v_scale.at[:, new].set(
                    self.v_scale[:, page])
            self._refcount[new] = 1
            pages[idx] = new
            self._tables[slot, idx] = new
            self._drop_ref(page)
            copied += 1
        if copied:
            self._tables_dev = None
            self.prefix_stats["cow_copies"] += copied
        return copied

    def release(self, slot):
        """Return the slot's resources (request finished, evicted, or
        failed).  Refcount-aware: shared prefix pages survive for their
        other holders, and published pages this slot held alone are
        retained for future hits instead of freed."""
        pages = self._pages_of.pop(slot, None)
        if pages is None:
            raise MXNetError("release of unallocated slot %r" % (slot,))
        for page in pages:
            self._drop_ref(page)
        self._enforce_retention_cap()
        heapq.heappush(self._free_slots, slot)
        self._tables[slot, :] = self.trash_page
        self.lengths[slot] = 0
        self._cached_len.pop(slot, None)
        self._tables_dev = None

    def truncate(self, slot, n_tokens):
        """Roll back the slot's last ``n_tokens`` KV rows (speculative-
        decode rejection).  Host-side O(1): only ``lengths`` shrinks —
        the slot's page mapping is untouched (vacated pages are reused
        when the length catches up again) and the vacated rows are
        invalidated deterministically by the length mask every
        executable applies: positions >= the new length are never read,
        and the next append overwrites them.  The device page-table
        upload cache is deliberately NOT touched (the invalidate-only-
        on-table-mutation contract holds): tables do not change here,
        and lengths re-upload every step anyway.

        Hybrid stacks stay O(1) too.  Window rings: the position -> ring
        row map is deterministic, so the rejected rows' ring slots are
        exactly the ones the re-issued positions overwrite next step,
        and the windowed mask (driven by the rolled-back length) never
        reads them in between — rolling back ``lengths`` IS rolling back
        the ring position.  SSM state: the verify executable selects the
        committed snapshot in-graph before returning (see
        ``model.verify_step``), so by the time the host truncates, the
        state pool already holds the post-commit state."""
        if slot not in self._pages_of:
            raise MXNetError("truncate of unallocated slot %r" % (slot,))
        n = int(n_tokens)
        if n < 0:
            raise MXNetError("truncate(%r, %d): negative rollback"
                             % (slot, n))
        if n > int(self.lengths[slot]):
            raise MXNetError(
                "truncate(%r, %d): slot only holds %d tokens"
                % (slot, n, int(self.lengths[slot])))
        self.lengths[slot] -= n

    def active_slots(self):
        return sorted(self._pages_of)

    def drop_prefix_index(self):
        """Forget every published prefix chain (replica cold rejoin:
        a restarted replica's pool holds no reusable KV, so its index
        must not advertise any).  Retained refcount-0 pages go back to
        the free heap; pages live slots still map merely lose their
        published key — their holders keep decoding untouched and the
        pages free normally on release.  Returns pages unpublished."""
        dropped = len(self._key_of)
        for page in self._retained:
            heapq.heappush(self._free_pages, page)
        self._retained.clear()
        self._index.clear()
        self._key_of.clear()
        return dropped

    # -- executable-facing views -----------------------------------------
    def device_tables(self):
        """The (slots, max_pages) int32 page-table array, uploaded only
        when the host copy changed since the last call."""
        import jax.numpy as jnp

        if self._tables_dev is None:
            self._tables_dev = jnp.asarray(self._tables)
        return self._tables_dev

    def device_lengths(self):
        import jax.numpy as jnp

        return jnp.asarray(self.lengths)

    def table_row(self, slot):
        import jax.numpy as jnp

        return jnp.asarray(self._tables[slot])

    # -- accounting -------------------------------------------------------
    def pool_bytes(self):
        """Total device bytes held by the pools (scale pools included
        for quantized caches) — constant for the session's lifetime,
        which IS the O(1) decode-memory story."""
        total = int(self.k_pool.nbytes) + int(self.v_pool.nbytes)
        if self.kv_quant:
            total += int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        if self.kw_pool is not None:
            total += int(self.kw_pool.nbytes) + int(self.vw_pool.nbytes)
            if self.kv_quant:
                total += (int(self.kw_scale.nbytes)
                          + int(self.vw_scale.nbytes))
        if self.ssm_state is not None:
            total += int(self.ssm_state.nbytes)
        return total

    @classmethod
    def page_bytes(cls, num_layers, num_heads, head_dim, page_size,
                   kv_quant=""):
        """Device bytes ONE page costs (k + v codes, plus scale rows
        for quantized caches) — what the capacity-at-fixed-bytes A/B in
        bench_serve.py divides a pool budget by."""
        import numpy as np

        from .. import quantize as _quantize

        mode = _quantize.quant_mode(kv_quant)
        itemsize = (np.dtype(_quantize.quant_dtype(mode)).itemsize
                    if mode else 4)
        per_row = num_heads * head_dim * itemsize + (4 if mode else 0)
        return 2 * num_layers * page_size * per_row

    def utilization(self):
        used = self.num_pages - len(self._free_pages)
        return used / float(self.num_pages)
