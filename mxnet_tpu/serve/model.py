"""Functional transformer decoder for the serving runtime.

The training side runs the symbolic graph (``models/transformer.py`` →
``ops/nn_ops.py``); serving needs the same network as a *pure function*
it can specialize three ways — full-context reference forward, bucketed
prefill (full forward + KV page writes), and the O(1) single-token
decode step — over one parameter dict.  This module is that function,
written against the exact op semantics of the training kernels
(``FullyConnected``'s ``(out, in)`` weight layout, ``LayerNorm`` at
eps 1e-5 in rsqrt form, the MHA in/out projection einsums, head split
``(n, t, h, d) -> (n, h, t, d)``, ``jax.nn.gelu``) and parameterized by
the training graph's own parameter names (``tok_embed_weight``,
``blk{i}_attn_in_weight`` …), so a ``CheckpointManager`` restore of a
training run drops straight in.

Bit-exactness contract (the serving acceptance criterion): with
``exact=True`` every matmul uses the M-invariant broadcast-multiply-
reduce form and attention runs the ``mi=True`` flash/decode kernels, so
a token decoded through the paged KV cache is bit-identical to the same
position of a full-context forward.  XLA's gemm accumulation order
depends on the M dimension (a 1-row projection differs from row T of a
T-row projection by ~1 ulp), which is why plain einsums cannot make
that guarantee; ``exact=False`` restores them for production serving
where ulp-level drift is acceptable and gemm throughput matters.
``MXNET_SERVE_EXACT`` picks the default.

The oracle for that contract is :func:`reference_last_logits`: a jitted
full-context forward padded to the next ``page_size`` multiple, so the
reference runs the *same attention-block geometry* as the serving
executables (whole-program XLA fusion is itself shape-dependent — an
unpadded T=9 forward and a padded T=16 one differ by ~1 ulp at some
widths, so the reference must share the padded shape family; causal
masking makes the pad positions exact no-ops).
"""
from __future__ import annotations

import dataclasses
import functools

from ..base import MXNetError, get_env
from ..ops.attention import decode_attention, flash_attention

__all__ = ["ModelConfig", "exact_mode", "init_params", "config_from_params",
           "full_forward", "prefill_forward", "decode_step", "verify_step",
           "draft_propose", "reference_last_logits"]


def exact_mode():
    """Default for the ``exact`` knob (``MXNET_SERVE_EXACT``, default 1):
    bit-exact M-invariant matmuls vs plain gemms."""
    return get_env("MXNET_SERVE_EXACT", True, bool)


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Static decoder geometry (everything the traced functions close
    over).

    ``layer_kinds``/``window`` describe a hybrid stack: per layer,
    ``"full"`` (paged full-context attention), ``"window"`` (sliding-
    window attention over the last ``window`` keys, ring-buffered KV),
    or ``"ssm"`` (linear-attention recurrence, O(1) state — see
    ``ops/ssm_ops.py``).  All kinds reuse the block's existing
    ``attn_in``/``attn_out`` weights, so any attention checkpoint hosts
    any stack.  The empty tuple means all-full (the classic decoder).
    """
    vocab_size: int
    num_layers: int
    d_model: int
    num_heads: int
    max_len: int          # pos_embed rows == the context ceiling
    window: int = 0       # sliding-window length for "window" layers
    layer_kinds: tuple = ()  # per-layer kind; () = all "full"

    @property
    def head_dim(self):
        return self.d_model // self.num_heads

    @property
    def kinds(self):
        """Per-layer kinds, expanded to ``num_layers`` entries."""
        return self.layer_kinds or ("full",) * self.num_layers

    @property
    def hybrid(self):
        return any(k != "full" for k in self.kinds)

    def validate(self):
        if self.d_model % self.num_heads:
            raise MXNetError("d_model %d not divisible by num_heads %d"
                             % (self.d_model, self.num_heads))
        if self.layer_kinds:
            if len(self.layer_kinds) != self.num_layers:
                raise MXNetError(
                    "layer_kinds %r does not cover %d layers"
                    % (self.layer_kinds, self.num_layers))
            bad = set(self.layer_kinds) - {"full", "window", "ssm"}
            if bad:
                raise MXNetError("unknown layer kinds %r" % sorted(bad))
            if "window" in self.layer_kinds and self.window < 1:
                raise MXNetError(
                    "windowed layers need window >= 1 (got %d)"
                    % self.window)
        return self


def _resolve_params(params):
    """See through a weight-only quantized params tree (name ->
    ``{"q", "s"}``, see ``mxnet_tpu.quantize``): dequantize to float32
    *inside* the traced function, so the executable's arguments stay
    1-byte codes while every matmul runs full precision.  Dequantization
    is an elementwise convert + multiply, so the resolved weight VALUES
    are identical across executables — which is why the M-invariant
    bit-exactness contract below holds per precision (quantized serial
    decode == quantized batched verify)."""
    if any(isinstance(v, dict) for v in params.values()):
        from ..quantize import dequantize_params

        return dequantize_params(params)
    return params


def _mm(x, w, exact):
    """``x (..., C) @ w (F, C)^T -> (..., F)`` — the ``FullyConnected``/
    MHA-projection contraction.  ``exact`` selects the M-invariant
    reduce form (each output element sums over C in an order independent
    of the leading dims)."""
    if exact:
        return (x[..., None, :] * w).sum(axis=-1)
    import jax.numpy as jnp

    return jnp.einsum("...c,fc->...f", x, w)


def _layer_norm(x, gamma, beta):
    """Training ``LayerNorm`` semantics: axis -1, eps 1e-5, rsqrt form.
    Row-wise, so it is M-invariant as-is."""
    import jax.numpy as jnp
    from jax import lax

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    return (x - mean) * lax.rsqrt(var + 1e-5) * gamma + beta


def init_params(cfg, seed=0, scale=0.02):
    """Fresh float32 parameters under the training graph's names (for
    benches/tests; real deployments restore a checkpoint)."""
    import jax
    import jax.numpy as jnp

    cfg.validate()
    keys = iter(jax.random.split(jax.random.PRNGKey(seed),
                                 4 * cfg.num_layers + 4))

    def normal(shape):
        return (scale * jax.random.normal(next(keys), shape)
                ).astype(jnp.float32)

    c, v = cfg.d_model, cfg.vocab_size
    params = {
        "tok_embed_weight": normal((v, c)),
        "pos_embed": normal((1, cfg.max_len, c)),
        "final_ln_gamma": jnp.ones((c,), jnp.float32),
        "final_ln_beta": jnp.zeros((c,), jnp.float32),
        "lm_head_weight": normal((v, c)),
        "lm_head_bias": jnp.zeros((v,), jnp.float32),
    }
    for i in range(cfg.num_layers):
        params.update({
            "blk%d_ln1_gamma" % i: jnp.ones((c,), jnp.float32),
            "blk%d_ln1_beta" % i: jnp.zeros((c,), jnp.float32),
            "blk%d_attn_in_weight" % i: normal((3 * c, c)),
            "blk%d_attn_in_bias" % i: jnp.zeros((3 * c,), jnp.float32),
            "blk%d_attn_out_weight" % i: normal((c, c)),
            "blk%d_attn_out_bias" % i: jnp.zeros((c,), jnp.float32),
            "blk%d_ln2_gamma" % i: jnp.ones((c,), jnp.float32),
            "blk%d_ln2_beta" % i: jnp.zeros((c,), jnp.float32),
            "blk%d_ffn1_weight" % i: normal((4 * c, c)),
            "blk%d_ffn1_bias" % i: jnp.zeros((4 * c,), jnp.float32),
            "blk%d_ffn2_weight" % i: normal((c, 4 * c)),
            "blk%d_ffn2_bias" % i: jnp.zeros((c,), jnp.float32),
        })
    return params


def config_from_params(params, num_heads):
    """Derive the :class:`ModelConfig` from parameter shapes (everything
    except ``num_heads`` — head count does not appear in any shape)."""
    if "tok_embed_weight" not in params or "pos_embed" not in params:
        raise MXNetError(
            "not a transformer LM parameter dict (expected "
            "tok_embed_weight / pos_embed; got %s)"
            % sorted(params)[:8])

    def _shape(v):
        # quantized entries keep the canonical shape on their codes
        return v["q"].shape if isinstance(v, dict) else v.shape

    vocab, d_model = _shape(params["tok_embed_weight"])
    max_len = _shape(params["pos_embed"])[1]
    n = 0
    while "blk%d_attn_in_weight" % n in params:
        n += 1
    if n == 0:
        raise MXNetError("no blk0_attn_in_weight — zero decoder layers?")
    return ModelConfig(vocab_size=int(vocab), num_layers=n,
                       d_model=int(d_model), num_heads=int(num_heads),
                       max_len=int(max_len)).validate()


def _attn_heads(x, n, t, h, d):
    return x.reshape(n, t, h, d).transpose(0, 2, 1, 3)


def _kv_append(pool, scale_pool, i, pages, offsets, rows, kv_quant):
    """Scatter a batch of KV rows into layer ``i`` of the page pool.

    ``rows`` is (N, H, D) — one row per token.  With ``kv_quant`` each
    row quantizes independently (codes into the storage pool, one
    float32 scale per row into the parallel scale pool), so a page's
    bytes are a pure function of the tokens written to it — the property
    that keeps prefill scatter, serial decode append, batched verify
    append, prefix-hit replay and preempt/re-prefill byte-identical.
    """
    if kv_quant:
        from .. import quantize as _q

        codes, scales = _q.kv_quantize_rows(rows, kv_quant)
        pool = pool.at[i, pages, offsets].set(codes)
        scale_pool = scale_pool.at[i, pages, offsets].set(scales)
        return pool, scale_pool
    return pool.at[i, pages, offsets].set(rows.astype(pool.dtype)), scale_pool


def _kv_fake_quant(k, v, kv_quant):
    """Reference-side half of the per-precision bit-exactness oracle:
    quantize-dequantize the (n, H, T, D) head tensors per token with the
    exact helper the paged path scatters with, so a full-context forward
    sees the same dequantized KV VALUES the paged kernels reconstruct
    in-block (the dequant is elementwise, hence order-independent)."""
    if not kv_quant:
        return k, v
    from .. import quantize as _q

    def _fq(t):
        rows = t.transpose(0, 2, 1, 3)          # (n, T, H, D): per-token rows
        q, s = _q.kv_quantize_rows(rows, kv_quant)
        return _q.kv_dequantize(q, s).transpose(0, 2, 1, 3)

    return _fq(k), _fq(v)


def _qkv_heads(params, i, x, cfg, exact):
    """Shared sublayer head: pre-norm + in-projection + head split.
    Returns (q, k, v) as (n, H, T, D) — identical ops for every layer
    kind, so hybrid stacks share the projection's bit pattern."""
    import jax.numpy as jnp

    n, t, _ = x.shape
    h, d = cfg.num_heads, cfg.head_dim
    hdn = _layer_norm(x, params["blk%d_ln1_gamma" % i],
                      params["blk%d_ln1_beta" % i])
    qkv = _mm(hdn, params["blk%d_attn_in_weight" % i], exact) \
        + params["blk%d_attn_in_bias" % i]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    return (_attn_heads(q, n, t, h, d), _attn_heads(k, n, t, h, d),
            _attn_heads(v, n, t, h, d))


def _pool_pack(k_pool, v_pool, k_scale, v_scale, kw_pool, vw_pool,
               kw_scale, vw_scale, ssm_state, kv_quant):
    """Canonical pool ordering every serve executable returns (and the
    session's ``_pool_args``/``_store_pools`` mirror): paged pools, the
    paged scales (kv_quant), the window rings, the ring scales
    (kv_quant), then the SSM state pool.  Absent pools are simply
    omitted, so the classic all-full stack keeps its historical
    signature byte-for-byte."""
    pools = [k_pool, v_pool]
    if kv_quant:
        pools += [k_scale, v_scale]
    if kw_pool is not None:
        pools += [kw_pool, vw_pool]
        if kv_quant:
            pools += [kw_scale, vw_scale]
    if ssm_state is not None:
        pools.append(ssm_state)
    return tuple(pools)


def _pool_names(kv_quant, has_window, has_ssm):
    """Keyword names matching :func:`_pool_pack`'s ordering — lets a
    caller re-bind a packed pool tuple onto the executables' signatures
    without hand-maintaining the order in two places."""
    names = ["k_pool", "v_pool"]
    if kv_quant:
        names += ["k_scale", "v_scale"]
    if has_window:
        names += ["kw_pool", "vw_pool"]
        if kv_quant:
            names += ["kw_scale", "vw_scale"]
    if has_ssm:
        names.append("ssm_state")
    return tuple(names)


def _block_attention(params, i, x, cfg, exact, block, kv_quant="",
                     window=0):
    """One pre-norm attention sublayer on (n, T, C); returns the
    residual-added activations plus this layer's (k, v) heads —
    (n, H, T, D) each, the page-writable prefill byproduct.  With
    ``kv_quant`` the keys/values are fake-quantized per token before
    attention, mirroring what a paged reader reconstructs.  ``window``
    restricts attention to the last ``window`` keys (the windowed-layer
    reference path)."""
    n, t, c = x.shape
    q, k, v = _qkv_heads(params, i, x, cfg, exact)
    k, v = _kv_fake_quant(k, v, kv_quant)
    ctx = flash_attention(q, k, v, causal=True, block=block, mi=exact,
                          window=window)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(n, t, c)
    out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
        + params["blk%d_attn_out_bias" % i]
    return x + out, (k, v)


def _block_ssm(params, i, x, cfg, exact, state0=None, row_valid=None,
               collect=False):
    """One SSM (linear-attention) sublayer on (n, T, C): the recurrence
    of ``ops/ssm_ops.py`` fed by the block's own q/k/v projections.
    ``state0`` (n, H, D, D) fp32 is the pre-scan state (zeros for a
    from-scratch forward); ``row_valid`` masks bucket padding out of the
    state.  Returns (x + out, state[, states]) — ``states`` (T, n, H, D,
    D) per-row snapshots when ``collect`` (the verify step's O(1)
    rollback source).  K/V are consumed in-register and never stored,
    so ``kv_quant`` does not apply (the state pool is fp32)."""
    import jax.numpy as jnp

    from ..ops.ssm_ops import ssm_decay, ssm_scan

    n, t, c = x.shape
    q, k, v = _qkv_heads(params, i, x, cfg, exact)
    # scan wants rows-major (n, T, H, D)
    q, k, v = (q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
               v.transpose(0, 2, 1, 3))
    if state0 is None:
        state0 = jnp.zeros((n, cfg.num_heads, cfg.head_dim, cfg.head_dim),
                           jnp.float32)
    res = ssm_scan(q, k, v, state0, ssm_decay(cfg.num_heads),
                   row_valid=row_valid, collect=collect)
    y = res[0].astype(x.dtype)
    ctx = y.reshape(n, t, c)
    out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
        + params["blk%d_attn_out_bias" % i]
    return (x + out,) + res[1:]


def _ring_append(pool, scale_pool, i, slot_ids, rows_idx, rows, kv_quant):
    """Scatter KV rows into windowed layer ``i``'s per-slot ring.

    pool: (Lw, S, R, H, D); ``slot_ids``/``rows_idx`` broadcastable int
    arrays selecting (slot, ring row) per written token; ``rows`` the
    matching (..., H, D) values.  Quantization is per row with the same
    helper the paged pools use, so ring bytes are a pure function of
    the token written — the preempt/re-prefill and COW arguments carry
    over to rings unchanged."""
    if kv_quant:
        from .. import quantize as _q

        codes, scales = _q.kv_quantize_rows(rows, kv_quant)
        pool = pool.at[i, slot_ids, rows_idx].set(codes)
        scale_pool = scale_pool.at[i, slot_ids, rows_idx].set(scales)
        return pool, scale_pool
    return (pool.at[i, slot_ids, rows_idx].set(rows.astype(pool.dtype)),
            scale_pool)


def _ring_gather(pool, scale_pool, i, pb_max, page_size, kv_quant,
                 slot=None):
    """Gather a ring in ascending-absolute-position order.

    ``pb_max``: (S,) int32 — the highest absolute PAGE index written
    (the newest page).  The ring's pages are rotated so the gathered
    page ``j`` is absolute page ``pb_max - ring_pages + 1 + j``; each
    row is labeled with its absolute position (``k_positions``) so the
    windowed mask in :func:`..ops.attention.decode_attention` sees
    page-aligned blocks in exactly the reference forward's visit order —
    that alignment is what keeps ring reads bit-exact.  ``slot`` selects
    one slot's ring (prefill); otherwise all slots gather.  Returns
    (ctx (S, R, H, D), scales (S, R) or None, k_positions (S, R))."""
    import jax.numpy as jnp

    ring = pool[i] if slot is None else \
        jnp.take(pool[i], slot, axis=0)[None]
    s, ring_tokens = ring.shape[0], ring.shape[1]
    ring_pages = ring_tokens // page_size
    pb = pb_max.reshape(-1, 1)                              # (S, 1)
    j = jnp.arange(ring_pages, dtype=pb.dtype)[None, :]     # (1, RP)
    gather_page = (pb + 1 + j) % ring_pages                 # ring page ids
    abs_page = pb - (ring_pages - 1) + j                    # their positions
    in_page = jnp.arange(page_size, dtype=pb.dtype)
    row_idx = (gather_page[:, :, None] * page_size
               + in_page[None, None, :]).reshape(s, ring_tokens)
    k_positions = (abs_page[:, :, None] * page_size
                   + in_page[None, None, :]).reshape(s, ring_tokens)
    ctx = jnp.take_along_axis(ring, row_idx[:, :, None, None], axis=1)
    scales = None
    if kv_quant:
        sc = scale_pool[i] if slot is None else \
            jnp.take(scale_pool[i], slot, axis=0)[None]
        scales = jnp.take_along_axis(sc, row_idx, axis=1)
    return ctx, scales, k_positions


def _block_mlp(params, i, x, exact):
    import jax

    hdn = _layer_norm(x, params["blk%d_ln2_gamma" % i],
                      params["blk%d_ln2_beta" % i])
    hdn = _mm(hdn, params["blk%d_ffn1_weight" % i], exact) \
        + params["blk%d_ffn1_bias" % i]
    hdn = jax.nn.gelu(hdn)
    hdn = _mm(hdn, params["blk%d_ffn2_weight" % i], exact) \
        + params["blk%d_ffn2_bias" % i]
    return x + hdn


def full_forward(params, tokens, cfg, exact=None, block=None,
                 return_kv=False, kv_quant=""):
    """Full-context forward: (n, T) int tokens -> (n, T, V) logits.

    The O(T²)-work reference every serve-path output is checked against,
    and the compute body of the bucketed prefill (``return_kv=True``
    additionally yields each layer's (k, v) head tensors for the page
    writes)."""
    import jax.numpy as jnp

    if exact is None:
        exact = exact_mode()
    params = _resolve_params(params)
    t = tokens.shape[-1]
    if t > cfg.max_len:
        raise MXNetError("sequence length %d > model max_len %d"
                         % (t, cfg.max_len))
    x = jnp.take(params["tok_embed_weight"], tokens.astype(jnp.int32),
                 axis=0)
    x = x + params["pos_embed"][:, :t]
    kvs = []
    for i, kind in enumerate(cfg.kinds):
        if kind == "ssm":
            # serial scan from a zero state: the same per-row op
            # sequence chunked prefill and recurrent decode run, so this
            # forward stays the bit-exactness oracle for hybrid stacks
            x, _ = _block_ssm(params, i, x, cfg, exact)
            kvs.append(None)
        else:
            x, kv = _block_attention(
                params, i, x, cfg, exact, block, kv_quant=kv_quant,
                window=cfg.window if kind == "window" else 0)
            kvs.append(kv)
        x = _block_mlp(params, i, x, exact)
    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = _mm(x, params["lm_head_weight"], exact) \
        + params["lm_head_bias"]
    if return_kv:
        return logits, kvs
    return logits


def prefill_forward(params, tokens, length, offset, table_row, k_pool,
                    v_pool, cfg, page_size, exact=None, k_scale=None,
                    v_scale=None, kv_quant="", kw_pool=None, vw_pool=None,
                    kw_scale=None, vw_scale=None, ssm_state=None,
                    slot=None):
    """Bucketed prefill over one suffix chunk: write the chunk's KV into
    the slot's pages and attend each row over everything at or before
    its absolute position — including KV the slot did NOT compute this
    dispatch (prefix-cache hit pages, earlier chunks of a chunked or
    resumed prefill).

    tokens: (1, Tb) chunk padded to the bucket length (a multiple of
    ``page_size``); length: () int32 real tokens in THIS chunk;
    offset: () int32 absolute position of the chunk's first token (a
    ``page_size`` multiple — chunks are page-aligned; 0 reproduces the
    classic whole-prompt prefill); table_row: (max_pages,) int32 page
    ids — entries beyond the slot's mapped pages point at the trash
    page.  Returns (first_token, last_logits, k_pool, v_pool) where
    ``last_logits`` is the logits at chunk position ``length - 1``
    (absolute position ``offset + length - 1``); the pools are
    donate-safe.

    The body is :func:`verify_step` for one slot: per-row absolute
    positions, write-then-gather page scatter, and the shared
    online-softmax kernel with per-row validity horizons
    ``offset + j + 1`` — so row ``j`` reads the cached prefix plus
    chunk rows ``<= j`` and nothing else.  The same M-invariant
    transitivity that makes verify rows bit-identical to serial decode
    makes an offset-0 dispatch of this function bit-identical to the
    old whole-prompt flash prefill, and a suffix dispatch bit-identical
    to having prefilled the whole prompt cold.  Rows whose absolute
    page index runs past the table are routed to the trash page
    *in-graph* (a clipped index would alias the slot's LAST real page
    and corrupt it — bucket padding can overhang the mapped range when
    ``offset > 0``); their positions exceed every row's horizon, so
    nothing reads them.

    Hybrid stacks: windowed layers scatter the chunk's rows into the
    slot's ring (``kw_pool``/``vw_pool``, selected by the ``slot``
    scalar) at ``abs_pos % ring_tokens`` and attend over the
    position-labeled rotated ring gather; SSM layers advance the slot's
    recurrence state (``ssm_state``) across the chunk in one
    ``lax.scan`` — chunk padding passes the state through untouched.
    The updated ring/state pools ride the return tuple after the paged
    pools (and their scales).
    """
    import jax.numpy as jnp

    if exact is None:
        exact = exact_mode()
    params = _resolve_params(params)
    _, t_b = tokens.shape
    if t_b % page_size:
        raise MXNetError("bucket length %d not a multiple of page size %d"
                         % (t_b, page_size))
    h, d = cfg.num_heads, cfg.head_dim
    max_pages = table_row.shape[0]
    trash = k_pool.shape[1] - 1  # pool row num_pages, static in-graph
    offs = jnp.arange(t_b, dtype=jnp.int32)
    abs_pos = offset + offs                               # (Tb,)
    pos = jnp.clip(abs_pos, 0, cfg.max_len - 1)
    x = jnp.take(params["tok_embed_weight"], tokens.astype(jnp.int32),
                 axis=0)
    x = x + jnp.take(params["pos_embed"][0], pos, axis=0)
    row_valid = (abs_pos + 1).reshape(1, t_b)             # keys row j sees
    idx = abs_pos // page_size
    pages = jnp.where(idx < max_pages,
                      table_row[jnp.clip(idx, 0, max_pages - 1)], trash)
    offsets = abs_pos % page_size
    fi = wi = si = 0  # per-kind pool indices (static)
    for i, kind in enumerate(cfg.kinds):
        if kind == "ssm":
            state0 = jnp.take(ssm_state[si], slot, axis=0)[None]
            rv = (offs < length).reshape(1, t_b)  # padding: state no-op
            x, state = _block_ssm(params, i, x, cfg, exact, state0=state0,
                                  row_valid=rv)
            ssm_state = ssm_state.at[si, slot].set(state[0])
            si += 1
            x = _block_mlp(params, i, x, exact)
            continue
        hdn = _layer_norm(x, params["blk%d_ln1_gamma" % i],
                          params["blk%d_ln1_beta" % i])
        qkv = _mm(hdn, params["blk%d_attn_in_weight" % i], exact) \
            + params["blk%d_attn_in_bias" % i]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if kind == "window":
            ring_tokens = kw_pool.shape[2]
            ring_rows = abs_pos % ring_tokens
            kw_pool, kw_scale = _ring_append(
                kw_pool, kw_scale, wi, slot, ring_rows,
                k.reshape(t_b, h, d), kv_quant)
            vw_pool, vw_scale = _ring_append(
                vw_pool, vw_scale, wi, slot, ring_rows,
                v.reshape(t_b, h, d), kv_quant)
            pb_max = (offset + t_b - 1) // page_size
            ctx_k, ks, kp = _ring_gather(kw_pool, kw_scale, wi,
                                         jnp.atleast_1d(pb_max),
                                         page_size, kv_quant, slot=slot)
            ctx_v, vs, _ = _ring_gather(vw_pool, vw_scale, wi,
                                        jnp.atleast_1d(pb_max),
                                        page_size, kv_quant, slot=slot)
            att = decode_attention(
                q.reshape(1, t_b, h, d).transpose(0, 2, 1, 3),
                ctx_k.transpose(0, 2, 1, 3), ctx_v.transpose(0, 2, 1, 3),
                row_valid, block=page_size, mi=exact, k_scale=ks,
                v_scale=vs, window=cfg.window, k_positions=kp)
            wi += 1
        else:
            # append the chunk's KV at its absolute rows (one vectorized
            # scatter; only trash rows can collide, nothing reads them)
            k_pool, k_scale = _kv_append(k_pool, k_scale, fi, pages,
                                         offsets, k.reshape(t_b, h, d),
                                         kv_quant)
            v_pool, v_scale = _kv_append(v_pool, v_scale, fi, pages,
                                         offsets, v.reshape(t_b, h, d),
                                         kv_quant)
            ctx_k = k_pool[fi][table_row].reshape(
                1, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ctx_v = v_pool[fi][table_row].reshape(
                1, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ks = vs = None
            if kv_quant:
                ks = k_scale[fi][table_row].reshape(
                    1, max_pages * page_size)
                vs = v_scale[fi][table_row].reshape(
                    1, max_pages * page_size)
            att = decode_attention(
                q.reshape(1, t_b, h, d).transpose(0, 2, 1, 3),
                ctx_k, ctx_v, row_valid, block=page_size, mi=exact,
                k_scale=ks, v_scale=vs)
            fi += 1
        ctx = att.transpose(0, 2, 1, 3).reshape(1, t_b, cfg.d_model)
        out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
            + params["blk%d_attn_out_bias" % i]
        x = x + out
        x = _block_mlp(params, i, x, exact)
    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = _mm(x, params["lm_head_weight"], exact) \
        + params["lm_head_bias"]
    last = jnp.take(logits[0], length - 1, axis=0)
    first_token = jnp.argmax(last, axis=-1).astype(jnp.int32)
    return (first_token, last) + _pool_pack(
        k_pool, v_pool, k_scale, v_scale, kw_pool, vw_pool, kw_scale,
        vw_scale, ssm_state, kv_quant)


def decode_step(params, tokens, lengths, tables, k_pool, v_pool, cfg,
                page_size, exact=None, k_scale=None, v_scale=None,
                kv_quant="", kw_pool=None, vw_pool=None, kw_scale=None,
                vw_scale=None, ssm_state=None):
    """One continuous-batching decode step for every slot at once.

    tokens: (S,) int32 — each slot's previous output token; lengths:
    (S,) int32 — KV rows already cached per slot (the new token's
    position); tables: (S, max_pages) int32 page tables (inactive slots:
    all-trash rows, length 0).  Appends each slot's new KV at
    ``lengths``, attends over the gathered pages with the shared
    online-softmax kernel, and returns
    (next_tokens (S,), logits (S, V), *pools).

    Per-token cost is constant in the generated length: fixed-shape
    gather/scatter over the page pool plus ``Tcap/page_size`` block
    visits — there is no tensor here whose size depends on how many
    tokens any request has generated.

    Hybrid stacks tighten that constant further: windowed layers write
    the token's KV at ``lengths % ring_tokens`` in the slot's ring and
    attend over only ``ring_tokens`` rows (the rotated position-labeled
    gather); SSM layers advance the (H, D, D) recurrence one step.
    Idle slots harmlessly re-write their own ring row 0 / state (both
    are re-initialized on alloc/prefill before anything reads them) —
    the hybrid analog of idle slots writing the trash page.
    """
    import jax.numpy as jnp

    if exact is None:
        exact = exact_mode()
    params = _resolve_params(params)
    s = tokens.shape[0]
    h, d = cfg.num_heads, cfg.head_dim
    max_pages = tables.shape[1]
    x = jnp.take(params["tok_embed_weight"], tokens.astype(jnp.int32),
                 axis=0)
    pos = jnp.clip(lengths, 0, cfg.max_len - 1)
    x = x + jnp.take(params["pos_embed"][0], pos, axis=0)
    page_slot = jnp.clip(lengths // page_size, 0, max_pages - 1)
    page = jnp.take_along_axis(tables, page_slot[:, None], axis=1)[:, 0]
    offset = lengths % page_size
    slot_ids = jnp.arange(s)
    fi = wi = si = 0
    for i, kind in enumerate(cfg.kinds):
        if kind == "ssm":
            hdn = _layer_norm(x[:, None, :],
                              params["blk%d_ln1_gamma" % i],
                              params["blk%d_ln1_beta" % i])
            qkv = _mm(hdn, params["blk%d_attn_in_weight" % i], exact) \
                + params["blk%d_attn_in_bias" % i]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            from ..ops.ssm_ops import ssm_decay, ssm_scan

            y, state = ssm_scan(q.reshape(s, 1, h, d),
                                k.reshape(s, 1, h, d),
                                v.reshape(s, 1, h, d),
                                ssm_state[si], ssm_decay(h))
            ssm_state = ssm_state.at[si].set(state)
            ctx = y.astype(x.dtype).reshape(s, cfg.d_model)
            out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
                + params["blk%d_attn_out_bias" % i]
            x = x + out
            x = _block_mlp(params, i, x, exact)
            si += 1
            continue
        hdn = _layer_norm(x, params["blk%d_ln1_gamma" % i],
                          params["blk%d_ln1_beta" % i])
        qkv = _mm(hdn, params["blk%d_attn_in_weight" % i], exact) \
            + params["blk%d_attn_in_bias" % i]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if kind == "window":
            ring_tokens = kw_pool.shape[2]
            ring_rows = lengths % ring_tokens
            kw_pool, kw_scale = _ring_append(
                kw_pool, kw_scale, wi, slot_ids, ring_rows,
                k.reshape(s, h, d), kv_quant)
            vw_pool, vw_scale = _ring_append(
                vw_pool, vw_scale, wi, slot_ids, ring_rows,
                v.reshape(s, h, d), kv_quant)
            pb_max = lengths // page_size
            ctx_k, ks, kp = _ring_gather(kw_pool, kw_scale, wi, pb_max,
                                         page_size, kv_quant)
            ctx_v, vs, _ = _ring_gather(vw_pool, vw_scale, wi, pb_max,
                                        page_size, kv_quant)
            att = decode_attention(q.reshape(s, h, 1, d),
                                   ctx_k.transpose(0, 2, 1, 3),
                                   ctx_v.transpose(0, 2, 1, 3),
                                   lengths + 1, block=page_size, mi=exact,
                                   k_scale=ks, v_scale=vs,
                                   window=cfg.window, k_positions=kp)
            wi += 1
        else:
            # append this token's KV at (page, offset); inactive slots
            # write the trash page (their table rows are all-trash)
            k_pool, k_scale = _kv_append(k_pool, k_scale, fi, page,
                                         offset, k.reshape(s, h, d),
                                         kv_quant)
            v_pool, v_scale = _kv_append(v_pool, v_scale, fi, page,
                                         offset, v.reshape(s, h, d),
                                         kv_quant)
            # gather the slot's full page set: (S, P, page, H, D) ->
            # (S, H, P*page, D)
            ctx_k = k_pool[fi][tables].reshape(
                s, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ctx_v = v_pool[fi][tables].reshape(
                s, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ks = vs = None
            if kv_quant:
                ks = k_scale[fi][tables].reshape(s, max_pages * page_size)
                vs = v_scale[fi][tables].reshape(s, max_pages * page_size)
            att = decode_attention(q.reshape(s, h, 1, d), ctx_k, ctx_v,
                                   lengths + 1, block=page_size, mi=exact,
                                   k_scale=ks, v_scale=vs)
            fi += 1
        ctx = att.transpose(0, 2, 1, 3).reshape(s, cfg.d_model)
        out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
            + params["blk%d_attn_out_bias" % i]
        x = x + out
        x = _block_mlp(params, i, x, exact)
    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = _mm(x, params["lm_head_weight"], exact) \
        + params["lm_head_bias"]
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return (next_tokens, logits) + _pool_pack(
        k_pool, v_pool, k_scale, v_scale, kw_pool, vw_pool, kw_scale,
        vw_scale, ssm_state, kv_quant)


def verify_step(params, tokens, lengths, tables, k_pool, v_pool, cfg,
                page_size, exact=None, k_scale=None, v_scale=None,
                kv_quant="", kw_pool=None, vw_pool=None, kw_scale=None,
                vw_scale=None, ssm_state=None, limits=None):
    """Speculative-decoding verify: advance every slot ``W = K + 1``
    teacher-forced positions in ONE fixed-shape step.

    tokens: (S, W) int32 — per slot, the last committed token followed
    by the draft's K proposals; lengths: (S,) int32 — committed KV rows
    per slot (position of tokens[:, 0]); tables: (S, max_pages) int32.
    Writes all W rows' KV at positions ``lengths .. lengths + W - 1``
    and attends row ``j`` over exactly ``lengths + j + 1`` keys (the
    causal horizon expressed as a per-row validity length), then
    returns (greedy (S, W), logits (S, W, V), *pools).

    Bit-exactness contract: with ``exact=True`` every op here is the
    M-invariant form of the matching :func:`decode_step` op, and the
    attention merge visits the same page blocks with the same masks —
    so row ``j`` of one verify step is bit-identical to the ``j``-th of
    W serial ``decode_step`` calls fed the same tokens.  That is what
    makes greedy acceptance exact: comparing the draft's proposal to
    ``greedy[:, j]`` is comparing against precisely what non-speculative
    decode would have emitted.

    Rows whose write position runs past the slot's page reservation
    land on the trash page (the session widens the table by
    ``spec_pad_pages`` all-trash columns so the page clip below can
    never alias a real page); such rows are never committed, so their
    garbage logits are dead by construction.

    Hybrid-stack rollback is O(1) by construction.  Windowed layers
    write all W rows into the ring at their deterministic slots
    ``abs_pos % ring_tokens``; rejected rows need no undo — after the
    host rolls ``lengths`` back, their ring rows label as positions
    outside every future mask until the committed stream rewrites them.
    SSM layers scan with ``collect=True`` and, because a recurrence has
    no per-row storage to mask, the acceptance count is recomputed
    IN-GRAPH (``limits``: (S,) int32 per-slot commit cap — the same
    integer comparison the host's commit loop runs) to select each
    slot's state snapshot at its commit point; only that snapshot is
    written back, so a rejected suffix never touches committed state.
    """
    import jax.numpy as jnp

    if exact is None:
        exact = exact_mode()
    params = _resolve_params(params)
    s, w = tokens.shape
    h, d = cfg.num_heads, cfg.head_dim
    max_pages = tables.shape[1]
    x = jnp.take(params["tok_embed_weight"], tokens.astype(jnp.int32),
                 axis=0)
    offs = jnp.arange(w, dtype=lengths.dtype)
    abs_pos = lengths[:, None] + offs[None, :]            # (S, W)
    pos = jnp.clip(abs_pos, 0, cfg.max_len - 1)
    x = x + jnp.take(params["pos_embed"][0], pos, axis=0)
    row_valid = abs_pos + 1                               # keys row j sees
    page_slot = jnp.clip(abs_pos // page_size, 0, max_pages - 1)
    pages = jnp.take_along_axis(tables, page_slot, axis=1)  # (S, W)
    offsets = abs_pos % page_size
    slot_ids = jnp.arange(s)
    ssm_snaps = []          # (pool index, (W, S, H, D, D) snapshots)
    fi = wi = si = 0
    for i, kind in enumerate(cfg.kinds):
        hdn = _layer_norm(x, params["blk%d_ln1_gamma" % i],
                          params["blk%d_ln1_beta" % i])
        qkv = _mm(hdn, params["blk%d_attn_in_weight" % i], exact) \
            + params["blk%d_attn_in_bias" % i]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        k = k.reshape(s, w, h, d)
        v = v.reshape(s, w, h, d)
        if kind == "ssm":
            from ..ops.ssm_ops import ssm_decay, ssm_scan

            y, _, snaps = ssm_scan(q.reshape(s, w, h, d), k, v,
                                   ssm_state[si], ssm_decay(h),
                                   collect=True)
            ssm_snaps.append((si, snaps))
            si += 1
            ctx = y.astype(x.dtype).reshape(s, w, cfg.d_model)
            out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
                + params["blk%d_attn_out_bias" % i]
            x = x + out
            x = _block_mlp(params, i, x, exact)
            continue
        # append all W rows' KV, then attend with per-row horizons: row
        # j only ever reads rows <= j of this very step plus committed
        # context, so write-then-attend reproduces the serial interleave
        if kind == "window":
            ring_tokens = kw_pool.shape[2]
            for j in range(w):
                rr = abs_pos[:, j] % ring_tokens
                kw_pool, kw_scale = _ring_append(
                    kw_pool, kw_scale, wi, slot_ids, rr, k[:, j], kv_quant)
                vw_pool, vw_scale = _ring_append(
                    vw_pool, vw_scale, wi, slot_ids, rr, v[:, j], kv_quant)
            pb_max = (lengths + w - 1) // page_size
            ctx_k, ks, kp = _ring_gather(kw_pool, kw_scale, wi, pb_max,
                                         page_size, kv_quant)
            ctx_v, vs, _ = _ring_gather(vw_pool, vw_scale, wi, pb_max,
                                        page_size, kv_quant)
            ctx_k = ctx_k.transpose(0, 2, 1, 3)
            ctx_v = ctx_v.transpose(0, 2, 1, 3)
            win = cfg.window
            wi += 1
        else:
            for j in range(w):
                k_pool, k_scale = _kv_append(k_pool, k_scale, fi,
                                             pages[:, j], offsets[:, j],
                                             k[:, j], kv_quant)
                v_pool, v_scale = _kv_append(v_pool, v_scale, fi,
                                             pages[:, j], offsets[:, j],
                                             v[:, j], kv_quant)
            ctx_k = k_pool[fi][tables].reshape(
                s, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ctx_v = v_pool[fi][tables].reshape(
                s, max_pages * page_size, h, d).transpose(0, 2, 1, 3)
            ks = vs = kp = None
            if kv_quant:
                ks = k_scale[fi][tables].reshape(s, max_pages * page_size)
                vs = v_scale[fi][tables].reshape(s, max_pages * page_size)
            win = 0
            fi += 1
        att = decode_attention(q.reshape(s, w, h, d).transpose(0, 2, 1, 3),
                               ctx_k, ctx_v, row_valid, block=page_size,
                               mi=exact, k_scale=ks, v_scale=vs,
                               window=win, k_positions=kp)
        ctx = att.transpose(0, 2, 1, 3).reshape(s, w, cfg.d_model)
        out = _mm(ctx, params["blk%d_attn_out_weight" % i], exact) \
            + params["blk%d_attn_out_bias" % i]
        x = x + out
        x = _block_mlp(params, i, x, exact)
    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = _mm(x, params["lm_head_weight"], exact) \
        + params["lm_head_bias"]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if ssm_snaps:
        # in-graph acceptance: leading run of draft tokens that match
        # greedy — integer-exact, so it reproduces the host commit loop
        agree = (tokens[:, 1:].astype(jnp.int32) == greedy[:, :-1])
        run = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1),
                      axis=1)
        c = 1 + run
        if limits is not None:
            c = jnp.minimum(c, limits.astype(jnp.int32))
        idx = jnp.clip(c - 1, 0, w - 1)
        for si, snaps in ssm_snaps:
            # (W, S, H, D, D) -> (S, W, H, D, D), pick each slot's
            # commit-point snapshot
            per_slot = jnp.moveaxis(snaps, 0, 1)
            sel = jnp.take_along_axis(
                per_slot, idx[:, None, None, None, None], axis=1)[:, 0]
            ssm_state = ssm_state.at[si].set(sel)
    return (greedy, logits) + _pool_pack(
        k_pool, v_pool, k_scale, v_scale, kw_pool, vw_pool, kw_scale,
        vw_scale, ssm_state, kv_quant)


def draft_propose(params, tokens, n_feed, lengths, tables, k_pool, v_pool,
                  cfg, page_size, exact=None, k_scale=None, v_scale=None,
                  kv_quant="", kw_pool=None, vw_pool=None, kw_scale=None,
                  vw_scale=None):
    """Draft-model K+1-step scan: one dispatch that both *ingests*
    committed tokens and *proposes* speculative continuations.

    tokens: (S, W) int32 teacher tokens; n_feed: (S,) int32 — step ``j``
    feeds ``tokens[s, j]`` while ``j < n_feed[s]`` and the draft's own
    greedy output from step ``j - 1`` after that.  ``n_feed = 1`` is
    propose mode (feed the last committed token, then autoregress);
    ``n_feed = W`` is pure teacher forcing (prompt ingestion in W-token
    chunks).  Every step appends its token's KV at ``lengths + j``, so
    the draft cache tracks exactly the positions the target cache holds.
    Returns (outs (S, W), *pools) where ``outs[:, j]`` is the greedy
    token after feeding position ``lengths + j`` — propose mode uses
    ``outs[:, :W-1]`` as its K proposals.

    Draft stacks may mix full and windowed layers (the ring append /
    rotated gather is scan-compatible and rollback is lengths-only) but
    never SSM layers — see the guard below.
    """
    import jax.numpy as jnp
    from jax import lax

    if exact is None:
        exact = exact_mode()
    if "ssm" in cfg.kinds:
        # an SSM draft would need its own state pool threaded through the
        # scan AND verify-synchronized rollback; nothing needs it, so the
        # session rejects the configuration up front
        raise MXNetError("draft_propose: SSM layers are not supported in "
                         "draft models")
    # resolve once, outside the scan body, so the dequantized weights
    # are loop invariants XLA hoists rather than per-step work
    params = _resolve_params(params)
    pools0 = _pool_pack(k_pool, v_pool, k_scale, v_scale, kw_pool,
                        vw_pool, kw_scale, vw_scale, None, kv_quant)
    names = _pool_names(kv_quant, kw_pool is not None, False)

    def body(carry, xs):
        prev, pools = carry
        teach, j = xs
        tok = jnp.where(j < n_feed, teach, prev)
        out = decode_step(params, tok, lengths + j, tables,
                          cfg=cfg, page_size=page_size, exact=exact,
                          kv_quant=kv_quant,
                          **dict(zip(names, pools)))
        return (out[0], out[2:]), out[0]

    w = tokens.shape[1]
    xs = (tokens.T, jnp.arange(w, dtype=lengths.dtype))
    carry0 = (tokens[:, 0].astype(jnp.int32), pools0)
    (_, pools), outs = lax.scan(body, carry0, xs)
    return (outs.T,) + pools


@functools.lru_cache(maxsize=None)
def _reference_fn(cfg, page_size, exact, kv_quant=""):
    import jax

    def fwd(params, tokens):
        return full_forward(params, tokens, cfg, exact=exact,
                            block=page_size, kv_quant=kv_quant)

    return jax.jit(fwd)


def reference_last_logits(params, seq, cfg, page_size, exact=None,
                          kv_quant=""):
    """Bit-exactness oracle for the serving path: full-context forward
    over ``seq`` padded to the next ``page_size`` multiple (the same
    attention-block geometry the prefill/decode executables run), logits
    at the last *real* position.  Jitted and cached per padded shape —
    eager dispatch fuses differently and is NOT bit-comparable.

    ``kv_quant`` pins the oracle to a KV precision: the reference
    fake-quantizes each token's K/V row with the same helper the paged
    path scatters with, so it certifies the quantized serving path
    bit-exactly *at that precision* (PR 13's per-precision pattern)."""
    import jax.numpy as jnp

    from ..quantize import quant_mode

    exact = exact_mode() if exact is None else bool(exact)
    seq = [int(t) for t in seq]
    if not seq:
        raise MXNetError("reference_last_logits: empty sequence")
    pad = (-len(seq)) % int(page_size)
    toks = jnp.asarray([seq + [0] * pad], jnp.int32)
    logits = _reference_fn(cfg, int(page_size), exact,
                           quant_mode(kv_quant))(params, toks)
    return logits[0, len(seq) - 1]
