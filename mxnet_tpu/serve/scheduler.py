"""Request queue + batching policies over an :class:`InferenceSession`.

Three policies, all running the *same* compiled executables so the
bench comparison isolates scheduling:

* ``serial`` — one request at a time, admitted only when the previous
  one finished.  The baseline every serving system is measured against.
* ``static`` — classic static batching: admit up to ``slots`` requests
  only when the batch is empty, run them to completion together.  Head
  of-line blocking both ways (late arrivals wait for the batch to
  drain; the batch waits for its slowest member).
* ``continuous`` — in-flight batching: at *every* decode-step boundary,
  finished requests are evicted and newly-arrived ones are prefilled
  into freed slots, so the decode executable runs as full as the
  arrival process allows.

Requests replay an open-loop arrival trace (``arrival_s`` offsets from
run start) — the scheduler never back-pressures arrivals, so queueing
delay shows up in TTFT exactly as a production load balancer would see
it.

With speculative decoding on (``session.config.spec_k > 0``) the
per-step boundary calls :meth:`InferenceSession.spec_step` instead of
:meth:`~InferenceSession.step` and a slot commits 1..K+1 tokens per
boundary — the variable-advance accounting below consumes the committed
tokens one at a time so EOS / ``max_new`` cut at exactly the token a
non-speculative run would have stopped at (greedy acceptance is exact,
so the streams are bit-identical).

Fault sites (``testing/faults.py``): every admit / decode-step /
response boundary crosses ``serve_queue`` plus a phase-specific site
(``serve_admit`` / ``serve_decode`` — or ``serve_verify`` when
speculation is on — / ``serve_respond``).  A fault fails *that request
only*: its slot is released and surviving slots keep decoding — the
chaos tests assert exactly this isolation.
"""
from __future__ import annotations

import dataclasses
import time

from ..base import MXNetError
from ..testing import faults

__all__ = ["Request", "Scheduler", "summarize"]

_POLICIES = ("serial", "static", "continuous")


@dataclasses.dataclass
class Request:
    """One generation request plus its measured lifecycle."""

    rid: int
    prompt: list
    max_new: int
    arrival_s: float = 0.0
    eos_id: int = -1  # -1: never stops early
    # -- filled in by the scheduler --
    tokens: list = dataclasses.field(default_factory=list)
    ttft_s: float = -1.0
    done_s: float = -1.0
    failed: bool = False
    error: str = ""

    @property
    def finished(self):
        return self.failed or self.done_s >= 0.0


class Scheduler(object):
    """Drives a session through an arrival trace under one policy."""

    def __init__(self, session, policy="continuous"):
        if policy not in _POLICIES:
            raise MXNetError("unknown policy %r (one of %s)"
                             % (policy, ", ".join(_POLICIES)))
        self.session = session
        self.policy = policy

    # -- fault boundaries -------------------------------------------------
    def _boundary(self, req, slot, site):
        """Cross a fault boundary for one request; a fault fails the
        request (releasing its slot if held) and the run continues."""
        try:
            faults.inject("serve_queue")
            faults.inject(site)
            return True
        except faults.WorkerKilled as exc:
            self._fail(req, slot, exc)
            return False
        except Exception as exc:  # FaultInjected / MXNetError
            self._fail(req, slot, exc)
            return False

    def _fail(self, req, slot, exc):
        req.failed = True
        req.error = "%s: %s" % (type(exc).__name__, exc)
        if slot is not None:
            try:
                self.session.release(slot)
            except MXNetError:
                pass

    # -- the run loop -----------------------------------------------------
    def run(self, requests):
        """Replay ``requests`` (sorted by ``arrival_s``) to completion;
        returns ``(requests, makespan_s)``."""
        sess = self.session
        queue = sorted(requests, key=lambda r: (r.arrival_s, r.rid))
        pending = list(queue)
        active = {}  # slot -> Request
        t0 = time.perf_counter()

        def now():
            return time.perf_counter() - t0

        while pending or active:
            # 1) admit whatever the policy allows right now
            arrived = [r for r in pending if r.arrival_s <= now()]
            if self.policy == "serial":
                admit_cap = 1 if not active else 0
            elif self.policy == "static":
                admit_cap = sess.config.slots if not active else 0
            else:
                admit_cap = sess.config.slots - len(active)
            for req in arrived[:max(admit_cap, 0)]:
                if not self._boundary(req, None, "serve_admit"):
                    pending.remove(req)
                    continue
                slot = sess.try_alloc(len(req.prompt), req.max_new)
                if slot is None:
                    break  # pool full: stays queued for a later boundary
                pending.remove(req)
                first, _ = sess.prefill(slot, req.prompt)
                req.ttft_s = now() - req.arrival_s
                req.tokens.append(first)
                active[slot] = req
                if len(req.tokens) >= req.max_new or first == req.eos_id:
                    self._finish(req, slot, active, now)

            if not active:
                if pending:
                    # idle until the next arrival (open-loop replay)
                    wait = min(r.arrival_s for r in pending) - now()
                    if wait > 0:
                        time.sleep(min(wait, 0.05))
                continue

            # 2) per-request step boundaries (deterministic slot order)
            spec = getattr(sess.config, "spec_k", 0) > 0
            site = "serve_verify" if spec else "serve_decode"
            for slot in sorted(active):
                req = active[slot]
                if not self._boundary(req, slot, site):
                    del active[slot]

            if not active:
                continue

            # 3) one fixed-shape step advances every survivor — by one
            # token (decode) or by 1..K+1 committed tokens (verify)
            if spec:
                limits = {slot: active[slot].max_new
                          - len(active[slot].tokens) for slot in active}
                committed = sess.spec_step(limits=limits)
                for slot in sorted(active):
                    req = active[slot]
                    for tok in committed[slot]:
                        req.tokens.append(tok)
                        if (len(req.tokens) >= req.max_new
                                or tok == req.eos_id):
                            # EOS inside the speculated window: the
                            # committed tail past it is dropped, exactly
                            # where non-speculative decode would stop
                            self._finish(req, slot, active, now)
                            break
            else:
                step_tokens, _ = sess.step()
                for slot in sorted(active):
                    req = active[slot]
                    req.tokens.append(step_tokens[slot])
                    if (len(req.tokens) >= req.max_new
                            or step_tokens[slot] == req.eos_id):
                        self._finish(req, slot, active, now)

        return queue, now()

    def _finish(self, req, slot, active, now):
        active.pop(slot, None)
        if self._boundary(req, slot, "serve_respond"):
            req.done_s = now()
            self.session.release(slot)


def _percentile(values, pct):
    vals = sorted(values)
    if not vals:
        return 0.0
    idx = min(int(round((pct / 100.0) * (len(vals) - 1))), len(vals) - 1)
    return float(vals[idx])


def summarize(requests, makespan_s):
    """Latency/throughput rollup the bench emits per policy."""
    done = [r for r in requests if r.done_s >= 0.0 and not r.failed]
    failed = [r for r in requests if r.failed]
    ttfts = [r.ttft_s for r in done if r.ttft_s >= 0.0]
    per_token = []
    total_tokens = 0
    for r in done:
        total_tokens += len(r.tokens)
        if len(r.tokens) > 1 and r.ttft_s >= 0.0:
            decode_span = (r.done_s - r.arrival_s) - r.ttft_s
            per_token.append(decode_span / (len(r.tokens) - 1))
    return {
        "completed": len(done),
        "failed": len(failed),
        "total_tokens": total_tokens,
        "makespan_s": float(makespan_s),
        "tokens_per_sec": (total_tokens / makespan_s) if makespan_s > 0
        else 0.0,
        "ttft_p50_s": _percentile(ttfts, 50),
        "ttft_p99_s": _percentile(ttfts, 99),
        "per_token_p50_s": _percentile(per_token, 50),
        "per_token_p99_s": _percentile(per_token, 99),
    }
