"""Request queue + batching policies over an :class:`InferenceSession`.

Three policies, all running the *same* compiled executables so the
bench comparison isolates scheduling:

* ``serial`` — one request at a time, admitted only when the previous
  one finished.  The baseline every serving system is measured against.
* ``static`` — classic static batching: admit up to ``slots`` requests
  only when the batch is empty, run them to completion together.  Head
  of-line blocking both ways (late arrivals wait for the batch to
  drain; the batch waits for its slowest member).
* ``continuous`` — in-flight batching: at *every* decode-step boundary,
  finished requests are evicted and newly-arrived ones are prefilled
  into freed slots, so the decode executable runs as full as the
  arrival process allows.

Requests replay an open-loop arrival trace (``arrival_s`` offsets from
run start) — the scheduler never back-pressures arrivals, so queueing
delay shows up in TTFT exactly as a production load balancer would see
it.

With speculative decoding on (``session.config.spec_k > 0``) the
per-step boundary calls :meth:`InferenceSession.spec_step` instead of
:meth:`~InferenceSession.step` and a slot commits 1..K+1 tokens per
boundary — the variable-advance accounting below consumes the committed
tokens one at a time so EOS / ``max_new`` cut at exactly the token a
non-speculative run would have stopped at (greedy acceptance is exact,
so the streams are bit-identical).

Preemption and resume (oversubscribed sessions,
``session.config.oversub``): before every step the scheduler probes the
session's page shortfall for the coming boundary; when shortfall plus
the configured watermark exceeds the pool's reclaimable pages it
preempts the *coldest* active request — least queue seniority, i.e.
latest arrival (ties: highest rid) — releasing its pages (refcount-
aware, so shared prefix pages survive for their other holders) and
parking it.  Parked requests resume with top priority: their transcript
(prompt + committed tokens) re-prefills through the chunked offset
prefill, and because prefill is deterministic and decode M-invariant
exact, the recomputed stream is bit-identical to a never-evicted one —
the resume asserts it by checking the replayed token against the last
committed one.  Oversubscription changes capacity, never content.

Hybrid stacks (windowed/SSM layers) ride the same resume path with no
extra bookkeeping: eviction releases only pages (a slot's window rings
and SSM state stay physically allocated but become garbage), and the
re-prefill deterministically reconstructs both — ring rows are a pure
function of the replayed tokens and their positions, and the SSM
recurrence replays from its zero alloc state through the identical
chunked scan — so the bit-exact divergence assert above pins ring and
state reconstruction exactly as it pins page contents.

SLO-aware admission (``session.config.ttft_slo_ms`` > 0): arrivals are
admitted can-still-meet-the-TTFT-budget first (FIFO within each class),
so a burst spends its slots on requests that still count toward
goodput; :func:`summarize` reports ``goodput_rps`` and
``slo_attainment`` when given the budget.

Closed-loop driving: ``run(requests, followup=...)`` calls ``followup(
finished_request, now_s)`` at every completion; returned requests join
the arrival queue — that is how the bench holds concurrency constant
instead of replaying a fixed open-loop trace.

Tick form (the replica supervisor's hook, ``serve/supervisor.py``):
``run()`` is ``begin(requests)`` followed by ``tick()`` until no work
remains — one ``tick()`` is exactly one decode-boundary iteration
(resume parked, admit arrivals, step every active slot once).  A
:class:`~mxnet_tpu.serve.supervisor.ReplicaSet` drives N schedulers
tick-by-tick from one thread, all sharing the supervisor's ``t0`` so
arrival offsets stay comparable, and on replica death calls
:meth:`drain` to pull the unfinished requests out for re-admission on
a survivor — requests with committed tokens re-enter a survivor's
parked list and replay through the same resume path preemption uses.

Fault sites (``testing/faults.py``): every admit / decode-step /
response boundary crosses ``serve_queue`` plus a phase-specific site
(``serve_admit`` / ``serve_decode`` — or ``serve_verify`` when
speculation is on — / ``serve_respond``), and the preemption machinery
adds ``serve_evict`` (before a victim's pages are released) and
``serve_resume`` (before a parked request re-prefills).  A fault fails
*that request only*: its slot is released and surviving slots keep
decoding — the chaos tests assert exactly this isolation, including
that a faulted eviction/resume leaves shared prefix pages and the
survivors' streams intact.
"""
from __future__ import annotations

import dataclasses
import time

from ..base import MXNetError
from ..testing import faults

__all__ = ["Request", "Scheduler", "ServeCancelled", "summarize"]

_POLICIES = ("serial", "static", "continuous")

_FRESH_STATS = {"preemptions": 0, "resumes": 0, "peak_active": 0,
                "faulted": 0, "cancelled": 0}


class ServeCancelled(MXNetError):
    """A request cancelled before completion — client disconnect,
    per-request deadline, or a gateway drain force-cancel.  Typed so
    accounting can tell deliberate cancellation apart from faults and
    load sheds: a cancelled request is neither lost nor shed."""

    def __init__(self, msg, rid=None, reason=""):
        super().__init__(msg)
        self.rid = rid
        self.reason = reason


def mark_cancelled(req, reason):
    """Stamp one request as typed-cancelled (shared by
    :meth:`Scheduler.cancel`, the replica dispatcher, and the gateway's
    drain force-cancel, so the error string is uniform)."""
    exc = ServeCancelled("request %d cancelled: %s" % (req.rid, reason),
                         rid=req.rid, reason=reason)
    req.failed = True
    req.cancelled = True
    req.error = "%s: %s" % (type(exc).__name__, exc)


@dataclasses.dataclass
class Request:
    """One generation request plus its measured lifecycle."""

    rid: int
    prompt: list
    max_new: int
    arrival_s: float = 0.0
    eos_id: int = -1  # -1: never stops early
    # -- filled in by the scheduler --
    tokens: list = dataclasses.field(default_factory=list)
    ttft_s: float = -1.0
    done_s: float = -1.0
    failed: bool = False
    error: str = ""
    preemptions: int = 0  # times this request was evicted and parked
    resumes: int = 0      # times its transcript re-prefilled (park or
    #                       failover — both cross the same resume path)
    shed: bool = False    # refused by overload protection (typed error)
    shed_kind: str = ""   # "queue" | "deadline" when shed is set
    cancelled: bool = False  # typed-cancelled (disconnect / deadline /
    #                          drain) — deliberate, not a fault

    @property
    def finished(self):
        return self.failed or self.done_s >= 0.0


class Scheduler(object):
    """Drives a session through an arrival trace under one policy."""

    def __init__(self, session, policy="continuous"):
        if policy not in _POLICIES:
            raise MXNetError("unknown policy %r (one of %s)"
                             % (policy, ", ".join(_POLICIES)))
        self.session = session
        self.policy = policy
        self.stats = dict(_FRESH_STATS)
        self._followup = None
        self._pending = []
        self._queue = []
        self._parked = []
        self._active = {}
        self._t0 = None

    # -- fault boundaries -------------------------------------------------
    def _boundary(self, req, slot, site):
        """Cross a fault boundary for one request; a fault fails the
        request (releasing its slot if held) and the run continues."""
        try:
            faults.inject("serve_queue")
            faults.inject(site)
            return True
        except faults.WorkerKilled as exc:
            self._fail(req, slot, exc)
            return False
        except Exception as exc:  # FaultInjected / MXNetError
            self._fail(req, slot, exc)
            return False

    def _prefill(self, req, slot, seq):
        """Run one request's prefill with the same isolation as a
        boundary crossing: a session-raised fault (e.g. the ``kv_quant``
        chaos site, which fires before any of the request's quantized
        pages/scales are written) fails THAT request, releases its slot,
        and the run continues.  Returns the first token, or None when
        the request failed."""
        try:
            first, _ = self.session.prefill(slot, seq)
            return first
        except faults.WorkerKilled as exc:
            self._fail(req, slot, exc)
            return None
        except MXNetError as exc:
            self._fail(req, slot, exc)
            return None

    def _fail(self, req, slot, exc):
        req.failed = True
        req.error = "%s: %s" % (type(exc).__name__, exc)
        self.stats["faulted"] += 1
        if slot is not None:
            try:
                self.session.release(slot)
            except MXNetError:
                pass

    # -- tick-form state machine ------------------------------------------
    def begin(self, requests, followup=None, t0=None):
        """Arm the scheduler for a run without stepping it: sort the
        trace, reset the stats, record the clock origin.  ``t0`` (a
        ``time.perf_counter()`` value) lets a supervisor share one clock
        across many schedulers so ``arrival_s`` offsets line up."""
        self._queue = sorted(requests, key=lambda r: (r.arrival_s, r.rid))
        self._pending = list(self._queue)
        self._parked = []  # preempted requests, in eviction order
        self._active = {}  # slot -> Request
        self.stats = dict(_FRESH_STATS)
        self._followup = followup
        self._t0 = time.perf_counter() if t0 is None else t0
        return self

    def now(self):
        return time.perf_counter() - self._t0

    @property
    def outstanding(self):
        """True while unfinished requests remain anywhere (pending,
        parked, or active)."""
        return bool(self._pending or self._parked or self._active)

    @property
    def load(self):
        """Requests this scheduler currently owns (pending + parked +
        active) — the supervisor's least-loaded dispatch key."""
        return len(self._pending) + len(self._parked) + len(self._active)

    def submit(self, request, parked=False):
        """Enqueue one request mid-run.  ``parked=True`` re-admits a
        request that already holds committed tokens (replica failover)
        through the resume path: its transcript re-prefills and the
        replayed token is asserted against the last committed one."""
        self._queue.append(request)
        if parked:
            self._parked.append(request)
        else:
            self._pending.append(request)

    def drain(self):
        """Pull every unfinished request out (replica death): returns
        ``(resumable, fresh)`` — requests with committed tokens, and
        requests not yet prefilled.  Active slots are released
        best-effort (in-process the host-side bookkeeping is still
        reachable; a real dead replica's memory is gone with it)."""
        resumable, fresh = [], []
        for slot in sorted(self._active):
            req = self._active[slot]
            try:
                self.session.release(slot)
            except MXNetError:
                pass
            (resumable if req.tokens else fresh).append(req)
        resumable.extend(self._parked)
        fresh.extend(self._pending)
        self._active = {}
        self._parked = []
        self._pending = []
        return resumable, fresh

    def cancel(self, rid, reason="cancelled by client"):
        """Cancel one request at the current decode boundary: drop it
        from wherever it lives (pending / parked / active) and mark it
        with a typed :class:`ServeCancelled`.  An active request's slot
        is released refcount-aware — shared prefix pages survive for
        their other holders, and a speculative session's mirrored draft
        cache releases in lockstep — so pool occupancy returns to its
        pre-request baseline.  Cancelling an unknown or already-finished
        request is a no-op (a response that already completed stays
        completed); returns True when something was actually cancelled.

        Call between ticks: the tick loop owns the session, so the
        caller (the gateway's dispatch thread, or any single-threaded
        driver) must not race a tick in flight."""
        for bucket in (self._pending, self._parked):
            for req in bucket:
                if req.rid == rid and not req.finished:
                    bucket.remove(req)
                    mark_cancelled(req, reason)
                    self.stats["cancelled"] += 1
                    return True
        for slot in sorted(self._active):
            req = self._active[slot]
            if req.rid != rid:
                continue
            if req.finished:  # finish already accounted the slot
                return False
            del self._active[slot]
            try:
                self.session.release(slot)  # refcount-aware
            except MXNetError:
                pass
            mark_cancelled(req, reason)
            self.stats["cancelled"] += 1
            return True
        return False

    # -- the run loop -----------------------------------------------------
    def run(self, requests, followup=None):
        """Replay ``requests`` (sorted by ``arrival_s``) to completion;
        returns ``(requests, makespan_s)``.  ``followup(request,
        now_s)``, when given, is called as each request finishes and may
        return a new :class:`Request` (or list of them) to enqueue —
        the closed-loop driving hook; generated requests are included
        in the returned list."""
        self.begin(requests, followup=followup)
        while self.tick():
            pass
        return self._queue, self.now()

    def tick(self, wait=True):
        """One decode-boundary iteration: resume parked requests, admit
        arrivals, cross every fault boundary, preempt on the watermark,
        and run one fixed-shape step.  Returns :attr:`outstanding`.
        ``wait=False`` skips the idle open-loop sleep (a supervisor
        interleaving many schedulers owns the clock)."""
        sess = self.session
        pending, parked, active = self._pending, self._parked, self._active
        now = self.now
        if not self.outstanding:
            return False

        slo_s = float(getattr(sess.config, "ttft_slo_ms", 0.0)) / 1000.0
        oversub = bool(getattr(sess.config, "oversub", False))

        # 0) resume parked requests first — they hold queue
        # seniority over fresh arrivals, and their transcript pages
        # often still sit in the prefix cache
        for req in list(parked):
            if not self._boundary(req, None, "serve_resume"):
                parked.remove(req)
                continue
            seq = list(req.prompt) + req.tokens[:-1]
            budget = req.max_new - len(req.tokens) + 1
            slot = sess.try_alloc(len(seq), budget, tokens=seq,
                                  resume=True)
            if slot is None:
                if not active and not pending:
                    raise MXNetError(
                        "parked request %d cannot resume into an "
                        "idle session — pool smaller than one "
                        "request's worst case" % req.rid)
                break
            parked.remove(req)
            first = self._prefill(req, slot, seq)
            if first is None:
                continue
            if first != req.tokens[-1]:
                raise MXNetError(
                    "resume replay diverged for request %d: "
                    "re-prefill produced token %d, committed stream "
                    "holds %d — determinism bug"
                    % (req.rid, first, req.tokens[-1]))
            active[slot] = req
            req.resumes += 1
            self.stats["resumes"] += 1

        # 1) admit whatever the policy allows right now
        arrived = [r for r in pending if r.arrival_s <= now()]
        if slo_s > 0:
            # requests that can still meet the TTFT budget first
            # (FIFO within each class): a burst spends its slots on
            # goodput, not on arrivals that already blew the budget
            t = now()
            arrived.sort(key=lambda r: ((t - r.arrival_s) > slo_s,
                                        r.arrival_s, r.rid))
        if self.policy == "serial":
            admit_cap = 1 if not active else 0
        elif self.policy == "static":
            admit_cap = sess.config.slots if not active else 0
        else:
            admit_cap = sess.config.slots - len(active)
        for req in arrived[:max(admit_cap, 0)]:
            if not self._boundary(req, None, "serve_admit"):
                pending.remove(req)
                continue
            slot = sess.try_alloc(len(req.prompt), req.max_new,
                                  tokens=req.prompt)
            if slot is None:
                break  # pool full: stays queued for a later boundary
            pending.remove(req)
            first = self._prefill(req, slot, req.prompt)
            if first is None:
                continue
            req.ttft_s = now() - req.arrival_s
            req.tokens.append(first)
            active[slot] = req
            if len(req.tokens) >= req.max_new or first == req.eos_id:
                self._finish(req, slot, active, now)
        self.stats["peak_active"] = max(self.stats["peak_active"],
                                        len(active))

        if not active:
            if wait and pending and not parked:
                # idle until the next arrival (open-loop replay)
                idle = min(r.arrival_s for r in pending) - now()
                if idle > 0:
                    time.sleep(min(idle, 0.05))
            return self.outstanding

        # 2) per-request step boundaries (deterministic slot order)
        spec = getattr(sess.config, "spec_k", 0) > 0
        site = "serve_verify" if spec else "serve_decode"
        for slot in sorted(active):
            req = active[slot]
            if not self._boundary(req, slot, site):
                del active[slot]

        if not active:
            return self.outstanding

        # 2b) watermark preemption: if the coming step's page
        # growth would drain the pool below the watermark, evict
        # the coldest request(s) — latest arrival, ties highest rid
        # — park them, and let the survivors step.  The last active
        # request is never evicted (it can always finish: one
        # request's worst case fits the pool by construction).
        if oversub:
            rows = sess.config.spec_window if spec else 1
            wm = max(int(getattr(sess.config, "watermark", 0)), 0)
            while (len(active) > 1
                   and sess.pages_short(rows) + wm
                   > sess.cache.reclaimable_pages):
                victim_slot = max(
                    active, key=lambda s: (active[s].arrival_s,
                                           active[s].rid))
                victim = active.pop(victim_slot)
                if not self._boundary(victim, victim_slot,
                                      "serve_evict"):
                    continue  # fault: failed + slot released
                sess.release(victim_slot)  # shared pages survive
                victim.preemptions += 1
                parked.append(victim)
                self.stats["preemptions"] += 1

        if not active:
            return self.outstanding

        # 3) one fixed-shape step advances every survivor — by one
        # token (decode) or by 1..K+1 committed tokens (verify)
        if spec:
            limits = {slot: active[slot].max_new
                      - len(active[slot].tokens) for slot in active}
            committed = sess.spec_step(limits=limits)
            for slot in sorted(active):
                req = active[slot]
                for tok in committed[slot]:
                    req.tokens.append(tok)
                    if (len(req.tokens) >= req.max_new
                            or tok == req.eos_id):
                        # EOS inside the speculated window: the
                        # committed tail past it is dropped, exactly
                        # where non-speculative decode would stop
                        self._finish(req, slot, active, now)
                        break
        else:
            step_tokens, _ = sess.step()
            for slot in sorted(active):
                req = active[slot]
                req.tokens.append(step_tokens[slot])
                if (len(req.tokens) >= req.max_new
                        or step_tokens[slot] == req.eos_id):
                    self._finish(req, slot, active, now)

        return self.outstanding

    def _finish(self, req, slot, active, now):
        active.pop(slot, None)
        if self._boundary(req, slot, "serve_respond"):
            req.done_s = now()
            self.session.release(slot)
        if self._followup is not None:
            nxt = self._followup(req, now())
            if nxt is not None:
                for r in (nxt if isinstance(nxt, (list, tuple)) else [nxt]):
                    self._pending.append(r)
                    self._queue.append(r)


def _percentile(values, pct):
    vals = sorted(values)
    if not vals:
        return 0.0
    idx = min(int(round((pct / 100.0) * (len(vals) - 1))), len(vals) - 1)
    return float(vals[idx])


def summarize(requests, makespan_s, ttft_slo_ms=0.0):
    """Latency/throughput rollup the bench emits per policy.  With a
    TTFT budget (``ttft_slo_ms`` > 0) it additionally reports
    ``goodput_rps`` — completed requests that met the budget, per
    second — and ``slo_attainment``, the met-budget fraction of
    completions (the closed-loop bench's primary metric).

    Robustness counters always ride along so chaos A/Bs can assert on
    them: ``preemptions``/``resumes`` (watermark evictions and
    transcript replays, failover resumes included), ``shed`` (requests
    the dispatcher refused with a typed ``ServeOverloaded``) split into
    ``shed_queue`` (bounded admission queue overflowed) and
    ``shed_deadline`` (lapsed or projected-TTFT budget), ``cancelled``
    (typed :class:`ServeCancelled` — client disconnects and drain
    force-cancels, deliberate by definition), and ``faulted`` —
    failures that were NEITHER sheds nor cancels, i.e. a fault or
    crash ate the request.  ``failed`` stays the historical total
    (faulted + shed + cancelled), so existing ``failed == 0``
    assertions keep their meaning."""
    done = [r for r in requests if r.done_s >= 0.0 and not r.failed]
    failed = [r for r in requests if r.failed]
    shed = [r for r in failed if getattr(r, "shed", False)]
    cancelled = [r for r in failed if getattr(r, "cancelled", False)
                 and not getattr(r, "shed", False)]
    ttfts = [r.ttft_s for r in done if r.ttft_s >= 0.0]
    per_token = []
    total_tokens = 0
    for r in done:
        total_tokens += len(r.tokens)
        if len(r.tokens) > 1 and r.ttft_s >= 0.0:
            decode_span = (r.done_s - r.arrival_s) - r.ttft_s
            per_token.append(decode_span / (len(r.tokens) - 1))
    out = {
        "completed": len(done),
        "failed": len(failed),
        "shed": len(shed),
        "shed_queue": sum(1 for r in shed
                          if getattr(r, "shed_kind", "") == "queue"),
        "shed_deadline": sum(1 for r in shed
                             if getattr(r, "shed_kind", "") == "deadline"),
        "cancelled": len(cancelled),
        "faulted": len(failed) - len(shed) - len(cancelled),
        "preemptions": sum(r.preemptions for r in requests),
        "resumes": sum(getattr(r, "resumes", 0) for r in requests),
        "total_tokens": total_tokens,
        "makespan_s": float(makespan_s),
        "tokens_per_sec": (total_tokens / makespan_s) if makespan_s > 0
        else 0.0,
        "ttft_p50_s": _percentile(ttfts, 50),
        "ttft_p99_s": _percentile(ttfts, 99),
        "per_token_p50_s": _percentile(per_token, 50),
        "per_token_p99_s": _percentile(per_token, 99),
    }
    if ttft_slo_ms > 0:
        slo_s = float(ttft_slo_ms) / 1000.0
        good = sum(1 for r in done if 0.0 <= r.ttft_s <= slo_s)
        out["ttft_slo_ms"] = float(ttft_slo_ms)
        out["goodput_rps"] = (good / makespan_s) if makespan_s > 0 else 0.0
        out["slo_attainment"] = (good / float(len(done))) if done else 0.0
    return out
