"""InferenceSession: bucketed AOT executables over the paged KV cache.

The serving analogue of ``fused.TrainStep.compile`` (PR 4): every
executable the session will ever run is compiled up front with
``jax.jit(...).lower(*avals).compile()`` —

* one **prefill** executable per sequence-length bucket (prompts are
  right-padded to the smallest bucket that fits), and
* one fixed-shape **decode** executable advancing *all* batch slots by
  a single token against the paged KV pools.

Because every input shape is frozen (pools, page tables, token/length
vectors), the compiled-executable count is exactly
``len(buckets) + 1`` for the session's lifetime.  Each executable gets
a ``compile_cache`` recompile guard seeded at compile time; a dispatch
that would need a new trace (a bug) trips ``MXNET_RECOMPILE_WARN`` /
``RecompileStorm`` just like training steps do.

Model load goes through the v2 elastic checkpoint restore
(:meth:`InferenceSession.from_checkpoint`), so an N-process training
run's shards serve directly in a single process.

Env knobs (see docs/env_vars.md): ``MXNET_SERVE_SLOTS``,
``MXNET_SERVE_PAGE``, ``MXNET_SERVE_BUCKETS``, ``MXNET_SERVE_MAX_NEW``,
``MXNET_SERVE_PAGES``, ``MXNET_SERVE_EXACT``.
"""
from __future__ import annotations

import dataclasses
import json
import time

from ..base import MXNetError, get_env
from .kv_cache import PagedKVCache
from .model import ModelConfig, config_from_params, decode_step, exact_mode, \
    prefill_forward

__all__ = ["ServeConfig", "InferenceSession"]


def _parse_buckets(raw):
    if isinstance(raw, str):
        parts = [p for p in raw.replace(";", ",").split(",") if p.strip()]
        raw = [int(p) for p in parts]
    buckets = tuple(sorted(set(int(b) for b in raw)))
    if not buckets:
        raise MXNetError("ServeConfig: empty bucket set")
    return buckets


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Capacity knobs for one :class:`InferenceSession`.

    ``buckets`` are padded prefill lengths (each a multiple of
    ``page_size``); ``max_new`` caps tokens generated per request;
    ``num_pages`` sizes the shared KV pool (default: full reservation
    capacity for ``slots`` worst-case requests).
    """

    slots: int = 4
    page_size: int = 16
    buckets: tuple = (16, 32, 64)
    max_new: int = 32
    num_pages: int = 0  # 0 = slots * max_pages_per_slot
    exact: bool = True

    @classmethod
    def from_env(cls, **overrides):
        vals = dict(
            slots=get_env("MXNET_SERVE_SLOTS", cls.slots, int),
            page_size=get_env("MXNET_SERVE_PAGE", cls.page_size, int),
            buckets=_parse_buckets(
                get_env("MXNET_SERVE_BUCKETS", "16,32,64", str)),
            max_new=get_env("MXNET_SERVE_MAX_NEW", cls.max_new, int),
            num_pages=get_env("MXNET_SERVE_PAGES", 0, int),
            exact=exact_mode(),
        )
        vals.update(overrides)
        return cls(**vals)

    def __post_init__(self):
        object.__setattr__(self, "buckets", _parse_buckets(self.buckets))
        if self.slots < 1 or self.page_size < 1 or self.max_new < 1:
            raise MXNetError("ServeConfig: slots/page_size/max_new must "
                             "be >= 1")
        for b in self.buckets:
            if b % self.page_size:
                raise MXNetError(
                    "ServeConfig: bucket %d is not a multiple of page_size "
                    "%d (prefill writes whole pages)" % (b, self.page_size))

    @property
    def max_pages_per_slot(self):
        worst = max(self.buckets) + self.max_new
        return -(-worst // self.page_size)

    @property
    def pool_pages(self):
        return self.num_pages or self.slots * self.max_pages_per_slot


class _Executable(object):
    """One AOT-compiled entry point + its recompile guard."""

    __slots__ = ("name", "compiled", "jitted", "guard", "aval_sig",
                 "memory", "fallbacks")

    def __init__(self, name, compiled, jitted, guard, aval_sig, memory):
        self.name = name
        self.compiled = compiled
        self.jitted = jitted
        self.guard = guard
        self.aval_sig = aval_sig
        self.memory = memory  # dict from memory_analysis(), at compile time
        self.fallbacks = 0


class InferenceSession(object):
    """Compile-once serving session for the built-in transformer LM.

    ``params`` is a flat name->array dict (raw ``jax.numpy`` arrays,
    numpy arrays, or NDArray) under the training parameter names;
    ``num_heads`` is required unless recoverable from a checkpoint
    symbol.  All executables are compiled in ``__init__`` — steady-state
    serving never traces.
    """

    def __init__(self, params, num_heads, config=None):
        import jax
        import jax.numpy as jnp

        from .. import compile_cache, profiler

        compile_cache.ensure_initialized()
        self.config = config or ServeConfig.from_env()
        cfg = self.config
        self.params = {}
        for k, v in params.items():
            if k in ("data", "softmax_label"):
                continue
            arr = getattr(v, "_data", v)
            self.params[k] = jnp.asarray(arr, jnp.float32)
        self.model = config_from_params(self.params, num_heads=num_heads)
        if max(cfg.buckets) + cfg.max_new > self.model.max_len:
            raise MXNetError(
                "ServeConfig worst case %d (bucket %d + max_new %d) exceeds "
                "the model's max_len %d"
                % (max(cfg.buckets) + cfg.max_new, max(cfg.buckets),
                   cfg.max_new, self.model.max_len))
        self.cache = PagedKVCache(
            num_layers=self.model.num_layers,
            num_heads=self.model.num_heads,
            head_dim=self.model.head_dim,
            page_size=cfg.page_size,
            num_pages=cfg.pool_pages,
            slots=cfg.slots,
            max_pages_per_slot=cfg.max_pages_per_slot)
        self._slot_tokens = {}  # slot -> next token to feed the decoder
        self._exes = {}
        # Recompile guards live in the process-global registry; embed the
        # model + capacity fingerprint in the guard name so two sessions
        # with different shapes (different avals) don't share a guard and
        # read each other's compiles as retraces.  Identical-config
        # sessions deliberately share: same avals -> same signature.
        self._guard_prefix = (
            "InferenceSession(%dL-d%d-h%d-V%d-s%d-p%d-m%d-n%d)"
            % (self.model.num_layers, self.model.d_model,
               self.model.num_heads, self.model.vocab_size, cfg.slots,
               cfg.page_size, cfg.max_pages_per_slot, cfg.pool_pages))
        self._compile_all()

    # -- compilation ------------------------------------------------------
    def _aot(self, name, fn, avals, donate_argnums):
        """``TrainStep.compile``-style AOT build of one executable."""
        import jax

        from .. import compile_cache, profiler
        from ..compile_cache import registry, signature_of

        jitted = jax.jit(fn, donate_argnums=donate_argnums)
        hits_before = compile_cache.cache_stats()["hits"]
        t0 = time.perf_counter()
        compiled = jitted.lower(*avals).compile()
        dt = time.perf_counter() - t0
        cache_hit = compile_cache.cache_stats()["hits"] > hits_before
        flops = None
        code_bytes = None
        memory = {}
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0)) or None
        except Exception:
            pass
        try:
            mem = compiled.memory_analysis()
            for attr in ("generated_code_size_in_bytes",
                         "argument_size_in_bytes",
                         "output_size_in_bytes",
                         "temp_size_in_bytes"):
                val = getattr(mem, attr, None)
                if val is not None:
                    memory[attr] = int(val)
            code_bytes = memory.get("generated_code_size_in_bytes")
        except Exception:
            pass
        profiler.compile_event("%s.%s" % (self._guard_prefix, name), dt,
                               flops=flops, executable_bytes=code_bytes,
                               cache_hit=cache_hit)
        guard = registry.guard("%s.%s" % (self._guard_prefix, name))
        sig = signature_of(avals)
        guard.observe(sig)
        self._exes[name] = _Executable(name, compiled, jitted, guard,
                                       sig, memory)

    def _compile_all(self):
        import jax
        import numpy as np

        cfg = self.config
        model = self.model
        exact = bool(cfg.exact)
        psize = cfg.page_size
        f32 = jax.numpy.float32
        i32 = jax.numpy.int32
        sds = jax.ShapeDtypeStruct
        param_avals = {k: sds(v.shape, v.dtype)
                       for k, v in self.params.items()}
        pool_shape = self.cache.k_pool.shape
        pool_aval = sds(pool_shape, f32)
        max_pages = cfg.max_pages_per_slot

        def decode_fn(params, tokens, lengths, tables, k_pool, v_pool):
            return decode_step(params, tokens, lengths, tables, k_pool,
                               v_pool, model, psize, exact=exact)

        self._aot(
            "decode", decode_fn,
            (param_avals, sds((cfg.slots,), i32), sds((cfg.slots,), i32),
             sds((cfg.slots, max_pages), i32), pool_aval, pool_aval),
            donate_argnums=(4, 5))

        for bucket in cfg.buckets:
            def prefill_fn(params, tokens, length, table_row, k_pool,
                           v_pool):
                return prefill_forward(params, tokens, length, table_row,
                                       k_pool, v_pool, model, psize,
                                       exact=exact)

            self._aot(
                "prefill_%d" % bucket, prefill_fn,
                (param_avals, sds((1, bucket), i32), sds((), i32),
                 sds((max_pages,), i32), pool_aval, pool_aval),
                donate_argnums=(4, 5))

    @classmethod
    def from_checkpoint(cls, directory, prefix="model", epoch=None,
                        num_heads=None, config=None):
        """Load params through the v2 elastic checkpoint restore and
        build a session.  An N-process training run's shards assemble
        in this single process; ``num_heads`` is read from the saved
        symbol when present."""
        from ..checkpoint import CheckpointManager

        state = CheckpointManager(directory, prefix=prefix).load(epoch=epoch)
        if num_heads is None and state.symbol is not None:
            num_heads = _num_heads_from_symbol(state.symbol)
        if num_heads is None:
            raise MXNetError(
                "from_checkpoint: pass num_heads= (the checkpoint symbol "
                "does not record a MultiHeadAttention op)")
        params = dict(state.arg_params)
        params.update(state.aux_params or {})
        return cls(params, num_heads=num_heads, config=config)

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, name, args):
        from ..compile_cache import signature_of

        rec = self._exes[name]
        sig = signature_of(args)
        rec.guard.observe(sig)
        try:
            return rec.compiled(*args)
        except Exception:
            # Shape/dtype drift from the compiled avals (guarded above)
            # falls back to the lazy jit rather than failing the request.
            rec.fallbacks += 1
            return rec.jitted(*args)

    # -- request lifecycle ------------------------------------------------
    def bucket_for(self, prompt_len):
        for b in self.config.buckets:
            if prompt_len <= b:
                return b
        raise MXNetError(
            "prompt of %d tokens exceeds the largest prefill bucket %d"
            % (prompt_len, max(self.config.buckets)))

    def try_alloc(self, prompt_len, max_new=None):
        """Reserve a slot for a request, or return ``None`` when the
        cache can't admit it right now."""
        if prompt_len < 1:
            raise MXNetError("empty prompt")
        self.bucket_for(prompt_len)  # validates length
        max_new = self.config.max_new if max_new is None else int(max_new)
        if max_new > self.config.max_new:
            raise MXNetError("max_new %d exceeds the session cap %d"
                             % (max_new, self.config.max_new))
        return self.cache.alloc(prompt_len, max_new)

    def prefill(self, slot, prompt_tokens):
        """Run the bucketed prefill for ``slot``; returns
        ``(first_token, last_logits)``."""
        import numpy as np
        import jax.numpy as jnp

        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        p = int(prompt.shape[0])
        bucket = self.bucket_for(p)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :p] = prompt
        args = (self.params, jnp.asarray(toks), jnp.asarray(p, jnp.int32),
                self.cache.table_row(slot), self.cache.k_pool,
                self.cache.v_pool)
        first, last_logits, k_pool, v_pool = self._dispatch(
            "prefill_%d" % bucket, args)
        self.cache.k_pool = k_pool
        self.cache.v_pool = v_pool
        self.cache.lengths[slot] = p
        first = int(first)
        self._slot_tokens[slot] = first
        return first, np.asarray(last_logits)

    def step(self):
        """Advance every active slot one token with the single decode
        executable; returns ``(tokens, logits)`` where ``tokens`` maps
        slot -> emitted token id and ``logits`` is the (slots, vocab)
        array (inactive rows are garbage by design)."""
        import numpy as np
        import jax.numpy as jnp

        cfg = self.config
        tokens = np.zeros((cfg.slots,), np.int32)
        for slot, tok in self._slot_tokens.items():
            tokens[slot] = tok
        args = (self.params, jnp.asarray(tokens),
                self.cache.device_lengths(), self.cache.device_tables(),
                self.cache.k_pool, self.cache.v_pool)
        next_toks, logits, k_pool, v_pool = self._dispatch("decode", args)
        self.cache.k_pool = k_pool
        self.cache.v_pool = v_pool
        next_np = np.asarray(next_toks)
        out = {}
        for slot in list(self._slot_tokens):
            self.cache.lengths[slot] += 1
            tok = int(next_np[slot])
            self._slot_tokens[slot] = tok
            out[slot] = tok
        return out, np.asarray(logits)

    def release(self, slot):
        self._slot_tokens.pop(slot, None)
        self.cache.release(slot)

    def active_slots(self):
        return sorted(self._slot_tokens)

    # -- accounting -------------------------------------------------------
    @property
    def executables(self):
        """name -> compiled executable (fixed set: buckets + decode)."""
        return {name: rec.compiled for name, rec in self._exes.items()}

    def memory_analysis(self, name="decode"):
        """Compile-time ``memory_analysis()`` numbers for one
        executable — the decode entry is the flat per-step watermark."""
        return dict(self._exes[name].memory)

    def guard_report(self):
        return {name: rec.guard.snapshot() for name, rec in
                self._exes.items()}

    def fallback_count(self):
        return sum(rec.fallbacks for rec in self._exes.values())


def _num_heads_from_symbol(symbol):
    """Pull ``num_heads`` out of a saved symbol's MultiHeadAttention
    node, if the checkpoint recorded one."""
    try:
        graph = json.loads(symbol.tojson())
    except Exception:
        return None
    for node in graph.get("nodes", []):
        op = (node.get("op") or "").lower()
        if "multiheadattention" in op.replace("_", ""):
            attrs = node.get("attrs") or node.get("param") or {}
            if "num_heads" in attrs:
                try:
                    return int(attrs["num_heads"])
                except (TypeError, ValueError):
                    pass
    return None
