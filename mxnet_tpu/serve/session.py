"""InferenceSession: bucketed AOT executables over the paged KV cache.

The serving analogue of ``fused.TrainStep.compile`` (PR 4): every
executable the session will ever run is compiled up front with
``jax.jit(...).lower(*avals).compile()`` —

* one **prefill** executable per sequence-length bucket (prompts are
  right-padded to the smallest bucket that fits), and
* one fixed-shape **decode** executable advancing *all* batch slots by
  a single token against the paged KV pools.

Because every input shape is frozen (pools, page tables, token/length
vectors), the compiled-executable count is exactly
``len(buckets) + 1`` for the session's lifetime — or, with speculative
decoding enabled (``spec_k > 0``), ``len(buckets) + 3``: the same
prefill set and decode step plus one fixed-shape K+1-row **verify**
executable and one **draft** decode executable (the draft executable is
skipped for the host-side n-gram draft, giving ``len(buckets) + 2``).
Each executable gets a ``compile_cache`` recompile guard seeded at
compile time; a dispatch that would need a new trace (a bug) trips
``MXNET_RECOMPILE_WARN`` / ``RecompileStorm`` just like training steps
do.

Speculative decoding (ROADMAP 3(b)): a draft proposes ``spec_k`` tokens
per slot, the target model verifies all slots' proposals in ONE
``spec_k + 1``-row teacher-forced step, and greedy acceptance commits
the longest prefix the target agrees with — 1..K+1 tokens per step.
Because verify runs under the same M-invariant ``exact`` mode as
decode, acceptance is *exact*: row ``j`` of the verify is bit-identical
to the ``j``-th serial decode step, so spec-on output == spec-off
output token for token.  Rejected suffixes roll back through
:meth:`PagedKVCache.truncate`; the draft keeps its own cache in
lockstep.  Draft selection via ``MXNET_SERVE_DRAFT``: ``ngram`` (host
prompt-lookup, no extra params), ``layers:N`` (the target's first N
blocks — self-speculative layer skip), or a checkpoint directory.

Model load goes through the v2 elastic checkpoint restore
(:meth:`InferenceSession.from_checkpoint`), so an N-process training
run's shards serve directly in a single process.

Weight-only quantization (``ServeConfig.quant`` / ``MXNET_SERVE_QUANT``,
``int8`` or ``fp8``): eligible weights are stored as 1-byte codes with
per-channel scales (see ``mxnet_tpu.quantize``) and dequantized INSIDE
each executable — at-rest and argument bytes shrink ~4x, the executable
count stays frozen, and because dequantization is deterministic
elementwise math the M-invariant exact mode still certifies bit-
exactness per precision (quantized decode == quantized verify, so
speculative decoding composes unchanged).  Quantized and full-precision
sessions never alias recompile guards: the guard prefix grows a
``-q<mode>`` tag.

KV-cache page quantization (``ServeConfig.kv_quant`` /
``MXNET_SERVE_KV_QUANT``, ``int8`` or ``e4m3``/``fp8``): the paged KV
pools store 1-byte codes with one float32 scale per (layer, page, row)
position kept in parallel scale pools.  Every executable quantizes on
append (a token's codes+scale are a pure function of that token's K/V
values, so prefill scatter, serial decode, batched verify, prefix-hit
replay and preempt/re-prefill stay byte-identical) and dequantizes
inside the attention kernel's block scan, where XLA fuses the convert.
Pool bytes shrink ~4x — slot capacity at fixed pool bytes multiplies on
top of oversubscription — the executable count stays frozen, and the
bit-exactness oracle re-pins per precision
(``reference_last_logits(..., kv_quant=...)`` fake-quantizes its
reference KV the same way).  Guard prefixes grow a ``-kv<mode>`` tag.

Prefix caching (``prefix_pages`` / ``MXNET_SERVE_PREFIX_PAGES``): after
every prefill the slot's full prompt pages are published into the KV
cache's token-hash index; a later admission whose prompt chain hits the
index maps those pages read-only (reference-counted, copy-on-write) and
prefills only the uncached suffix.  The suffix runs through the SAME
per-bucket prefill executables — each takes a position ``offset``
argument, so a suffix chunk is just a dispatch at a non-zero offset and
the executable count stays frozen.  The offset also enables *chunked*
prefill (sequences longer than the largest bucket run as page-aligned
max-bucket chunks), which is what lets a preempted request re-prefill
its whole transcript on resume.

Oversubscription (``oversub`` / ``MXNET_SERVE_OVERSUB``): admission
reserves only the prompt's pages; every decode/verify boundary grows
active slots on demand (:meth:`InferenceSession.pages_short` is the
scheduler's shortfall probe, and the scheduler preempts requests when
the pool runs below its watermark before the growth would fail).

Hybrid stacks (``layers`` / ``MXNET_SERVE_LAYERS`` +
``window`` / ``MXNET_SERVE_WINDOW``): a per-layer kind pattern
(``full`` | ``window`` | ``ssm``, cycled over the model's depth) turns
the decoder into a hybrid stack whose per-slot memory is O(1) in
context length.  Windowed layers keep a fixed ring of pages per slot
(ring append overwrites the oldest rows in place; the rotated,
position-labeled gather keeps attention bit-exact against the windowed
reference); SSM layers keep one (H, D, D) fp32 recurrence state per
slot in the cache's state pool, prefill advances it with a chunked
in-dispatch scan and decode with the same scan at T=1 — identical op
sequences, so chunked and serial execution are bit-identical.  The
executable count stays frozen (hybrid changes argument lists, not the
executable set), speculative decoding composes (verify recomputes
acceptance in-graph to commit SSM state snapshots at each slot's
commit point; rings roll back lengths-only), and preempt/resume uses
the same deterministic re-prefill oracle — re-running prefill
reconstructs ring contents and SSM state exactly.  Prefix caching is
the one subsystem hybrid stacks opt out of: rings and states are
slot-private, so the only window-aligned boundary at which every layer
kind's state is reconstructible from published pages is offset 0 —
lookups miss and nothing is published.

Env knobs (see docs/env_vars.md): ``MXNET_SERVE_SLOTS``,
``MXNET_SERVE_PAGE``, ``MXNET_SERVE_BUCKETS``, ``MXNET_SERVE_MAX_NEW``,
``MXNET_SERVE_PAGES``, ``MXNET_SERVE_EXACT``, ``MXNET_SERVE_SPEC_K``,
``MXNET_SERVE_DRAFT``, ``MXNET_SERVE_QUANT``,
``MXNET_SERVE_KV_QUANT``, ``MXNET_SERVE_PREFIX_PAGES``,
``MXNET_SERVE_OVERSUB``,
``MXNET_SERVE_WATERMARK``, ``MXNET_SERVE_TTFT_SLO_MS``,
``MXNET_SERVE_WINDOW``, ``MXNET_SERVE_LAYERS``.
"""
from __future__ import annotations

import dataclasses
import json
import time

from ..base import MXNetError, get_env
from ..quantize import quant_mode
from .kv_cache import PagedKVCache
from .model import ModelConfig, _pool_names, config_from_params, \
    decode_step, draft_propose, exact_mode, prefill_forward, verify_step

__all__ = ["ServeConfig", "InferenceSession"]


def _parse_buckets(raw):
    if isinstance(raw, str):
        parts = [p for p in raw.replace(";", ",").split(",") if p.strip()]
        raw = [int(p) for p in parts]
    buckets = tuple(sorted(set(int(b) for b in raw)))
    if not buckets:
        raise MXNetError("ServeConfig: empty bucket set")
    return buckets


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Capacity knobs for one :class:`InferenceSession`.

    ``buckets`` are padded prefill lengths (each a multiple of
    ``page_size``); ``max_new`` caps tokens generated per request;
    ``num_pages`` sizes the shared KV pool (default: full reservation
    capacity for ``slots`` worst-case requests); ``spec_k`` > 0 turns
    on speculative decoding with K draft proposals per step and
    ``draft`` picks the proposer (``""``/``"ngram"`` host prompt-lookup,
    ``"layers:N"`` target-derived truncation, else a checkpoint
    directory).
    """

    slots: int = 4
    page_size: int = 16
    buckets: tuple = (16, 32, 64)
    max_new: int = 32
    num_pages: int = 0  # 0 = slots * max_pages_per_slot
    exact: bool = True
    spec_k: int = 0  # 0 = speculative decoding off
    draft: str = ""  # "", "ngram", "layers:N", or a checkpoint dir
    quant: str = ""  # "", "int8", or "fp8" weight-only quantization
    kv_quant: str = ""  # "", "int8", or "fp8" KV-cache page quantization
    prefix_pages: int = 0  # 0 = prefix cache off; -1 = unbounded retention
    oversub: bool = False  # admit by current need, grow on demand
    watermark: int = 0  # free-pool floor that triggers preemption
    ttft_slo_ms: float = 0.0  # 0 = no TTFT budget (SLO admission off)
    window: int = 0  # sliding-window length for "window" layers
    layers: str = ""  # layer-kind pattern, e.g. "full,window,ssm"
    # ``layers`` is cycled over the model's depth ("full,window" on a
    # 4-layer model -> full,window,full,window); ``window`` sizes every
    # "window" layer's attention span (its KV lives in a fixed ring of
    # pages per slot, so per-slot bytes stop scaling with context).
    # Empty layers = the classic all-full-attention stack; window is
    # ignored then.

    @classmethod
    def from_env(cls, **overrides):
        vals = dict(
            slots=get_env("MXNET_SERVE_SLOTS", cls.slots, int),
            page_size=get_env("MXNET_SERVE_PAGE", cls.page_size, int),
            buckets=_parse_buckets(
                get_env("MXNET_SERVE_BUCKETS", "16,32,64", str)),
            max_new=get_env("MXNET_SERVE_MAX_NEW", cls.max_new, int),
            num_pages=get_env("MXNET_SERVE_PAGES", 0, int),
            exact=exact_mode(),
            spec_k=get_env("MXNET_SERVE_SPEC_K", 0, int),
            draft=get_env("MXNET_SERVE_DRAFT", "", str),
            quant=get_env("MXNET_SERVE_QUANT", "", str),
            kv_quant=get_env("MXNET_SERVE_KV_QUANT", "", str),
            prefix_pages=get_env("MXNET_SERVE_PREFIX_PAGES", 0, int),
            oversub=get_env("MXNET_SERVE_OVERSUB", False, bool),
            watermark=get_env("MXNET_SERVE_WATERMARK", 0, int),
            ttft_slo_ms=get_env("MXNET_SERVE_TTFT_SLO_MS", 0.0, float),
            window=get_env("MXNET_SERVE_WINDOW", 0, int),
            layers=get_env("MXNET_SERVE_LAYERS", "", str),
        )
        vals.update(overrides)
        return cls(**vals)

    def __post_init__(self):
        object.__setattr__(self, "buckets", _parse_buckets(self.buckets))
        object.__setattr__(self, "quant", quant_mode(self.quant))
        object.__setattr__(self, "kv_quant", quant_mode(self.kv_quant))
        if self.slots < 1 or self.page_size < 1 or self.max_new < 1:
            raise MXNetError("ServeConfig: slots/page_size/max_new must "
                             "be >= 1")
        if self.spec_k < 0:
            raise MXNetError("ServeConfig: spec_k must be >= 0")
        if self.prefix_pages < -1:
            raise MXNetError("ServeConfig: prefix_pages must be >= -1")
        if self.watermark < 0:
            raise MXNetError("ServeConfig: watermark must be >= 0")
        if self.ttft_slo_ms < 0:
            raise MXNetError("ServeConfig: ttft_slo_ms must be >= 0")
        for b in self.buckets:
            if b % self.page_size:
                raise MXNetError(
                    "ServeConfig: bucket %d is not a multiple of page_size "
                    "%d (prefill writes whole pages)" % (b, self.page_size))
        if self.window < 0:
            raise MXNetError("ServeConfig: window must be >= 0")
        pat = self.layer_pattern
        bad = set(pat) - {"full", "window", "ssm"}
        if bad:
            raise MXNetError("ServeConfig: unknown layer kinds %r in "
                             "layers=%r" % (sorted(bad), self.layers))
        if "window" in pat and self.window < 1:
            raise MXNetError(
                "ServeConfig: layers=%r has windowed layers but window "
                "is unset (MXNET_SERVE_WINDOW)" % (self.layers,))

    @property
    def layer_pattern(self):
        """``layers`` parsed into a kind tuple (may be shorter than the
        model — :meth:`kinds_for` cycles it over the real depth)."""
        return tuple(t.strip() for t in self.layers.replace(";", ",")
                     .split(",") if t.strip())

    def kinds_for(self, num_layers):
        """Per-layer kinds for an ``num_layers``-deep model: the
        ``layers`` pattern repeated to cover the stack.  All-full
        patterns normalize to ``()`` so they keep the classic executable
        signatures (and recompile-guard names) byte-identical."""
        pat = self.layer_pattern
        if not pat:
            return ()
        kinds = tuple(pat[i % len(pat)] for i in range(int(num_layers)))
        return () if set(kinds) == {"full"} else kinds

    @property
    def ring_pages(self):
        """Ring capacity (pages) for each windowed layer's per-slot KV.

        A dispatch writes up to ``write_span`` rows (the largest prefill
        chunk, or the speculative window) before its queries read, so a
        ring must hold the window plus the whole span minus the row that
        overlaps (``window + span - 1`` rows) for no visible key to be
        overwritten mid-dispatch — plus one extra page because the
        rotated gather is page-granular: the newest page may be only
        one row full, yet the gather must still reach ``window + span -
        1`` rows below that row."""
        span = max(max(self.buckets),
                   self.spec_window if self.spec_k else 1)
        return -(-(self.window + span - 1) // self.page_size) + 1

    @property
    def max_pages_per_slot(self):
        worst = max(self.buckets) + self.max_new
        return -(-worst // self.page_size)

    @property
    def pool_pages(self):
        return self.num_pages or self.slots * self.max_pages_per_slot

    @property
    def spec_window(self):
        """Verify rows per speculative step: the last committed token
        plus the K proposals."""
        return self.spec_k + 1

    @property
    def spec_pad_pages(self):
        """All-trash page-table columns appended past the reservable
        range.  A verify/draft step writes up to ``spec_k`` rows beyond
        a slot's committed horizon; near the end of a request those can
        cross the reservation boundary, and the executables' page-index
        clip must then land on trash instead of aliasing the slot's
        last real page."""
        return -(-self.spec_k // self.page_size) if self.spec_k else 0


class _Executable(object):
    """One AOT-compiled entry point + its recompile guard."""

    __slots__ = ("name", "compiled", "jitted", "guard", "aval_sig",
                 "memory", "fallbacks")

    def __init__(self, name, compiled, jitted, guard, aval_sig, memory):
        self.name = name
        self.compiled = compiled
        self.jitted = jitted
        self.guard = guard
        self.aval_sig = aval_sig
        self.memory = memory  # dict from memory_analysis(), at compile time
        self.fallbacks = 0


class InferenceSession(object):
    """Compile-once serving session for the built-in transformer LM.

    ``params`` is a flat name->array dict (raw ``jax.numpy`` arrays,
    numpy arrays, or NDArray) under the training parameter names;
    ``num_heads`` is required unless recoverable from a checkpoint
    symbol.  All executables are compiled in ``__init__`` — steady-state
    serving never traces.

    With ``config.spec_k > 0`` the session also hosts a draft proposer:
    pass ``draft_params`` (+ ``draft_num_heads``) explicitly, or let
    ``config.draft`` resolve one (``"ngram"``, ``"layers:N"``, or a
    checkpoint directory).  A parameterized draft gets its own
    :class:`PagedKVCache` (same slot/page geometry, draft dims) that
    the session keeps in exact lockstep with the target cache.
    """

    def __init__(self, params, num_heads, config=None, draft_params=None,
                 draft_num_heads=None):
        import jax
        import jax.numpy as jnp

        from .. import compile_cache, profiler

        compile_cache.ensure_initialized()
        self.config = config or ServeConfig.from_env()
        if config is None:
            # env-driven config: a cached autotune record for this
            # (model-fingerprint, backend) may override knobs (opt-in
            # via MXNET_AUTOTUNE; provenance rides the compile report)
            from .. import autotune as _autotune

            self.config = _autotune.apply_serve(self.config, params)
        cfg = self.config
        self.params = {}
        for k, v in params.items():
            if k in ("data", "softmax_label"):
                continue
            arr = getattr(v, "_data", v)
            self.params[k] = jnp.asarray(arr, jnp.float32)
        self.model = config_from_params(self.params, num_heads=num_heads)
        kinds = cfg.kinds_for(self.model.num_layers)
        if kinds:
            # hybrid stack: the kind pattern cycles over the real depth
            # and every kind reuses the block's attention weights, so
            # any checkpoint hosts any stack
            self.model = dataclasses.replace(
                self.model, window=cfg.window, layer_kinds=kinds).validate()
        if max(cfg.buckets) + cfg.max_new > self.model.max_len:
            raise MXNetError(
                "ServeConfig worst case %d (bucket %d + max_new %d) exceeds "
                "the model's max_len %d"
                % (max(cfg.buckets) + cfg.max_new, max(cfg.buckets),
                   cfg.max_new, self.model.max_len))
        self.cache = PagedKVCache(
            num_layers=self.model.num_layers,
            num_heads=self.model.num_heads,
            head_dim=self.model.head_dim,
            page_size=cfg.page_size,
            num_pages=cfg.pool_pages,
            slots=cfg.slots,
            max_pages_per_slot=cfg.max_pages_per_slot,
            table_pad=cfg.spec_pad_pages,
            prefix_pages=cfg.prefix_pages,
            kv_quant=cfg.kv_quant,
            layer_kinds=self.model.layer_kinds,
            window=self.model.window,
            ring_pages=cfg.ring_pages if "window" in kinds else 0)
        self._slot_tokens = {}  # slot -> next token to feed the decoder
        self._slot_history = {}  # slot -> prompt + committed tokens
        self._spec_stats = {"verify_steps": 0, "slot_steps": 0,
                            "proposed": 0, "accepted": 0, "committed": 0}
        self._resolve_draft(draft_params, draft_num_heads)
        if cfg.quant:
            # weight-only quantization of the at-rest params (the draft
            # shares the mode): eligible weights become {"q", "s"} code/
            # scale records that every executable dequantizes in-graph
            from .. import quantize as _quant

            self.params = _quant.quantize_params(self.params, cfg.quant)
            if self.draft_params is not None:
                self.draft_params = _quant.quantize_params(
                    self.draft_params, cfg.quant)
        self._exes = {}
        # Recompile guards live in the process-global registry; embed the
        # model + capacity fingerprint in the guard name so two sessions
        # with different shapes (different avals) don't share a guard and
        # read each other's compiles as retraces.  Identical-config
        # sessions deliberately share: same avals -> same signature.
        # spec_k changes the table width (and adds executables), so it
        # is part of the fingerprint.
        self._guard_prefix = (
            "InferenceSession(%dL-d%d-h%d-V%d-s%d-p%d-m%d-n%d)"
            % (self.model.num_layers, self.model.d_model,
               self.model.num_heads, self.model.vocab_size, cfg.slots,
               cfg.page_size, cfg.max_pages_per_slot, cfg.pool_pages))
        if cfg.spec_k:
            self._guard_prefix += "-k%d" % cfg.spec_k
        if cfg.quant:
            # quantized avals differ from full-precision ones, so the
            # sessions must never share a guard fingerprint
            self._guard_prefix += "-q%s" % cfg.quant
        if cfg.kv_quant:
            # quantized KV pools change every executable's pool avals
            # (storage dtype + parallel scale arrays)
            self._guard_prefix += "-kv%s" % cfg.kv_quant
        if self.model.hybrid:
            # hybrid stacks add ring/state pool avals (and a window
            # length baked into every trace), so they must never share
            # a guard with the classic stack — tag: window length plus
            # the per-layer kind initials (f/w/s)
            self._guard_prefix += "-w%d%s" % (
                self.model.window,
                "".join(k[0] for k in self.model.kinds))
        self._compile_all()

    def _resolve_draft(self, draft_params, draft_num_heads):
        """Pick the speculative proposer: explicit params, the host-side
        n-gram lookup, a layer-truncated copy of the target, or a
        checkpoint restore — then build its mirrored cache."""
        import jax.numpy as jnp

        cfg = self.config
        self.draft_params = None
        self.draft_model = None
        self.draft_cache = None
        self._draft_mode = "off"
        if not cfg.spec_k:
            if draft_params is not None:
                raise MXNetError(
                    "draft_params given but spec_k == 0 — set "
                    "ServeConfig.spec_k (MXNET_SERVE_SPEC_K) to enable "
                    "speculative decoding")
            return
        inherit_layers = None
        if draft_params is None:
            spec = cfg.draft or "ngram"
            if spec == "ngram":
                self._draft_mode = "ngram"
                return
            if spec.startswith("layers:"):
                n = int(spec.split(":", 1)[1])
                draft_params = _layer_truncated(self.params, n)
                draft_num_heads = draft_num_heads or self.model.num_heads
                # a layer-skip draft IS the target's first n blocks, so
                # it inherits their kinds (and the window) — its ring
                # writes then track the target's committed stream and
                # roll back lengths-only, exactly like the paged pools
                inherit_layers = n
            else:
                from ..checkpoint import CheckpointManager

                state = CheckpointManager(spec).load()
                if draft_num_heads is None and state.symbol is not None:
                    draft_num_heads = _num_heads_from_symbol(state.symbol)
                draft_params = dict(state.arg_params)
                draft_params.update(state.aux_params or {})
        self._draft_mode = "model"
        self.draft_params = {}
        for k, v in draft_params.items():
            if k in ("data", "softmax_label"):
                continue
            arr = getattr(v, "_data", v)
            self.draft_params[k] = jnp.asarray(arr, jnp.float32)
        self.draft_model = config_from_params(
            self.draft_params,
            num_heads=draft_num_heads or self.model.num_heads)
        if inherit_layers is not None and self.model.hybrid:
            dkinds = self.model.kinds[:inherit_layers]
            if set(dkinds) != {"full"}:
                self.draft_model = dataclasses.replace(
                    self.draft_model, window=self.model.window,
                    layer_kinds=dkinds).validate()
        if "ssm" in self.draft_model.kinds:
            raise MXNetError(
                "draft model has SSM layers — a draft's speculative rows "
                "must roll back O(1), and an SSM draft would need its own "
                "verify-synchronized state pool; put SSM layers above the "
                "draft depth or use the ngram draft")
        if self.draft_model.vocab_size != self.model.vocab_size:
            raise MXNetError(
                "draft vocab %d != target vocab %d — a draft must share "
                "the target's token space"
                % (self.draft_model.vocab_size, self.model.vocab_size))
        if max(cfg.buckets) + cfg.max_new > self.draft_model.max_len:
            raise MXNetError(
                "draft max_len %d cannot cover the serve worst case %d"
                % (self.draft_model.max_len,
                   max(cfg.buckets) + cfg.max_new))
        self.draft_cache = PagedKVCache(
            num_layers=self.draft_model.num_layers,
            num_heads=self.draft_model.num_heads,
            head_dim=self.draft_model.head_dim,
            page_size=cfg.page_size,
            num_pages=cfg.pool_pages,
            slots=cfg.slots,
            max_pages_per_slot=cfg.max_pages_per_slot,
            table_pad=cfg.spec_pad_pages,
            prefix_pages=cfg.prefix_pages,
            kv_quant=cfg.kv_quant,
            layer_kinds=self.draft_model.layer_kinds,
            window=self.draft_model.window,
            ring_pages=(cfg.ring_pages
                        if "window" in self.draft_model.kinds else 0))

    # -- compilation ------------------------------------------------------
    def _aot(self, name, fn, avals, donate_argnums):
        """``TrainStep.compile``-style AOT build of one executable."""
        import jax

        from .. import compile_cache, profiler
        from ..compile_cache import registry, signature_of

        jitted = jax.jit(fn, donate_argnums=donate_argnums)
        hits_before = compile_cache.cache_stats()["hits"]
        t0 = time.perf_counter()
        compiled = jitted.lower(*avals).compile()
        dt = time.perf_counter() - t0
        cache_hit = compile_cache.cache_stats()["hits"] > hits_before
        flops = None
        code_bytes = None
        memory = {}
        try:
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            flops = float(cost.get("flops", 0.0)) or None
        except Exception:
            pass
        try:
            mem = compiled.memory_analysis()
            for attr in ("generated_code_size_in_bytes",
                         "argument_size_in_bytes",
                         "output_size_in_bytes",
                         "temp_size_in_bytes"):
                val = getattr(mem, attr, None)
                if val is not None:
                    memory[attr] = int(val)
            code_bytes = memory.get("generated_code_size_in_bytes")
        except Exception:
            pass
        profiler.compile_event("%s.%s" % (self._guard_prefix, name), dt,
                               flops=flops, executable_bytes=code_bytes,
                               cache_hit=cache_hit)
        guard = registry.guard("%s.%s" % (self._guard_prefix, name))
        sig = signature_of(avals)
        guard.observe(sig)
        self._exes[name] = _Executable(name, compiled, jitted, guard,
                                       sig, memory)

    def _compile_all(self):
        import jax

        cfg = self.config
        model = self.model
        exact = bool(cfg.exact)
        psize = cfg.page_size
        kvq = cfg.kv_quant
        i32 = jax.numpy.int32
        sds = jax.ShapeDtypeStruct
        # tree.map sees through quantized {"q", "s"} records, so the
        # executables' arguments are the 1-byte codes themselves
        param_avals = jax.tree.map(lambda v: sds(v.shape, v.dtype),
                                   self.params)

        # pool avals in the canonical _pool_pack order — float32 pools
        # clean, 1-byte codes + scale pools under kv_quant, ring/state
        # pools appended for hybrid stacks.  The classic all-full stack
        # keeps its historical signatures byte-identical.
        def pool_avals(cache):
            return tuple(sds(p.shape, p.dtype)
                         for p in self._pool_args(cache))

        pools = pool_avals(self.cache)
        names = _pool_names(kvq, self.cache.n_window > 0,
                            self.cache.n_ssm > 0)
        hybrid = self.cache.hybrid
        # table width includes the speculative all-trash pad columns
        # (zero when spec_k == 0, so non-spec avals are unchanged)
        max_pages = self.cache.table_width

        def decode_fn(params, tokens, lengths, tables, *pool_args):
            return decode_step(params, tokens, lengths, tables,
                               cfg=model, page_size=psize, exact=exact,
                               kv_quant=kvq, **dict(zip(names, pool_args)))

        self._aot(
            "decode", decode_fn,
            (param_avals, sds((cfg.slots,), i32), sds((cfg.slots,), i32),
             sds((cfg.slots, max_pages), i32)) + pools,
            donate_argnums=tuple(range(4, 4 + len(pools))))

        for bucket in cfg.buckets:
            # hybrid prefill takes a slot scalar (rings and SSM state
            # are slot-indexed, unlike the table-indirected pages)
            def prefill_fn(params, tokens, length, offset, table_row,
                           *rest):
                if hybrid:
                    slot, pool_args = rest[0], rest[1:]
                else:
                    slot, pool_args = None, rest
                return prefill_forward(params, tokens, length, offset,
                                       table_row, cfg=model,
                                       page_size=psize, exact=exact,
                                       kv_quant=kvq, slot=slot,
                                       **dict(zip(names, pool_args)))

            self._aot(
                "prefill_%d" % bucket, prefill_fn,
                (param_avals, sds((1, bucket), i32), sds((), i32),
                 sds((), i32), sds((max_pages,), i32))
                + ((sds((), i32),) if hybrid else ()) + pools,
                donate_argnums=tuple(range(5 + (1 if hybrid else 0),
                                           5 + (1 if hybrid else 0)
                                           + len(pools))))

        if cfg.spec_k:
            w = cfg.spec_window
            # SSM layers make the per-slot commit cap an executable
            # input: the in-graph acceptance recomputation selects each
            # slot's state snapshot at its commit point (O(1) rollback)
            has_limits = self.cache.n_ssm > 0

            def verify_fn(params, tokens, lengths, tables, *rest):
                if has_limits:
                    limits, pool_args = rest[0], rest[1:]
                else:
                    limits, pool_args = None, rest
                return verify_step(params, tokens, lengths, tables,
                                   cfg=model, page_size=psize,
                                   exact=exact, kv_quant=kvq,
                                   limits=limits,
                                   **dict(zip(names, pool_args)))

            self._aot(
                "verify", verify_fn,
                (param_avals, sds((cfg.slots, w), i32),
                 sds((cfg.slots,), i32), sds((cfg.slots, max_pages), i32))
                + ((sds((cfg.slots,), i32),) if has_limits else ())
                + pools,
                donate_argnums=tuple(range(4 + (1 if has_limits else 0),
                                           4 + (1 if has_limits else 0)
                                           + len(pools))))

        if self._draft_mode == "model":
            w = cfg.spec_window
            dmodel = self.draft_model
            draft_avals = jax.tree.map(lambda v: sds(v.shape, v.dtype),
                                       self.draft_params)
            dpools = pool_avals(self.draft_cache)
            dnames = _pool_names(kvq, self.draft_cache.n_window > 0,
                                 False)

            def draft_fn(params, tokens, n_feed, lengths, tables,
                         *pool_args):
                return draft_propose(params, tokens, n_feed, lengths,
                                     tables, cfg=dmodel, page_size=psize,
                                     exact=exact, kv_quant=kvq,
                                     **dict(zip(dnames, pool_args)))

            self._aot(
                "draft", draft_fn,
                (draft_avals, sds((cfg.slots, w), i32),
                 sds((cfg.slots,), i32), sds((cfg.slots,), i32),
                 sds((cfg.slots, max_pages), i32)) + dpools,
                donate_argnums=tuple(range(5, 5 + len(dpools))))

    @classmethod
    def from_checkpoint(cls, directory, prefix="model", epoch=None,
                        num_heads=None, config=None):
        """Load params through the v2 elastic checkpoint restore and
        build a session.  An N-process training run's shards assemble
        in this single process; ``num_heads`` is read from the saved
        symbol when present."""
        from ..checkpoint import CheckpointManager

        state = CheckpointManager(directory, prefix=prefix).load(epoch=epoch)
        if num_heads is None and state.symbol is not None:
            num_heads = _num_heads_from_symbol(state.symbol)
        if num_heads is None:
            raise MXNetError(
                "from_checkpoint: pass num_heads= (the checkpoint symbol "
                "does not record a MultiHeadAttention op)")
        params = dict(state.arg_params)
        params.update(state.aux_params or {})
        return cls(params, num_heads=num_heads, config=config)

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, name, args):
        from ..compile_cache import signature_of

        rec = self._exes[name]
        sig = signature_of(args)
        rec.guard.observe(sig)
        try:
            return rec.compiled(*args)
        except Exception:
            # Shape/dtype drift from the compiled avals (guarded above)
            # falls back to the lazy jit rather than failing the request.
            rec.fallbacks += 1
            return rec.jitted(*args)

    def _pool_args(self, cache):
        """The pool arguments a dispatch appends, in the canonical
        ``model._pool_pack`` order: (k, v) pools, the per-row scale
        pools under ``kv_quant``, then any windowed-layer rings (plus
        ring scales) and the SSM state pool."""
        pools = [cache.k_pool, cache.v_pool]
        if self.config.kv_quant:
            pools += [cache.k_scale, cache.v_scale]
        if cache.n_window:
            pools += [cache.kw_pool, cache.vw_pool]
            if self.config.kv_quant:
                pools += [cache.kw_scale, cache.vw_scale]
        if cache.n_ssm:
            pools.append(cache.ssm_state)
        return tuple(pools)

    def _store_pools(self, cache, pools):
        """Re-adopt the (donated) pool outputs of a dispatch."""
        it = iter(pools)
        cache.k_pool, cache.v_pool = next(it), next(it)
        if self.config.kv_quant:
            cache.k_scale, cache.v_scale = next(it), next(it)
        if cache.n_window:
            cache.kw_pool, cache.vw_pool = next(it), next(it)
            if self.config.kv_quant:
                cache.kw_scale, cache.vw_scale = next(it), next(it)
        if cache.n_ssm:
            cache.ssm_state = next(it)

    # -- request lifecycle ------------------------------------------------
    def bucket_for(self, prompt_len):
        for b in self.config.buckets:
            if prompt_len <= b:
                return b
        raise MXNetError(
            "prompt of %d tokens exceeds the largest prefill bucket %d"
            % (prompt_len, max(self.config.buckets)))

    def try_alloc(self, prompt_len, max_new=None, tokens=None,
                  resume=False):
        """Reserve a slot for a request, or return ``None`` when the
        cache can't admit it right now.

        ``tokens`` (the prompt's token ids) enables the prefix-cache
        lookup: published pages whose chain matches are mapped into the
        slot and :meth:`PagedKVCache.cached_len` reports the positions
        prefill may skip.  ``resume=True`` lifts the bucket-length check
        (a preempted request's re-prefill sequence — prompt plus already
        committed tokens — may exceed the largest bucket; chunked
        prefill covers it, and page capacity is still enforced because
        the resumed worst case equals the original one)."""
        if prompt_len < 1:
            raise MXNetError("empty prompt")
        if not resume:
            self.bucket_for(prompt_len)  # validates length
        max_new = self.config.max_new if max_new is None else int(max_new)
        if max_new > self.config.max_new:
            raise MXNetError("max_new %d exceeds the session cap %d"
                             % (max_new, self.config.max_new))
        toks = None
        if tokens is not None:
            toks = [int(t) for t in tokens]
            if len(toks) != int(prompt_len):
                raise MXNetError(
                    "try_alloc: tokens length %d != prompt_len %d"
                    % (len(toks), prompt_len))
        oversub = self.config.oversub
        slot = self.cache.alloc(prompt_len, max_new, tokens=toks,
                                oversub=oversub)
        if slot is not None and self.draft_cache is not None:
            # identical geometry + identical alloc/release/publish
            # sequences keep the two caches' deterministic free lists
            # AND prefix indexes in lockstep (the draft's hit pages hold
            # draft-model KV for the same token chain)
            dslot = self.draft_cache.alloc(prompt_len, max_new,
                                           tokens=toks, oversub=oversub)
            if dslot != slot or (self.draft_cache.cached_len(dslot)
                                 != self.cache.cached_len(slot)):
                raise MXNetError(
                    "draft cache desync: target slot %r (cached %d) vs "
                    "draft slot %r (cached %d)"
                    % (slot, self.cache.cached_len(slot), dslot,
                       self.draft_cache.cached_len(dslot)
                       if dslot is not None else -1))
        return slot

    def _chunk_bucket(self, remaining):
        """Bucket for one prefill chunk: the smallest that fits, else
        the largest (a further chunk follows — max buckets are page
        multiples, so the next offset stays page-aligned)."""
        for b in self.config.buckets:
            if remaining <= b:
                return b
        return max(self.config.buckets)

    def prefill(self, slot, prompt_tokens):
        """Run the bucketed prefill for ``slot``; returns
        ``(first_token, last_logits)``.

        Only the *uncached suffix* is computed: prompt positions covered
        by prefix-cache hit pages (``cache.cached_len``) are skipped,
        and the rest runs in page-aligned chunks through the per-bucket
        offset-taking executables — one chunk for a classic in-bucket
        prompt, several max-bucket chunks for a resumed transcript
        longer than the largest bucket.  Afterwards the slot's full
        prompt pages are published into the prefix index for future
        admissions."""
        import numpy as np
        import jax.numpy as jnp

        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        p = int(prompt.shape[0])
        cached = self.cache.cached_len(slot)
        if not 0 <= cached < p:
            raise MXNetError("prefill: cached prefix %d outside prompt "
                             "of %d tokens" % (cached, p))
        if self.config.kv_quant:
            # chaos site: a fault here fails THIS request before any of
            # its quantized pages/scales are written, so survivors'
            # pages and scale rows stay consistent
            from ..testing import faults

            faults.inject("kv_quant")
        if self.cache.n_window:
            # chaos site: fail before any ring row is written — the
            # slot's ring still holds only rows whose gather labels fall
            # outside every future mask, so survivors (and this slot's
            # re-admission) see a consistent ring
            from ..testing import faults

            faults.inject("kv_window")
        first = last_logits = None
        off = cached
        while off < p:
            bucket = self._chunk_bucket(p - off)
            n = min(p - off, bucket)
            toks = np.zeros((1, bucket), np.int32)
            toks[0, :n] = prompt[off:off + n]
            self.cache.ensure_writable(slot, off, n)
            args = (self.params, jnp.asarray(toks),
                    jnp.asarray(n, jnp.int32),
                    jnp.asarray(off, jnp.int32),
                    self.cache.table_row(slot)) \
                + ((jnp.asarray(slot, jnp.int32),)
                   if self.cache.hybrid else ()) \
                + self._pool_args(self.cache)
            out = self._dispatch("prefill_%d" % bucket, args)
            first, last_logits = out[0], out[1]
            self._store_pools(self.cache, out[2:])
            off += n
            self.cache.lengths[slot] = off
        first = int(first)
        self._slot_tokens[slot] = first
        self._slot_history[slot] = [int(t) for t in prompt] + [first]
        prompt_list = [int(t) for t in prompt]
        self.cache.register_prefix(slot, prompt_list)
        if self._draft_mode == "model":
            self._draft_ingest(slot, prompt)
            self.draft_cache.register_prefix(slot, prompt_list)
        return first, np.asarray(last_logits)

    def _draft_ingest(self, slot, prompt):
        """Teacher-force the prompt through the draft executable in
        W-token chunks so the draft cache holds the same positions the
        target prefill just wrote.  The single scan executable serves
        both ingest and propose (``n_feed`` switches the mode), keeping
        the executable count frozen.  Rows a chunk writes past its feed
        horizon — and the rows written for *other* active slots, whose
        ``n_feed`` is 0 — are junk beyond each slot's committed length;
        the next draft call overwrites those exact positions before any
        validity mask admits them."""
        import numpy as np
        import jax.numpy as jnp

        cfg = self.config
        w = cfg.spec_window
        p = int(prompt.shape[0])
        # prefix hits skip ingestion too: the draft's hit pages already
        # hold draft-model KV for the cached positions (same chain, same
        # lockstep publication), and alloc left lengths at cached_len
        cached = self.draft_cache.cached_len(slot)
        self.draft_cache.ensure_writable(slot, cached, p - cached)
        for off in range(cached, p, w):
            chunk = prompt[off:off + w]
            toks = np.zeros((cfg.slots, w), np.int32)
            toks[slot, :len(chunk)] = chunk
            n_feed = np.zeros((cfg.slots,), np.int32)
            n_feed[slot] = len(chunk)
            args = (self.draft_params, jnp.asarray(toks),
                    jnp.asarray(n_feed), self.draft_cache.device_lengths(),
                    self.draft_cache.device_tables()) \
                + self._pool_args(self.draft_cache)
            out = self._dispatch("draft", args)
            self._store_pools(self.draft_cache, out[1:])
            self.draft_cache.lengths[slot] = off + len(chunk)

    def step(self):
        """Advance every active slot one token with the single decode
        executable; returns ``(tokens, logits)`` where ``tokens`` maps
        slot -> emitted token id and ``logits`` is the (slots, vocab)
        array (inactive rows are garbage by design)."""
        import numpy as np
        import jax.numpy as jnp

        cfg = self.config
        self._pre_dispatch(1)
        tokens = np.zeros((cfg.slots,), np.int32)
        for slot, tok in self._slot_tokens.items():
            tokens[slot] = tok
        args = (self.params, jnp.asarray(tokens),
                self.cache.device_lengths(), self.cache.device_tables()) \
            + self._pool_args(self.cache)
        out = self._dispatch("decode", args)
        next_toks, logits = out[0], out[1]
        self._store_pools(self.cache, out[2:])
        next_np = np.asarray(next_toks)
        out = {}
        for slot in list(self._slot_tokens):
            self.cache.lengths[slot] += 1
            tok = int(next_np[slot])
            self._slot_tokens[slot] = tok
            if slot in self._slot_history:
                self._slot_history[slot].append(tok)
            out[slot] = tok
        return out, np.asarray(logits)

    def spec_step(self, limits=None):
        """One speculative step for every active slot: draft proposes K
        tokens, ONE fixed-shape verify teacher-forces all ``W = K + 1``
        rows through the target, and greedy acceptance commits the
        longest agreeing prefix (1..W tokens — always at least one, the
        target's own greedy continuation, so progress is unconditional).

        ``limits`` (slot -> int) caps how many tokens a slot may commit
        this step (the scheduler passes ``max_new - emitted`` so a slot
        never overruns its page reservation).  Returns slot ->
        ``[committed tokens]``, bit-identical to what the same number of
        :meth:`step` calls would have emitted — exactness of the verify
        kernel makes acceptance a pure integer comparison.

        Both caches advance ``W`` rows then roll back the rejected
        suffix via :meth:`PagedKVCache.truncate`, so target and draft
        lengths stay equal and every retained row's KV belongs to a
        committed token.
        """
        import numpy as np
        import jax.numpy as jnp

        cfg = self.config
        if not cfg.spec_k:
            raise MXNetError("spec_step on a session with spec_k == 0 — "
                             "set MXNET_SERVE_SPEC_K / ServeConfig.spec_k")
        out = {}
        if not self._slot_tokens:
            return out
        w, k = cfg.spec_window, cfg.spec_k
        active = sorted(self._slot_tokens)
        self._pre_dispatch(w)
        tokens = np.zeros((cfg.slots, w), np.int32)
        for slot, tok in self._slot_tokens.items():
            tokens[slot, 0] = tok
        if self._draft_mode == "model":
            dtoks = np.zeros((cfg.slots, w), np.int32)
            dtoks[:, 0] = tokens[:, 0]
            n_feed = np.ones((cfg.slots,), np.int32)
            args = (self.draft_params, jnp.asarray(dtoks),
                    jnp.asarray(n_feed), self.draft_cache.device_lengths(),
                    self.draft_cache.device_tables()) \
                + self._pool_args(self.draft_cache)
            res = self._dispatch("draft", args)
            self._store_pools(self.draft_cache, res[1:])
            tokens[:, 1:] = np.asarray(res[0])[:, :k]
        else:
            for slot in active:
                tokens[slot, 1:] = self._ngram_propose(slot, k)
        lims = {}
        for slot in active:
            limit = w
            if limits is not None:
                limit = max(1, min(w, int(limits.get(slot, w))))
            lims[slot] = limit
        args = (self.params, jnp.asarray(tokens),
                self.cache.device_lengths(), self.cache.device_tables())
        if self.cache.n_ssm:
            # the same per-slot caps ride into the executable: the
            # in-graph acceptance recomputation must reach the exact c
            # the commit loop below reaches, or the committed SSM state
            # would belong to a different prefix (inactive slots cap at
            # 1; their state is garbage until alloc re-zeroes it)
            lim_arr = np.ones((cfg.slots,), np.int32)
            for slot, limit in lims.items():
                lim_arr[slot] = limit
            args += (jnp.asarray(lim_arr),)
        args += self._pool_args(self.cache)
        res = self._dispatch("verify", args)
        self._store_pools(self.cache, res[2:])
        greedy = np.asarray(res[0])
        self._spec_stats["verify_steps"] += 1
        for slot in active:
            limit = lims[slot]
            # commit greedy[:c]: row 0 unconditionally, then one more
            # per proposal the target's previous row agreed with
            c = 1
            while c < limit and tokens[slot, c] == greedy[slot, c - 1]:
                c += 1
            committed = [int(t) for t in greedy[slot, :c]]
            self.cache.lengths[slot] += w
            self.cache.truncate(slot, w - c)
            if self.draft_cache is not None:
                self.draft_cache.lengths[slot] += w
                self.draft_cache.truncate(slot, w - c)
            self._slot_tokens[slot] = committed[-1]
            self._slot_history[slot].extend(committed)
            # proposals past the commit limit never had a chance, so
            # they don't count against the draft's acceptance rate
            self._spec_stats["slot_steps"] += 1
            self._spec_stats["proposed"] += limit - 1
            self._spec_stats["accepted"] += c - 1
            self._spec_stats["committed"] += c
            out[slot] = committed
        return out

    def _ngram_propose(self, slot, k, max_n=3):
        """Prompt-lookup draft: match the longest suffix n-gram of the
        slot's history (prompt + committed tokens, ending at the pending
        feed token) against an earlier occurrence and propose its
        continuation; shortfall pads with the last token.  Zero
        executables, zero params — the fallback draft."""
        hist = self._slot_history.get(slot) or [0]
        for n in range(min(max_n, len(hist) - 1), 0, -1):
            pat = hist[-n:]
            for start in range(len(hist) - n - 1, -1, -1):
                if hist[start:start + n] == pat:
                    cont = hist[start + n:start + n + k]
                    if cont:
                        out = list(cont)
                        while len(out) < k:
                            out.append(out[-1])
                        return out
        return [hist[-1]] * k

    def spec_report(self):
        """Speculation counters: ``acceptance_rate`` = accepted /
        proposed (proposals with a chance to commit), and
        ``tokens_per_verify_step`` = committed tokens per slot per
        verify dispatch (1..K+1 — the decode-throughput multiplier)."""
        rep = dict(self._spec_stats)
        rep["acceptance_rate"] = (
            rep["accepted"] / float(rep["proposed"])
            if rep["proposed"] else 0.0)
        rep["tokens_per_verify_step"] = (
            rep["committed"] / float(rep["slot_steps"])
            if rep["slot_steps"] else 0.0)
        return rep

    def _pre_dispatch(self, rows):
        """Per-boundary page upkeep before a decode/verify/draft
        dispatch writes ``rows`` KV rows per active slot: grow
        oversubscribed slots to cover their next rows (a no-op under
        reservation admission — the pages are already mapped) and cross
        the copy-on-write guard so no write can land in a shared or
        published page.  The scheduler preempts on the watermark BEFORE
        stepping, so growth here never finds an empty pool."""
        cfg = self.config
        for slot in sorted(self._slot_tokens):
            n = int(self.cache.lengths[slot])
            if cfg.oversub:
                self.cache.append_pages(slot, n + rows)
            self.cache.ensure_writable(slot, n, rows)
            if self.draft_cache is not None:
                dn = int(self.draft_cache.lengths[slot])
                if cfg.oversub:
                    self.draft_cache.append_pages(slot, dn + rows)
                self.draft_cache.ensure_writable(slot, dn, rows)

    def pages_short(self, rows=None):
        """Fresh pages the next decode boundary must obtain across all
        active slots — the scheduler compares this (plus its watermark)
        against :attr:`PagedKVCache.reclaimable_pages` to decide whether
        to preempt.  ``rows`` defaults to the step width (1, or the
        speculative window)."""
        if rows is None:
            rows = self.config.spec_window if self.config.spec_k else 1
        short = 0
        for slot in self._slot_tokens:
            short += self.cache.pages_short(
                slot, int(self.cache.lengths[slot]) + rows)
        return short

    def release(self, slot):
        self._slot_tokens.pop(slot, None)
        self._slot_history.pop(slot, None)
        self.cache.release(slot)
        if self.draft_cache is not None:
            self.draft_cache.release(slot)

    def active_slots(self):
        return sorted(self._slot_tokens)

    def reset_cold(self):
        """Return the session to a just-built state (replica rejoin
        after a supervisor eject): every slot released and the prefix
        index dropped, so the replica re-enters rotation COLD and warms
        its cache from live traffic — exactly what a restarted process
        would do, minus the recompile (the executables are immutable
        and carry no request state, so reusing them in-process models
        only the state a real restart loses)."""
        for slot in list(self._slot_tokens):
            try:
                self.release(slot)
            except MXNetError:
                pass
        self.cache.drop_prefix_index()
        if self.draft_cache is not None:
            self.draft_cache.drop_prefix_index()

    def state_report(self):
        """Occupancy snapshot for leak assertions: the gateway's
        cancellation tests take one before traffic and assert the
        post-traffic report is identical — freed slots, freed pages
        (refcount-aware: retained published-prefix pages are reported
        separately, since they deliberately survive release), and the
        draft cache in lockstep.  ``pool_bytes`` rides along to make
        "pool bytes return to baseline" observable (the pools are fixed
        buffers, so it must never move at all)."""
        out = {
            "active_slots": self.active_slots(),
            "free_slots": self.cache.free_slots,
            "free_pages": self.cache.free_pages,
            "retained_pages": self.cache.retained_pages,
            "pool_bytes": self.cache.pool_bytes(),
        }
        if self.draft_cache is not None:
            out["draft_free_slots"] = self.draft_cache.free_slots
            out["draft_free_pages"] = self.draft_cache.free_pages
        return out

    # -- accounting -------------------------------------------------------
    @property
    def executables(self):
        """name -> compiled executable.  Fixed set for the session's
        lifetime: prefill per bucket + decode, plus verify (and draft,
        for a parameterized proposer) when ``spec_k > 0``."""
        return {name: rec.compiled for name, rec in self._exes.items()}

    def memory_analysis(self, name="decode"):
        """Compile-time ``memory_analysis()`` numbers for one
        executable — the decode entry is the flat per-step watermark."""
        return dict(self._exes[name].memory)

    def params_bytes_at_rest(self):
        """Bytes the serving params occupy as held — quantized codes +
        scales under ``config.quant``, full precision otherwise (the
        bench shrink ratios compare the two)."""
        from ..quantize import at_rest_bytes

        return at_rest_bytes(self.params)

    def dequantized_params(self):
        """Plain float32 view of the serving params — for a quantized
        session, exactly the weight values the executables' in-graph
        dequantization computes (elementwise convert + multiply is
        bit-identical on host and in-graph).  Full-precision sessions
        get the params as-is."""
        from ..quantize import dequantize_params

        return dequantize_params(self.params)

    def guard_report(self):
        return {name: rec.guard.snapshot() for name, rec in
                self._exes.items()}

    def fallback_count(self):
        return sum(rec.fallbacks for rec in self._exes.values())


def _layer_truncated(params, n):
    """Derive a draft from the target's own weights: its first ``n``
    decoder blocks plus the shared embedding / final-LN / head — the
    self-speculative "layer skip" draft.  ``n`` equal to the full depth
    yields an (expensive, always-accepting) identity draft, useful for
    exactness tests."""
    total = 0
    while "blk%d_attn_in_weight" % total in params:
        total += 1
    n = int(n)
    if not 1 <= n <= total:
        raise MXNetError(
            "draft layers:%d out of range (target has %d blocks)"
            % (n, total))
    keep = {"tok_embed_weight", "pos_embed", "final_ln_gamma",
            "final_ln_beta", "lm_head_weight", "lm_head_bias"}
    out = {}
    for key, val in params.items():
        if key in keep or (key.startswith("blk")
                           and int(key[3:].split("_", 1)[0]) < n):
            out[key] = val
    return out


def _num_heads_from_symbol(symbol):
    """Pull ``num_heads`` out of a saved symbol's MultiHeadAttention
    node, if the checkpoint recorded one."""
    try:
        graph = json.loads(symbol.tojson())
    except Exception:
        return None
    for node in graph.get("nodes", []):
        op = (node.get("op") or "").lower()
        if "multiheadattention" in op.replace("_", ""):
            attrs = node.get("attrs") or node.get("param") or {}
            if "num_heads" in attrs:
                try:
                    return int(attrs["num_heads"])
                except (TypeError, ValueError):
                    pass
    return None
