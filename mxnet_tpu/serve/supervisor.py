"""Replicated serving with deadline-supervised failover and load
shedding.

Training survives preemption, divergence, hangs, and dead peers; this
module gives the *serving* stack the same posture.  A
:class:`ReplicaSet` runs N :class:`~mxnet_tpu.serve.InferenceSession`
replicas — in-process, same :class:`~mxnet_tpu.serve.ServeConfig`,
shared checkpoint, in a real deployment each on its own device slice —
behind a dispatcher, and treats replica failure and overload as the
steady state:

* **Deadline-supervised liveness.** Every replica is driven at decode
  boundaries through its own :class:`~mxnet_tpu.serve.Scheduler` in
  tick form; a per-replica watchdog (the PR 3
  :class:`~mxnet_tpu.health.StepWatchdog` reused verbatim, kicked once
  per replica boundary) trips when a replica makes no progress for
  ``MXNET_SERVE_STEP_TIMEOUT_S`` — the asynchronously delivered
  :class:`~mxnet_tpu.base.StepHung` lands in the supervisor's step
  loop, and the wedged replica is marked dead.  A replica that raises
  out of its step loop or is chaos-killed (``serve_replica_kill``) dies
  the same way.

* **Failover is the PR 14 resume path.** A dead replica's in-flight
  requests are drained and re-admitted on survivors as *parked*
  requests: their transcript (prompt + committed tokens) re-prefills
  deterministically and the replayed token is asserted equal to the
  last committed one — so every completed response is bit-identical to
  a never-failed run.  Requests the dead replica had queued but not yet
  prefilled re-enter the dispatcher queue with their original arrival
  seniority.

* **Overload protection.** The dispatcher holds a bounded admission
  queue with deadline-aware shedding: a request is refused with a
  typed :class:`ServeOverloaded` when the queue is full, when its
  deadline (``MXNET_SERVE_DEADLINE_MS``) lapses while it queues, or
  when the queue's *projected* TTFT — observed TTFT EMA scaled by
  queue depth over live capacity — already exceeds its budget.  This
  extends PR 14's SLO admission from "order by" to "refuse beyond".

* **Circuit breaker + cold rejoin.** ``MXNET_SERVE_BREAKER_K``
  consecutive step faults eject a replica; an ejected replica is
  probed for rejoin under exponential backoff (``serve_rejoin`` fault
  site), and on success rejoins COLD — slots empty, prefix index
  dropped via :meth:`InferenceSession.reset_cold` — then warms its
  prefix cache from live traffic, exactly like a restarted process.

* **The last replica dying raises** a typed :class:`ServeUnavailable`
  (outstanding requests are failed with the same typed error) instead
  of hanging or silently dropping work.

Every run that sheds, kills, or rejoins writes an incident artifact
(``serve-incident-<pid>-<n>.json`` under ``MXNET_HEALTH_DIR``) with the
per-replica timeline — deaths, failover drains, shed counts, rejoin
probes; pretty-print it with ``tools/diagnose.py``.

This is the robustness substrate ROADMAP item 1's network gateway and
router sit on: everything above the dispatcher can stay stateless
because everything below it already guarantees drain-and-replay.
"""
from __future__ import annotations

import bisect
import json
import os
import tempfile
import time

from ..base import MXNetError, StepHung, get_env, logger
from ..health import StepWatchdog
from ..testing import faults
from .scheduler import Scheduler, mark_cancelled
from .session import InferenceSession

__all__ = ["ReplicaSet", "ServeOverloaded", "ServeUnavailable"]


class ServeOverloaded(MXNetError):
    """A request the dispatcher refused: queue full, deadline lapsed in
    queue, or projected TTFT beyond the deadline budget.  Typed so
    callers (and the shed accounting) can tell load shedding apart from
    faults."""

    def __init__(self, msg, rid=None, reason=""):
        super().__init__(msg)
        self.rid = rid
        self.reason = reason


class ServeUnavailable(MXNetError):
    """Every replica is dead with work outstanding.  Raised instead of
    hanging; the outstanding requests are failed with this same typed
    error first, so accounting never loses them."""

    def __init__(self, msg, replicas=0, outstanding=0):
        super().__init__(msg)
        self.replicas = replicas
        self.outstanding = outstanding


class _Replica(object):
    """One replica's supervisor-side record."""

    __slots__ = ("index", "session", "scheduler", "state", "faults",
                 "deaths", "probe_at", "probe_backoff_s")

    def __init__(self, index, session, policy):
        self.index = index
        self.session = session
        self.scheduler = Scheduler(session, policy=policy)
        self.state = "live"
        self.faults = 0       # consecutive step faults (breaker input)
        self.deaths = 0
        self.probe_at = 0.0
        self.probe_backoff_s = 0.0

    @property
    def headroom(self):
        return self.session.config.slots - self.scheduler.load


class ReplicaSet(object):
    """N in-process session replicas behind a shedding dispatcher.

    Build from shared weights (``ReplicaSet(params, num_heads,
    config=...)`` compiles one session per replica) or hand it
    pre-built identical-config ``sessions=[...]`` — identical configs
    deliberately share recompile guards, so the executables-per-replica
    count stays frozen either way.  Drive it exactly like a
    :class:`Scheduler`: ``run(requests, followup=...)`` returns
    ``(requests, makespan_s)`` and the result feeds
    :func:`~mxnet_tpu.serve.summarize`.
    """

    def __init__(self, params=None, num_heads=None, config=None,
                 replicas=None, sessions=None, policy="continuous",
                 deadline_ms=None, step_timeout_s=None, breaker_k=None,
                 queue_cap=None, rejoin_backoff_s=0.05,
                 rejoin_backoff_max_s=5.0, incident_dir=None):
        if sessions:
            self.replicas = [_Replica(i, s, policy)
                             for i, s in enumerate(sessions)]
        else:
            n = int(replicas) if replicas is not None else \
                get_env("MXNET_SERVE_REPLICAS", 2, int)
            if n < 1:
                raise MXNetError("ReplicaSet needs >= 1 replica (got %d)"
                                 % n)
            if params is None or num_heads is None:
                raise MXNetError("ReplicaSet needs params + num_heads "
                                 "(or pre-built sessions=)")
            self.replicas = [
                _Replica(i, InferenceSession(params, num_heads,
                                             config=config), policy)
                for i in range(n)]
        cfgs = {r.session.config for r in self.replicas}
        if len(cfgs) != 1:
            raise MXNetError(
                "ReplicaSet replicas must share one ServeConfig "
                "(failover re-prefill is only bit-exact across identical "
                "capacity/precision); got %d distinct configs" % len(cfgs))
        self.config = self.replicas[0].session.config
        self.deadline_ms = float(deadline_ms) if deadline_ms is not None \
            else get_env("MXNET_SERVE_DEADLINE_MS", 0.0, float)
        self.step_timeout_s = float(step_timeout_s) \
            if step_timeout_s is not None \
            else get_env("MXNET_SERVE_STEP_TIMEOUT_S", 0.0, float)
        self.breaker_k = int(breaker_k) if breaker_k is not None \
            else get_env("MXNET_SERVE_BREAKER_K", 1, int)
        if self.breaker_k < 1:
            raise MXNetError("breaker K must be >= 1 (got %d)"
                             % self.breaker_k)
        total_slots = self.config.slots * len(self.replicas)
        self.queue_cap = int(queue_cap) if queue_cap is not None \
            else 4 * total_slots
        self.rejoin_backoff_s = float(rejoin_backoff_s)
        self.rejoin_backoff_max_s = float(rejoin_backoff_max_s)
        self._incident_dir = incident_dir or get_env(
            "MXNET_HEALTH_DIR", tempfile.gettempdir(), str)
        self.events = []
        self.counters = {"deaths": 0, "failover_requests": 0, "shed": 0,
                         "shed_queue": 0, "shed_deadline": 0,
                         "cancelled": 0, "rejoins": 0, "probes_failed": 0,
                         "dispatch_faults": 0}
        self.incident_path = None
        self._watchdog = None
        self._user_followup = None
        self._ema_ttft_s = 0.0
        self._t0 = None
        self._waiting = []   # trace requests not yet past their arrival_s
        self._queue = []     # admitted, not yet assigned (arrival order)
        self._failover = []  # drained resumable requests awaiting a home
        self._all = []

    # -- introspection ----------------------------------------------------
    def live_replicas(self):
        return [r for r in self.replicas if r.state == "live"]

    def executables_per_replica(self):
        """Compiled-executable count per replica — frozen for the set's
        lifetime; the chaos soak asserts it never moves across kills,
        failovers, and rejoins."""
        return [len(r.session.executables) for r in self.replicas]

    def _now(self):
        return time.perf_counter() - self._t0

    def _event(self, event, replica=None, **detail):
        rec = {"t": round(self._now(), 4), "event": event,
               "replica": replica}
        rec.update(detail)
        self.events.append(rec)

    # -- dispatcher -------------------------------------------------------
    def _submit(self, req, now):
        """One request enters the dispatcher: cross the
        ``serve_dispatch`` fault boundary (a fault fails THAT request,
        typed), enforce the bounded queue, stamp the deadline."""
        self._all.append(req)
        try:
            faults.inject("serve_dispatch")
        except faults.WorkerKilled as exc:
            self._fail_dispatch(req, exc)
            return
        except Exception as exc:  # mxlint: disable=MX008 — a dispatch
            # fault (typed or not) fails THAT request; the dispatcher
            # itself must keep admitting the rest of the trace
            self._fail_dispatch(req, exc)
            return
        if len(self._queue) >= self.queue_cap:
            self._shed(req, "admission queue full (cap %d)"
                       % self.queue_cap, kind="queue")
            return
        budget_ms = float(getattr(req, "deadline_ms", 0.0)
                          or self.deadline_ms)
        req._deadline_s = (req.arrival_s + budget_ms / 1000.0) \
            if budget_ms > 0 else None
        bisect.insort(self._queue,
                      ((req.arrival_s, req.rid), req))

    def _fail_dispatch(self, req, exc):
        req.failed = True
        req.error = "%s: %s" % (type(exc).__name__, exc)
        self.counters["dispatch_faults"] += 1
        self._event("dispatch_fault", rid=req.rid, detail=req.error)

    def _shed(self, req, why, kind):
        """Refuse one request typed.  ``kind`` splits the accounting:
        ``"queue"`` (the bounded admission queue overflowed — capacity)
        vs ``"deadline"`` (its TTFT budget lapsed or is projected to —
        latency), which ``summarize()`` and the incident artifact keep
        as separate counters."""
        exc = ServeOverloaded(
            "request %d shed: %s" % (req.rid, why), rid=req.rid,
            reason=why)
        req.failed = True
        req.shed = True
        req.shed_kind = kind
        req.error = "%s: %s" % (type(exc).__name__, exc)
        self.counters["shed"] += 1
        self.counters["shed_" + kind] += 1
        self._event("shed", rid=req.rid, detail=why, kind=kind)

    def _live_capacity(self):
        return sum(max(r.headroom, 0) for r in self.live_replicas()) \
            + self.config.slots * len(self.live_replicas())

    def _shed_pass(self, now):
        """Deadline-aware shedding over the queued (unassigned)
        requests: refuse what already blew its budget, and what the
        queue's *projected* TTFT says cannot make it — the observed
        TTFT EMA scaled by queue depth over live capacity.  Refusing
        early spends the slots on requests that still count."""
        if not self._queue:
            return
        slots = max(self.config.slots * len(self.live_replicas()), 1)
        keep = []
        for pos, (key, req) in enumerate(self._queue):
            deadline = getattr(req, "_deadline_s", None)
            if deadline is None:
                keep.append((key, req))
                continue
            if now >= deadline:
                self._shed(req, "deadline lapsed after %.0f ms in queue"
                           % ((now - req.arrival_s) * 1e3),
                           kind="deadline")
                continue
            projected = now + self._ema_ttft_s * (1.0 + pos / slots)
            if self._ema_ttft_s > 0.0 and projected > deadline:
                self._shed(req, "projected TTFT %.0f ms exceeds the "
                           "%.0f ms budget"
                           % ((projected - req.arrival_s) * 1e3,
                              (deadline - req.arrival_s) * 1e3),
                           kind="deadline")
                continue
            keep.append((key, req))
        self._queue = keep

    def _assign(self):
        """Hand queued requests to live replicas with slot headroom —
        least-loaded first, ties to the lowest replica id, so identical
        traffic lands identically run over run."""
        while self._queue:
            live = [r for r in self.live_replicas() if r.headroom > 0]
            if not live:
                return
            best = min(live, key=lambda r: (-r.headroom, r.index))
            _, req = self._queue.pop(0)
            best.scheduler.submit(req)

    def _place_failover(self):
        """Re-admit drained requests on survivors via the park/resume
        path — the scheduler re-prefills their transcript and asserts
        the replayed token against the last committed one, so the
        completed stream is bit-identical to a never-failed run."""
        while self._failover:
            live = self.live_replicas()
            if not live:
                return
            best = min(live, key=lambda r: (r.scheduler.load, r.index))
            req = self._failover.pop(0)
            best.scheduler.submit(req, parked=True)
            self.counters["failover_requests"] += 1
            self._event("failover", replica=best.index, rid=req.rid,
                        committed=len(req.tokens))

    # -- replica lifecycle ------------------------------------------------
    def _eject(self, rep, reason, now):
        rep.state = "dead"
        rep.deaths += 1
        rep.faults = 0
        resumable, fresh = rep.scheduler.drain()
        self._failover.extend(resumable)
        for req in fresh:
            # queued-not-yet-prefilled work keeps arrival seniority
            bisect.insort(self._queue, ((req.arrival_s, req.rid), req))
        rep.probe_backoff_s = max(self.rejoin_backoff_s, 1e-3)
        rep.probe_at = now + rep.probe_backoff_s
        self.counters["deaths"] += 1
        self._event("death", replica=rep.index, detail=reason,
                    drained_resumable=len(resumable),
                    drained_fresh=len(fresh),
                    committed=[len(r.tokens) for r in resumable])
        logger.warning("serve replica %d marked dead (%s): drained %d "
                       "in-flight + %d queued requests for failover",
                       rep.index, reason, len(resumable), len(fresh))

    def _probe(self, rep, now):
        """One rejoin probe of an ejected replica.  A fault at
        ``serve_rejoin`` fails the probe and doubles the backoff; on
        success the replica rejoins cold (slots empty, prefix index
        dropped) and warms its cache from live traffic."""
        try:
            faults.inject("serve_rejoin")
        except (Exception, faults.WorkerKilled) as exc:  # mxlint: disable=MX008
            # a failed probe never escapes: the replica just stays dead
            # and the backoff doubles
            rep.probe_backoff_s = min(rep.probe_backoff_s * 2.0,
                                      self.rejoin_backoff_max_s)
            rep.probe_at = now + rep.probe_backoff_s
            self.counters["probes_failed"] += 1
            self._event("probe_failed", replica=rep.index,
                        detail="%s: %s" % (type(exc).__name__, exc),
                        next_backoff_s=round(rep.probe_backoff_s, 4))
            return
        rep.session.reset_cold()
        rep.scheduler.begin([], followup=self._on_finish, t0=self._t0)
        rep.state = "live"
        rep.faults = 0
        self.counters["rejoins"] += 1
        self._event("rejoin", replica=rep.index)
        logger.warning("serve replica %d rejoined cold after %d "
                       "death(s)", rep.index, rep.deaths)

    def _arm_watchdog(self):
        if self.step_timeout_s <= 0:
            return
        if self._watchdog is not None:
            self._watchdog.stop()

        def _stats():
            return {"replicas": [
                {"index": r.index, "state": r.state,
                 "load": r.scheduler.load, "faults": r.faults}
                for r in self.replicas]}

        self._watchdog = StepWatchdog(
            self.step_timeout_s, stats_cb=_stats,
            dump_dir=self._incident_dir).start()

    def _on_finish(self, req, now_s):
        """Every completion flows through here: the TTFT EMA feeds the
        projected-TTFT shed rule, and closed-loop followup requests
        re-enter through the dispatcher (bounded queue, shed rules,
        ``serve_dispatch``) instead of bypassing it."""
        if req.ttft_s >= 0.0:
            self._ema_ttft_s = req.ttft_s if self._ema_ttft_s == 0.0 \
                else 0.7 * self._ema_ttft_s + 0.3 * req.ttft_s
        if self._user_followup is not None:
            nxt = self._user_followup(req, now_s)
            if nxt is not None:
                for r in (nxt if isinstance(nxt, (list, tuple))
                          else [nxt]):
                    self._submit(r, now_s)
        return None

    # -- the supervision loop ---------------------------------------------
    def _outstanding(self, waiting):
        return bool(waiting or self._queue or self._failover
                    or any(r.scheduler.outstanding
                           for r in self.live_replicas()))

    # -- tick form (the gateway's dispatch-thread hook) --------------------
    def begin(self, requests=(), followup=None, t0=None):
        """Arm the set for tick-form driving without stepping it: reset
        counters/events, arm every replica's scheduler on one shared
        clock, start the watchdog.  ``requests`` is an optional
        ``arrival_s``-stamped trace; mid-run work enters via
        :meth:`submit`.  Pair with :meth:`tick` and :meth:`finish` —
        :meth:`run` is exactly that loop."""
        self._t0 = time.perf_counter() if t0 is None else t0
        self._user_followup = followup
        self._waiting = sorted(requests,
                               key=lambda r: (r.arrival_s, r.rid))
        self._queue = []
        self._failover = []
        self._all = []
        self.events = []
        self.counters = {k: 0 for k in self.counters}
        self.incident_path = None
        for rep in self.replicas:
            rep.scheduler.begin([], followup=self._on_finish, t0=self._t0)
        self._arm_watchdog()
        return self

    def now(self):
        return self._now()

    @property
    def outstanding(self):
        """True while unfinished requests remain anywhere."""
        return self._outstanding(self._waiting)

    def submit(self, request):
        """One request enters the dispatcher mid-run (the gateway
        path): crosses ``serve_dispatch``, the bounded queue, and the
        deadline stamp exactly like a trace arrival."""
        self._submit(request, self._now())

    def cancel(self, rid, reason="cancelled by client"):
        """Cancel one request wherever it lives — the dispatcher's
        waiting/queued/failover holdings, or a live replica's scheduler
        (which releases its slot refcount-aware at the decode
        boundary).  A late cancel of a finished or unknown request is a
        no-op; returns True when something was cancelled.  Call between
        ticks — the tick loop owns the replicas."""
        def _take(seq, get=lambda item: item):
            for i, item in enumerate(seq):
                if get(item).rid == rid and not get(item).finished:
                    del seq[i]
                    return get(item)
            return None

        req = _take(self._waiting)
        if req is not None:
            self._all.append(req)  # never reached _submit's accounting
        else:
            req = _take(self._queue, get=lambda kv: kv[1]) \
                or _take(self._failover)
        if req is not None:
            mark_cancelled(req, reason)
            self.counters["cancelled"] += 1
            self._event("cancel", rid=rid, detail=reason)
            return True
        for rep in self.replicas:
            if rep.state == "live" and rep.scheduler.cancel(rid, reason):
                self.counters["cancelled"] += 1
                self._event("cancel", replica=rep.index, rid=rid,
                            detail=reason)
                return True
        return False

    def tick(self):
        """One supervision iteration; returns True when any replica
        made decode-boundary progress.  Raises
        :class:`ServeUnavailable` when every replica is dead with work
        outstanding."""
        now = self._now()
        # 1) arrivals enter the dispatcher
        while self._waiting and self._waiting[0].arrival_s <= now:
            self._submit(self._waiting.pop(0), now)
        # 2) overload protection over the queued tail
        self._shed_pass(now)
        # 3) queued work to replicas with headroom
        self._assign()
        # 4) one decode boundary per live replica
        progressed = self._tick_replicas()
        # 5) total outage is a typed failure, never a hang
        if not self.live_replicas() \
                and self._outstanding(self._waiting):
            self._raise_unavailable(self._waiting)
        # 6) drained requests re-admit on survivors
        self._place_failover()
        # 7) ejected replicas probe for rejoin (backoff-gated)
        now = self._now()
        for rep in self.replicas:
            if rep.state == "dead" and now >= rep.probe_at:
                self._probe(rep, now)
        return progressed

    def finish(self):
        """Stop the watchdog and persist the incident artifact — the
        tail of :meth:`run`, called by tick-form drivers when their
        loop ends (the gateway's drain path)."""
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None
        self._write_incident()

    def run(self, requests, followup=None):
        """Serve ``requests`` (an ``arrival_s``-stamped trace) across
        the replica set to completion; returns ``(requests,
        makespan_s)`` with followup-generated requests included.
        Raises :class:`ServeUnavailable` if every replica dies with
        work outstanding."""
        self.begin(requests, followup=followup)
        try:
            while True:
                progressed = self.tick()
                if not self.outstanding:
                    break
                if not progressed:
                    # idle: waiting on an arrival or a rejoin probe
                    time.sleep(0.002)
        finally:
            self.finish()
        return self._all, self._now()

    def _tick_replicas(self):
        """Cross ``serve_replica_kill`` and run one scheduler tick per
        live replica.  Death modes: ``kill``/``StepHung`` eject
        immediately; a raise counts against the circuit breaker and
        ejects at K consecutive faults.  A clean tick resets the
        breaker."""
        progressed = False
        for rep in self.replicas:
            if rep.state != "live":
                continue
            if self._watchdog is not None:
                self._watchdog.kick("serve replica %d decode boundary"
                                    % rep.index)
            now = self._now()
            try:
                faults.inject("serve_replica_kill")
                if rep.scheduler.outstanding:
                    rep.scheduler.tick(wait=False)
                    progressed = True
                rep.faults = 0
            except faults.WorkerKilled as exc:
                self._eject(rep, "chaos-killed: %s" % exc, now)
            except StepHung as exc:
                # the watchdog fired into this thread mid-tick; its
                # daemon thread has exited — re-arm for the survivors
                self._eject(rep, "watchdog: no decode-boundary progress "
                            "for %.1fs (MXNET_SERVE_STEP_TIMEOUT_S)"
                            % self.step_timeout_s, now)
                self._arm_watchdog()
            except MXNetError as exc:
                rep.faults += 1
                self._event("breaker_fault", replica=rep.index,
                            detail="%s: %s" % (type(exc).__name__, exc),
                            consecutive=rep.faults)
                if rep.faults >= self.breaker_k:
                    self._eject(rep, "circuit breaker: %d consecutive "
                                "step fault(s), K=%d"
                                % (rep.faults, self.breaker_k), now)
        return progressed

    def _raise_unavailable(self, waiting):
        outstanding = list(waiting) + [req for _, req in self._queue] \
            + list(self._failover)
        waiting.clear()
        self._queue = []
        self._failover = []
        exc = ServeUnavailable(
            "all %d replicas are dead with %d request(s) outstanding — "
            "the incident timeline is in %r (tools/diagnose.py)"
            % (len(self.replicas), len(outstanding),
               self._incident_dir),
            replicas=len(self.replicas), outstanding=len(outstanding))
        for req in outstanding:
            req.failed = True
            req.error = "%s: %s" % (type(exc).__name__, exc)
        raise exc

    # -- incident artifact ------------------------------------------------
    def incident_report(self):
        """JSON-able incident summary: counters plus the chronological
        per-replica timeline."""
        return {
            "kind": "mxnet_tpu-serve-incident",
            "pid": os.getpid(),
            "time": time.time(),
            "replicas": len(self.replicas),
            "slots_per_replica": self.config.slots,
            "deadline_ms": self.deadline_ms,
            "step_timeout_s": self.step_timeout_s,
            "breaker_k": self.breaker_k,
            "counters": dict(self.counters),
            "replica_states": [
                {"index": r.index, "state": r.state, "deaths": r.deaths}
                for r in self.replicas],
            "timeline": list(self.events),
        }

    def _write_incident(self):
        """Persist the timeline when anything noteworthy happened —
        a clean run writes nothing."""
        if not self.events:
            return
        payload = self.incident_report()
        try:
            os.makedirs(self._incident_dir, exist_ok=True)
            path = os.path.join(
                self._incident_dir, "serve-incident-%d-%d.json"
                % (os.getpid(), int(time.time() * 1e3)))
            with open(path, "w") as f:
                json.dump(payload, f, indent=2)
            self.incident_path = path
        except OSError as e:  # diagnostics must never mask the run
            logger.warning("serve incident artifact write failed: %s", e)
