"""The ``mx.sym`` namespace — generated from the op registry, like the
reference's ``_init_symbol_module`` (``python/mxnet/symbol/op.py``)."""
from __future__ import annotations

import sys as _sys

from ..ops import registry as _registry
from .symbol import (Symbol, Variable, var, Group, load, load_json, _apply)

_RESERVED = {"var", "load"}


def _make_sym_func(name):
    def sym_func(*args, **kwargs):
        node_name = kwargs.pop("name", None)
        attrs = {}
        sym_inputs = list(args)
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                # named symbol inputs (data=..., weight=...) — order by the
                # op's declared argument names
                attrs.setdefault("__named__", {})[k] = v
            else:
                attrs[k] = v
        named = attrs.pop("__named__", {})
        if named:
            from ..ops.op_names import expected_inputs

            arg_names, aux_names = expected_inputs(name, attrs)
            ordered = []
            for an in list(arg_names) + list(aux_names):
                if an in named:
                    ordered.append(named.pop(an))
                elif sym_inputs:
                    ordered.append(sym_inputs.pop(0))
                else:
                    break
            sym_inputs = ordered + sym_inputs + list(named.values())
        return _apply(name, sym_inputs, attrs, name=node_name)

    sym_func.__name__ = name
    sym_func.__doc__ = _registry.get(name).describe()
    return sym_func


def _init_module():
    mod = _sys.modules[__name__]
    for name in _registry.list_ops():
        if name in _RESERVED:
            continue
        setattr(mod, name, _make_sym_func(name))


_init_module()


def zeros(shape, dtype="float32", **kwargs):
    return _apply("_zeros", [], {"shape": tuple(shape), "dtype": dtype})


def ones(shape, dtype="float32", **kwargs):
    return _apply("_ones", [], {"shape": tuple(shape), "dtype": dtype})


def arange(start, stop=None, step=1.0, repeat=1, dtype="float32", **kwargs):
    return _apply("_arange", [], {"start": start, "stop": stop, "step": step,
                                  "repeat": repeat, "dtype": dtype})
