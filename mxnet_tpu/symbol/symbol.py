"""Symbol — the declarative graph frontend.

TPU-native replacement for the reference's nnvm ``Symbol``
(``python/mxnet/symbol/symbol.py`` over ``nnvm::Symbol`` composition,
SURVEY.md §2.1 "nnvm").  A Symbol is a lightweight DAG of op applications
over named variables; ``bind``/``simple_bind`` lower the whole graph —
forward *and* backward — into a single jitted XLA computation
(:mod:`mxnet_tpu.executor`), which is the design stance of SURVEY.md §7
item 5: nnvm passes (PlanMemory, inplace, DetectInplaceAddTo) are replaced
by XLA's buffer assignment and fusion; the Gradient pass is replaced by
``jax.vjp`` over the traced program.

JSON save/load keeps the reference's checkpoint graph format
(``nodes``/``arg_nodes``/``heads`` — ``nnvm::pass::SaveJSON``) so
``prefix-symbol.json`` files round-trip.
"""
from __future__ import annotations

import json

from ..base import MXNetError
from ..ops import registry as _registry
from ..ops.op_names import expected_inputs

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json"]

def _auto_name(prefix):
    from .. import name as _name_mod

    return _name_mod.current().get(None, prefix)


class _Node:
    """One graph node: an op application, or a variable (op is None)."""

    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs", "aux_slots")

    def __init__(self, op, name, attrs, inputs, aux_slots=()):
        self.op = op                      # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs)
        self.inputs = inputs              # list[(Node, out_idx)]
        self.aux_slots = tuple(aux_slots)  # input positions that are aux vars
        if op is None:
            self.num_outputs = 1
        else:
            self.num_outputs = op.count_outputs(_registry.FrozenAttrs(self.attrs))

    @property
    def is_variable(self):
        return self.op is None


class Symbol:
    """A (multi-)output slice of a graph. Composable like the reference."""

    def __init__(self, outputs):
        # outputs: list[(Node, out_idx)]
        self._outputs = list(outputs)

    # -- composition --------------------------------------------------------
    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        for i in range(len(self._outputs)):
            yield self[i]

    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.num_outputs == 1:
                out.append(node.name + "_output" if not node.is_variable
                           else node.name)
            else:
                out.append("%s_output%d" % (node.name, idx))
        return out

    def _makeloss_outputs(self):
        """Output names produced by ``MakeLoss``/``make_loss`` heads —
        loss-only terms a metric must never score as predictions
        (reference ``src/operator/make_loss.cc`` semantics)."""
        out = []
        for name, (node, _idx) in zip(self.list_outputs(), self._outputs):
            if (not node.is_variable
                    and node.op.name in ("make_loss", "MakeLoss")):
                out.append(name)
        return out

    def _topo(self):
        """Topological order of all nodes reachable from the outputs."""
        seen, order = set(), []

        def visit(node):
            if id(node) in seen:
                return
            seen.add(id(node))
            for (inp, _) in node.inputs:
                visit(inp)
            order.append(node)

        for node, _ in self._outputs:
            visit(node)
        return order

    def _aux_node_ids(self):
        """ids of variable nodes consumed through an aux slot (one pass)."""
        aux = set()
        for n in self._topo():
            for pos, (src, _) in enumerate(n.inputs):
                if pos in n.aux_slots and src.is_variable:
                    aux.add(id(src))
        return aux

    def list_arguments(self):
        aux = self._aux_node_ids()
        return [n.name for n in self._topo()
                if n.is_variable and id(n) not in aux]

    def list_auxiliary_states(self):
        aux = self._aux_node_ids()
        return [n.name for n in self._topo()
                if n.is_variable and id(n) in aux]

    def get_internals(self):
        """All node outputs as one group (reference
        ``Symbol.get_internals``)."""
        outs = []
        for node in self._topo():
            for i in range(node.num_outputs):
                outs.append((node, i))
        return Symbol(outs)

    def attr_dict(self):
        return {n.name: {k: str(v) for k, v in n.attrs.items()}
                for n in self._topo() if n.attrs}

    # -- shape/type inference ----------------------------------------------
    def infer_shape(self, *args, **kwargs):
        arg_shapes, _, aux_shapes = self._infer(kwargs, key="shape")
        out_shapes = self._infer_outputs(kwargs)
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, **kwargs):
        """Dtype inference (reference per-op FInferType).

        Propagation rules: most ops are same-type (inputs promote via
        numpy rules and outputs follow); ``Cast`` and the random
        initializer ops take their ``dtype`` attr; integer-output ops
        (argmax/argsort/one_hot indices) keep the reference's
        float-output convention so no special case is needed.  Unknown
        parameter variables are back-filled from their consumer's
        resolved dtype (the reference's backward inference: conv weights
        take the data's dtype), then default to float32.
        """
        import numpy as np

        dtypes = {}  # var name -> np.dtype
        for k, v in kwargs.items():
            if v is not None:
                dtypes[k] = np.dtype(v)
        node_out = {}  # (node id) -> np.dtype

        _ATTR_DTYPE_OPS = {"Cast", "cast", "_zeros", "_ones", "_arange",
                           "zeros", "ones", "arange"}

        def resolve_once():
            changed = False
            for node in self._topo():
                if node.is_variable:
                    continue
                from_attr = node.op.name in _ATTR_DTYPE_OPS and \
                    "dtype" in node.attrs
                if from_attr:
                    dt = np.dtype(str(node.attrs["dtype"]))
                else:
                    known = []
                    for (src, _i) in node.inputs:
                        if src.is_variable:
                            if src.name in dtypes:
                                known.append(dtypes[src.name])
                        elif id(src) in node_out:
                            known.append(node_out[id(src)])
                    if not known:
                        continue
                    dt = known[0]
                    for other in known[1:]:
                        dt = np.promote_types(dt, other)
                if node_out.get(id(node)) != dt:
                    node_out[id(node)] = dt
                    changed = True
                # backward fill: unresolved variable inputs adopt dt —
                # except through attr-dtyped ops (Cast's output says
                # nothing about its input)
                if not from_attr:
                    for (src, _i) in node.inputs:
                        if src.is_variable and src.name not in dtypes:
                            dtypes[src.name] = dt
                            changed = True
            return changed

        for _ in range(3):  # DAG fixpoint: 2 passes suffice, 3 is safety
            if not resolve_once():
                break

        default = np.dtype("float32")
        arg_types = [dtypes.get(n, default)
                     for n in self.list_arguments()]
        aux_types = [dtypes.get(n, default)
                     for n in self.list_auxiliary_states()]
        out_types = []
        for (n, _i) in self._outputs:
            if n.is_variable:
                out_types.append(dtypes.get(n.name, default))
            else:
                out_types.append(node_out.get(id(n), default))
        return arg_types, out_types, aux_types

    def _infer(self, shape_kwargs, key="shape"):
        """Infer every argument/aux shape from the given input shapes by
        abstract evaluation (jax.eval_shape replaces the reference's
        InferShape pass, graph_executor.cc:565)."""
        import numpy as np

        arg_names = self.list_arguments()
        aux_names = self.list_auxiliary_states()
        known = dict(shape_kwargs)
        # variables whose shape must be derived: walk graph, evaluating ops
        # abstractly where all input shapes known; parameter shapes come
        # from op-specific inference below.
        shapes = _infer_param_shapes(self, known)
        arg_shapes = [shapes.get(n) for n in arg_names]
        aux_shapes = [shapes.get(n) for n in aux_names]
        return arg_shapes, None, aux_shapes

    def _infer_outputs(self, shape_kwargs):
        import jax
        import numpy as np

        shapes = _infer_param_shapes(self, dict(shape_kwargs))

        class _Spec:
            def __init__(self, shape):
                self.shape = tuple(shape)
                self.dtype = np.float32

        def trace():
            env = {}
            out = []
            for node in self._topo():
                if node.is_variable:
                    env[(id(node), 0)] = jax.numpy.zeros(
                        shapes[node.name], "float32")
                else:
                    ins = [env[(id(n), i)] for (n, i) in node.inputs]
                    attrs = dict(node.attrs)
                    if node.op.uses_train_mode:
                        attrs["__is_train__"] = False
                    if node.op.needs_rng:
                        ins = [jax.random.PRNGKey(0)] + ins
                    res = node.op.compute(_registry.FrozenAttrs(attrs), *ins)
                    if not isinstance(res, tuple):
                        res = (res,)
                    for i, r in enumerate(res):
                        env[(id(node), i)] = r
            return tuple(env[(id(n), i)] for (n, i) in self._outputs)

        out_spec = jax.eval_shape(trace)
        return [tuple(int(d) for d in s.shape) for s in out_spec]

    # -- binding ------------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None,
                    stype_dict=None, group2ctx=None, shared_arg_names=None,
                    shared_exec=None, shared_buffer=None, **kwargs):
        from ..executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, kwargs,
                                     shared_exec=shared_exec)

    def bind(self, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        from ..executor import Executor

        return Executor._bind(self, ctx, args, args_grad, grad_req,
                              aux_states, shared_exec=shared_exec)

    # -- evaluation convenience --------------------------------------------
    def eval(self, ctx=None, **kwargs):
        shapes = {k: v.shape for k, v in kwargs.items()}
        ex = self.simple_bind(ctx, grad_req="null", **shapes)
        return ex.forward(is_train=False, **kwargs)

    # -- serialization ------------------------------------------------------
    def tojson(self):
        """Reference-compatible graph JSON (nodes/arg_nodes/heads)."""
        nodes_list = self._topo()
        node_idx = {id(n): i for i, n in enumerate(nodes_list)}
        nodes_json = []
        for n in nodes_list:
            nodes_json.append({
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                          for k, v in n.attrs.items()},
                "inputs": [[node_idx[id(src)], i, 0] for (src, i) in n.inputs],
            })
        arg_nodes = [i for i, n in enumerate(nodes_list) if n.is_variable]
        heads = [[node_idx[id(n)], i, 0] for (n, i) in self._outputs]
        return json.dumps({"nodes": nodes_json, "arg_nodes": arg_nodes,
                           "heads": heads,
                           "attrs": {"mxnet_tpu_version": "0.1.0"}}, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- operators ----------------------------------------------------------
    def _binary(self, other, op, scalar_op, rop=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if rop else (self, other)
            return _apply(op, [a, b], {})
        return _apply(scalar_op, [self], {"scalar": float(other)})

    def __add__(self, o): return self._binary(o, "elemwise_add", "_plus_scalar")
    def __radd__(self, o): return self._binary(o, "elemwise_add", "_plus_scalar")
    def __sub__(self, o): return self._binary(o, "elemwise_sub", "_minus_scalar")
    def __rsub__(self, o): return self._binary(o, "elemwise_sub", "_rminus_scalar", rop=True)
    def __mul__(self, o): return self._binary(o, "elemwise_mul", "_mul_scalar")
    def __rmul__(self, o): return self._binary(o, "elemwise_mul", "_mul_scalar")
    def __truediv__(self, o): return self._binary(o, "elemwise_div", "_div_scalar")
    def __rtruediv__(self, o): return self._binary(o, "elemwise_div", "_rdiv_scalar", rop=True)
    def __pow__(self, o): return self._binary(o, "elemwise_power", "_power_scalar")
    def __neg__(self): return _apply("negative", [self], {})

    def __getattr__(self, name):
        if name.startswith("_") or not _registry.exists(name):
            raise AttributeError(name)

        def method(*args, **kw):
            return _apply(name, [self] + [a for a in args
                                          if isinstance(a, Symbol)], kw)
        return method

    def __repr__(self):
        return "<Symbol %s>" % (self.name or self.list_outputs())


def _infer_param_shapes(sym, known):
    """Forward shape propagation with op-specific parameter inference —
    the equivalent of the reference's InferShape attr pass: given data
    shapes, derive weight/bias/aux shapes for layer ops."""
    env = {}     # (node id, out idx) -> shape
    shapes = {}  # var name -> shape

    for node in sym._topo():
        if node.is_variable:
            if node.name in known and known[node.name] is not None:
                shapes[node.name] = tuple(known[node.name])
            elif node.attrs.get("__shape__"):
                # Variable(shape=...) declared its own shape (reference
                # simple_bind honors the __shape__ attr)
                shapes[node.name] = tuple(
                    int(d) for d in node.attrs["__shape__"])
            continue
        # try to fill parameter-variable input shapes from op semantics
        _fill_param_shapes(node, env, shapes)
        in_shapes = []
        ok = True
        for (src, i) in node.inputs:
            if src.is_variable:
                s = shapes.get(src.name)
            else:
                s = env.get((id(src), i))
            if s is None:
                ok = False
                break
            in_shapes.append(s)
        if not ok:
            raise MXNetError(
                "infer_shape: cannot infer inputs of node %s" % node.name)
        out_shapes = _abstract_eval(node, in_shapes)
        for i, s in enumerate(out_shapes):
            env[(id(node), i)] = s
    return shapes


def _abstract_eval(node, in_shapes):
    import jax
    import numpy as np

    attrs = dict(node.attrs)
    if node.op.uses_train_mode:
        attrs["__is_train__"] = False

    def fn(*xs):
        ins = list(xs)
        if node.op.needs_rng:
            ins = [jax.random.PRNGKey(0)] + ins
        res = node.op.compute(_registry.FrozenAttrs(attrs), *ins)
        return res if isinstance(res, tuple) else (res,)

    specs = [jax.ShapeDtypeStruct(tuple(s), np.float32) for s in in_shapes]
    out = jax.eval_shape(fn, *specs)
    return [tuple(int(d) for d in o.shape) for o in out]


def _fill_param_shapes(node, env, shapes):
    """Derive weight/bias/gamma/... shapes from the data shape for the
    common layer ops (the part of reference per-op InferShape that runs
    'backward' from data to params)."""
    def in_shape(pos):
        src, i = node.inputs[pos]
        if src.is_variable:
            return shapes.get(src.name)
        return env.get((id(src), i))

    def set_var(pos, shape):
        src, _ = node.inputs[pos]
        if src.is_variable and src.name not in shapes:
            shapes[src.name] = tuple(int(d) for d in shape)

    op = node.op.name
    a = node.attrs
    if not node.inputs:
        return  # source ops (random_uniform etc.) have no data input
    data = in_shape(0)
    if data is None:
        return
    if op == "FullyConnected":
        nh = int(a["num_hidden"])
        flat = 1
        for d in (data[1:] if a.get("flatten", True) else data[-1:]):
            flat *= d
        set_var(1, (nh, flat))
        if len(node.inputs) > 2:
            set_var(2, (nh,))
    elif op in ("Convolution", "Convolution_v1"):
        nf = int(a["num_filter"])
        ng = int(a.get("num_group", 1))
        kernel = tuple(int(k) for k in a["kernel"])
        if a.get("layout") in ("NWC", "NHWC", "NDHWC"):
            # channels-last: weight (O, *kernel, I/g) — cuDNN-NHWC form
            set_var(1, (nf,) + kernel + (data[-1] // ng,))
        else:
            set_var(1, (nf, data[1] // ng) + kernel)
        if len(node.inputs) > 2:
            set_var(2, (nf,))
    elif op == "Deconvolution":
        nf = int(a["num_filter"])
        ng = int(a.get("num_group", 1))
        kernel = tuple(int(k) for k in a["kernel"])
        set_var(1, (data[1], nf // ng) + kernel)
        if len(node.inputs) > 2:
            set_var(2, (nf,))
    elif op == "RNN":
        from ..ops.rnn_ops import rnn_param_size

        h = int(a["state_size"])
        layers = int(a.get("num_layers", 1))
        bidir = bool(a.get("bidirectional", False))
        d = 2 if bidir else 1
        # data is TNC: (T, N, input_size)
        set_var(1, (rnn_param_size(data[2], h, layers,
                                   a.get("mode", "lstm"), bidir),))
        set_var(2, (layers * d, data[1], h))
        if len(node.inputs) > 3:
            set_var(3, (layers * d, data[1], h))
    elif op in ("BatchNorm", "BatchNorm_v1"):
        c = data[int(a.get("axis", 1))]
        for pos in (1, 2, 3, 4):
            if pos < len(node.inputs):
                set_var(pos, (c,))
    elif op in ("InstanceNorm",):
        c = data[1]
        set_var(1, (c,)); set_var(2, (c,))
    elif op == "LayerNorm":
        c = data[int(a.get("axis", -1))]
        set_var(1, (c,)); set_var(2, (c,))
    elif op == "Embedding":
        set_var(1, (int(a["input_dim"]), int(a["output_dim"])))
    elif op == "LeakyReLU" and a.get("act_type") == "prelu":
        set_var(1, (data[1],))
    elif op in ("SoftmaxOutput", "Softmax", "SVMOutput"):
        set_var(1, data[:-1] if not a.get("multi_output") else
                (data[0],) + tuple(data[2:]))
    elif op in ("LinearRegressionOutput", "MAERegressionOutput",
                "LogisticRegressionOutput"):
        set_var(1, data)
    elif op == "softmax_cross_entropy":
        set_var(1, (data[0],))
    elif op in ("MultiHeadAttention", "_contrib_MultiHeadAttention"):
        c = data[2]
        set_var(1, (3 * c, c)); set_var(2, (3 * c,))
        set_var(3, (c, c)); set_var(4, (c,))
    elif op in ("MoE", "_contrib_MoE"):
        d = data[-1]
        e = int(a["num_experts"])
        h = int(a.get("hidden_size", 4 * d))
        set_var(1, (d, e)); set_var(2, (e, d, h)); set_var(3, (e, h, d))
    elif op == "Custom":
        # the user's CustomOpProp.infer_shape derives every input shape
        # from the data shape (reference python/mxnet/operator.py
        # infer_shape_entry)
        from ..operator import _make_prop

        prop = _make_prop(a)
        n_args = len(prop.list_arguments())
        known_in = [list(in_shape(i) or ()) for i in range(n_args)]
        try:
            in_sh, _out_sh, _aux_sh = prop.infer_shape(known_in)
        except Exception:
            return
        for pos, s in enumerate(in_sh[:len(node.inputs)]):
            # an empty shape means the prop echoed an unknown input back
            # (CustomOpProp.infer_shape base default); leave it unknown so
            # simple_bind raises instead of binding a bogus 0-d scalar
            if s:
                set_var(pos, tuple(int(d) for d in s))


def _apply(op_name, input_syms, attrs, name=None):
    """Compose an op over symbols (the reference's atomic-symbol
    CreateAtomicSymbol + Compose C API path).  Active ``AttrScope``
    attributes apply under explicit ones (reference AttrScope.get)."""
    from ..attribute import current as _scope_attrs

    op = _registry.get(op_name)
    scoped = _scope_attrs()
    if scoped:
        merged = dict(scoped)
        merged.update(attrs)
        attrs = merged
    else:
        attrs = dict(attrs)
    # explicit names also go through the NameManager so Prefix prepends to
    # them too (reference name.py Prefix.get applies to given names)
    from .. import name as _name_mod

    name = _name_mod.current().get(name or attrs.pop("name", None),
                                   op_name.lower().lstrip("_"))
    attrs.pop("name", None)
    op.validate_attrs(attrs)

    arg_names, aux_names = expected_inputs(op_name, attrs)
    inputs = []
    for s in input_syms:
        if len(s._outputs) != 1:
            raise MXNetError("cannot compose multi-output symbol directly")
        inputs.append(s._outputs[0])
    # auto-create missing parameter/aux variables (reference behavior:
    # conv = sym.Convolution(data) creates convolution0_weight, ...);
    # they inherit the active AttrScope like explicit Variables, which is
    # how `with AttrScope(__lr_mult__=...)` reaches the parameters the
    # optimizer keys multipliers on
    total_wanted = len(arg_names) + len(aux_names)
    if len(inputs) < total_wanted and op_name in _PARAMETRIC_OPS:
        for extra in list(arg_names)[len(inputs):] + list(aux_names):
            vnode = _Node(None, "%s_%s" % (name, extra),
                          dict(scoped) if scoped else {}, [])
            inputs.append((vnode, 0))
    aux_slots = tuple(range(len(arg_names),
                            len(arg_names) + len(aux_names)))
    node = _Node(op, name, attrs, inputs, aux_slots)
    return Symbol([(node, i) for i in range(node.num_outputs)])


_PARAMETRIC_OPS = {
    "FullyConnected", "Convolution", "Convolution_v1", "Deconvolution",
    "BatchNorm", "BatchNorm_v1", "Embedding", "InstanceNorm", "LayerNorm",
    "SoftmaxOutput", "Softmax", "SVMOutput", "LinearRegressionOutput",
    "MAERegressionOutput", "LogisticRegressionOutput",
    "softmax_cross_entropy", "LeakyReLU",
    # Custom ops declare their arguments via CustomOpProp.list_arguments;
    # the reference Compose path auto-creates the missing ones just like
    # any layer op (python/mxnet/operator.py)
    "Custom",
    "MultiHeadAttention", "_contrib_MultiHeadAttention",
    "MoE", "_contrib_MoE",
    # sym.RNN(data, state_size=..) auto-creates parameters/state like the
    # reference Compose path; shapes from the RNN branch of
    # _fill_param_shapes
    "RNN",
}


def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None, stype=None, **kwargs):
    """Create a symbolic variable (reference ``mx.sym.Variable``);
    active AttrScope attributes apply under explicit ones (reference
    ``symbol.var`` applies AttrScope)."""
    from ..attribute import current as _scope_attrs

    attrs = dict(_scope_attrs())
    attrs.update(attr or {})
    if shape is not None:
        attrs["__shape__"] = tuple(shape)
    if lr_mult is not None:
        attrs["__lr_mult__"] = lr_mult
    if wd_mult is not None:
        attrs["__wd_mult__"] = wd_mult
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    if init is not None:
        attrs["__init__"] = init if isinstance(init, str) else \
            (init.dumps() if hasattr(init, "dumps")
             else init.__class__.__name__)
    node = _Node(None, name, attrs, [])
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol (reference
    ``mx.sym.Group``)."""
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for nj in data["nodes"]:
        attrs = {}
        for k, v in nj.get("attrs", {}).items():
            if k == "__init__":
                # keep the serialized initializer STRING: decoding it to
                # a list would get re-str()'d by attr_dict() into
                # single-quoted non-json that initializer.create rejects
                attrs[k] = v
                continue
            try:
                attrs[k] = json.loads(v)
            except (ValueError, TypeError):
                attrs[k] = v
        if nj["op"] == "null":
            node = _Node(None, nj["name"], attrs, [])
        else:
            op = _registry.get(nj["op"])
            inputs = [(nodes[i], oi) for (i, oi, _) in nj["inputs"]]
            arg_names, aux_names = expected_inputs(nj["op"], attrs)
            aux_slots = tuple(range(len(arg_names),
                                    len(arg_names) + len(aux_names))) \
                if aux_names else ()
            node = _Node(op, nj["name"], attrs, inputs, aux_slots)
        nodes.append(node)
    heads = [(nodes[i], oi) for (i, oi, _) in data["heads"]]
    return Symbol(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
