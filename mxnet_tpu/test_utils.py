"""Test harness utilities.

Reference: ``python/mxnet/test_utils.py`` — the de-facto op-validation
toolkit (SURVEY.md §4): ``check_numeric_gradient`` (``:620``) runs a
finite-difference check of any symbol's gradients,
``check_symbolic_forward/backward`` (``:744,:809``) compare against numpy
references, and ``check_consistency`` (``:987``) cross-validates one
symbol across context/dtype combos (the reference's CPU↔GPU pattern; here
dtype combos and, when available, cpu↔tpu).
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray import NDArray, array, zeros

__all__ = [
    "default_context", "set_default_context", "same", "almost_equal",
    "assert_almost_equal", "rand_shape_2d", "rand_shape_3d", "rand_shape_nd",
    "rand_ndarray", "random_arrays", "simple_forward", "numeric_grad",
    "check_numeric_gradient", "check_symbolic_forward",
    "check_symbolic_backward", "check_consistency", "check_speed",
]

_default_ctx = None

# one explicitly-seeded stream feeds every random helper below, so an
# op sweep replays bit-exactly run to run (MX003 — the global
# np.random stream would couple draws to whatever ran before)
_rng = np.random.RandomState(1234)


def default_context():
    """The context tests run on (reference ``default_context()``,
    env-switchable)."""
    return _default_ctx if _default_ctx is not None else current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b):
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=1e-5, atol=1e-20):
    return np.allclose(_as_np(a), _as_np(b), rtol=rtol, atol=atol)


def assert_almost_equal(a, b, rtol=1e-5, atol=1e-20, names=("a", "b")):
    a, b = _as_np(a), _as_np(b)
    if not np.allclose(a, b, rtol=rtol, atol=atol):
        idx = np.unravel_index(
            np.argmax(np.abs(a - b)), a.shape) if a.shape else ()
        raise AssertionError(
            "%s and %s differ: max |diff| %g at %s (rtol=%g atol=%g)\n%r\n%r"
            % (names[0], names[1], np.max(np.abs(a - b)), idx, rtol, atol,
               a, b))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(_rng.randint(1, d + 1) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(_rng.randint(1, d + 1) for d in (dim0, dim1, dim2))


def rand_shape_nd(ndim, dim=10):
    return tuple(_rng.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, dtype="float32", ctx=None):
    return array(_rng.uniform(-1, 1, size=shape).astype(dtype),
                 ctx or default_context())


def random_arrays(*shapes):
    arrays = [_rng.randn(*s).astype("float32") if s else
              np.array(_rng.randn(), "float32") for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def _highest_precision():
    """Numeric checks compare against fp32 numpy references, so force
    full-precision matmuls for their executors (TPUs default to
    bf16-accumulated fp32 matmuls, ~1e-2 relative error)."""
    import jax

    return jax.default_matmul_precision("highest")


def _with_highest_precision(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with _highest_precision():
            return fn(*args, **kwargs)
    return wrapper


def _parse_location(sym, location, ctx):
    """location: dict name->np/NDArray, or list in list_arguments order."""
    if isinstance(location, dict):
        bad = set(location) - set(sym.list_arguments())
        if bad:
            raise MXNetError("location has unknown arguments %s" % bad)
        loc = location
    else:
        loc = dict(zip(sym.list_arguments(), location))
    return {k: (v if isinstance(v, NDArray) else array(v, ctx))
            for k, v in loc.items()}


def _parse_aux(sym, aux_states, ctx):
    if aux_states is None:
        return {}
    if isinstance(aux_states, dict):
        aux = aux_states
    else:
        aux = dict(zip(sym.list_auxiliary_states(), aux_states))
    return {k: (v if isinstance(v, NDArray) else array(v, ctx))
            for k, v in aux.items()}


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol with the given inputs; returns numpy output(s)."""
    ctx = ctx or default_context()
    ex = sym.bind(ctx, args=_parse_location(sym, inputs, ctx))
    outs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outs[0] if len(outs) == 1 else outs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences of sum(outputs) wrt each location entry
    (reference ``numeric_grad``, ``test_utils.py:573``)."""
    grads = {}
    for name, arr in location.items():
        base = arr.asnumpy().copy()
        g = np.zeros_like(base, dtype="float64")
        flat = base.reshape(-1)
        gf = g.reshape(-1)
        for i in range(flat.size):
            orig = flat[i]
            flat[i] = orig + eps / 2
            executor.arg_dict[name][:] = base.reshape(base.shape)
            outs = executor.forward(is_train=use_forward_train)
            fp = sum(o.asnumpy().astype("float64").sum() for o in outs)
            flat[i] = orig - eps / 2
            executor.arg_dict[name][:] = base.reshape(base.shape)
            outs = executor.forward(is_train=use_forward_train)
            fm = sum(o.asnumpy().astype("float64").sum() for o in outs)
            flat[i] = orig
            executor.arg_dict[name][:] = base.reshape(base.shape)
            gf[i] = (fp - fm) / eps
        grads[name] = g.astype("float32")
    return grads


@_with_highest_precision
def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           rtol=1e-2, atol=None, grad_nodes=None, ctx=None,
                           use_forward_train=True):
    """Finite-difference check of a symbol's gradients (reference
    ``check_numeric_gradient``, ``test_utils.py:620``).

    The analytic gradient of ``sum(outputs)`` from ``Executor.backward``
    must match central differences for every (or each of ``grad_nodes``)
    argument.
    """
    ctx = ctx or default_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states, ctx)
    if grad_nodes is None:
        grad_nodes = [n for n in loc
                      if n in sym.list_arguments()]
    grad_req = {n: ("write" if n in grad_nodes else "null")
                for n in sym.list_arguments()}

    args = {n: loc[n].copy() for n in loc}
    grad_dict = {n: zeros(loc[n].shape, ctx) for n in grad_nodes}
    ex = sym.bind(ctx, args=args, args_grad=grad_dict, grad_req=grad_req,
                  aux_states={n: a.copy() for n, a in aux.items()} or None)
    ex.forward(is_train=use_forward_train)
    ex.backward()
    analytic = {n: grad_dict[n].asnumpy() for n in grad_nodes}

    # fresh executor for the numeric pass (aux must not carry train-mode
    # updates from the analytic pass)
    ex_num = sym.bind(ctx, args={n: loc[n].copy() for n in loc},
                      aux_states={n: a.copy() for n, a in aux.items()}
                      or None, grad_req={n: "null" for n in
                                         sym.list_arguments()})
    numeric = numeric_grad(ex_num, {n: loc[n] for n in grad_nodes},
                           eps=numeric_eps,
                           use_forward_train=use_forward_train)
    for name in grad_nodes:
        assert_almost_equal(analytic[name], numeric[name], rtol=rtol,
                            atol=atol if atol is not None else 1e-4,
                            names=("analytic d%s" % name,
                                   "numeric d%s" % name))


@_with_highest_precision
def check_symbolic_forward(sym, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None, is_train=False):
    """Compare a symbol's outputs against numpy references (reference
    ``check_symbolic_forward``, ``test_utils.py:744``)."""
    ctx = ctx or default_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states, ctx)
    ex = sym.bind(ctx, args=loc, aux_states=aux or None,
                  grad_req={n: "null" for n in sym.list_arguments()})
    outputs = ex.forward(is_train=is_train)
    if isinstance(expected, dict):
        expected = [expected[n] for n in sym.list_outputs()]
    for out, exp, name in zip(outputs, expected, sym.list_outputs()):
        assert_almost_equal(out, exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-5,
                            names=("forward[%s]" % name, "expected"))
    return [o.asnumpy() for o in outputs]


@_with_highest_precision
def check_symbolic_backward(sym, location, out_grads, expected, rtol=1e-4,
                            atol=None, aux_states=None, grad_req="write",
                            ctx=None):
    """Compare a symbol's input gradients against numpy references
    (reference ``check_symbolic_backward``, ``test_utils.py:809``)."""
    ctx = ctx or default_context()
    loc = _parse_location(sym, location, ctx)
    aux = _parse_aux(sym, aux_states, ctx)
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    if isinstance(grad_req, str):
        grad_req = {n: (grad_req if n in expected else "null")
                    for n in sym.list_arguments()}
    grad_dict = {n: zeros(loc[n].shape, ctx) for n in expected}
    ex = sym.bind(ctx, args=loc, args_grad=grad_dict, grad_req=grad_req,
                  aux_states=aux or None)
    ex.forward(is_train=True)
    if out_grads is not None and not isinstance(out_grads, (list, tuple)):
        out_grads = [out_grads]
    if out_grads is not None:
        out_grads = [g if isinstance(g, NDArray) else array(g, ctx)
                     for g in out_grads]
    ex.backward(out_grads)
    for name, exp in expected.items():
        assert_almost_equal(grad_dict[name], exp, rtol=rtol,
                            atol=atol if atol is not None else 1e-5,
                            names=("grad[%s]" % name, "expected"))
    return {n: grad_dict[n].asnumpy() for n in expected}


@_with_highest_precision
def check_consistency(sym, ctx_list, scale=1.0, grad_req="write",
                      rtol=1e-3, atol=1e-4):
    """Run one symbol across several context/dtype configs and
    cross-compare outputs and gradients (reference ``check_consistency``,
    ``test_utils.py:987`` — the CPU↔GPU validation pattern; here the
    combos are (ctx, dtype) dicts with a ``ctx`` key and shape kwargs).
    """
    if len(ctx_list) < 2:
        raise MXNetError("check_consistency needs >= 2 configs")
    arg_names = sym.list_arguments()
    # generate inputs once, from the first config's shapes
    shapes = {k: v for k, v in ctx_list[0].items()
              if k not in ("ctx", "type_dict")}
    inputs = {n: _rng.normal(size=shapes[n], scale=scale)
              .astype("float64") for n in shapes if n in arg_names}
    results = []
    for cfg in ctx_list:
        ctx = cfg.get("ctx", default_context())
        type_dict = cfg.get("type_dict", {})
        loc = {n: array(v.astype(type_dict.get(n, "float32")), ctx)
               for n, v in inputs.items()}
        # params not in shapes get zeros
        full_shapes = dict(shapes)
        ex = sym.simple_bind(ctx, grad_req=grad_req, **full_shapes)
        for n, v in loc.items():
            ex.arg_dict[n][:] = v.asnumpy()
        outs = [o.asnumpy().astype("float64")
                for o in ex.forward(is_train=True)]
        ex.backward()
        grads = {n: g.asnumpy().astype("float64")
                 for n, g in ex.grad_dict.items() if g is not None}
        results.append((outs, grads))
    ref_outs, ref_grads = results[0]
    for outs, grads in results[1:]:
        for a, b in zip(ref_outs, outs):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=("ctx0 out", "ctxN out"))
        for n in ref_grads:
            assert_almost_equal(ref_grads[n], grads[n], rtol=rtol,
                                atol=atol, names=("ctx0 d%s" % n,
                                                  "ctxN d%s" % n))
    return results


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                **shapes):
    """Time N forward+backward executions (reference ``check_speed``,
    ``test_utils.py:913``)."""
    import time

    ctx = ctx or default_context()
    if location is None:
        ex = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
    else:
        loc = _parse_location(sym, location, ctx)
        grad_dict = {n: zeros(v.shape, ctx) for n, v in loc.items()}
        ex = sym.bind(ctx, args=loc, args_grad=grad_dict, grad_req=grad_req)
    ex.forward(is_train=True)
    ex.backward()
    for o in ex.outputs:
        o.wait_to_read()
    tic = time.time()
    for _ in range(N):
        ex.forward(is_train=True)
        ex.backward()
    for o in ex.outputs:
        o.wait_to_read()
    return (time.time() - tic) / N
