"""Testing utilities: deterministic fault injection for resilience tests.

The reference ships its chaos tooling as nightly scripts
(``tests/nightly/test_kvstore.py`` restart loops); the TPU build makes
fault injection a first-class, deterministic harness
(:mod:`mxnet_tpu.testing.faults`, driven by ``MXNET_FAULT_INJECT``) so
preemption, IO failure, and wedged-collective behavior are unit-testable.
"""
from . import faults

__all__ = ["faults"]
