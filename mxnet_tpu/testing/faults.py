"""Deterministic fault injection, driven by ``MXNET_FAULT_INJECT``.

Production code calls :func:`inject` at a named *site*; the env var
decides whether anything happens there, so the hooks are free when the
variable is unset.  Grammar (entries separated by ``,`` or ``;``, fields
by ``:``)::

    MXNET_FAULT_INJECT = "<site>:<action>[:key=value]*[,...]"

Actions:

* ``raise`` — raise :class:`FaultInjected` (an ``MXNetError``) at the
  site.  In a prefetch worker this models a crashing decode thread whose
  error must surface at the consumer's next ``next()``.
* ``kill``  — raise :class:`WorkerKilled`.  Worker loops catch it
  *explicitly* and return without enqueueing anything, modeling a thread
  that dies silently (OOM-killed, segfaulted C extension); the consumer
  must detect the dead worker instead of blocking on the queue forever.
  ``WorkerKilled`` deliberately subclasses ``BaseException`` so generic
  ``except Exception`` error-forwarding paths cannot swallow it into the
  "clean error" channel.
* ``delay`` — sleep ``seconds`` at the site, modeling a wedged peer or a
  slow network; used to trip the ``MXNET_KV_TIMEOUT_S`` watchdogs.
* ``hang`` — sleep ``seconds`` in short slices, modeling a wedged step;
  used to trip the ``MXNET_STEP_TIMEOUT_S`` step watchdog.  The sliced
  sleep gives the watchdog's asynchronously-raised
  :class:`~mxnet_tpu.base.StepHung` a bytecode boundary to land on, so
  the "hung" thread dies the way a wedged-but-interruptible one would.
* ``nan`` / ``inf`` — *value* injection: :func:`inject` RETURNS
  ``float('nan')`` / ``float('inf')`` instead of raising, and the site
  folds it into its data (``Module`` poisons one element of the batch at
  site ``numerics``, which flows through forward/backward into the loss
  and every gradient).  Callers that ignore the return value are
  unaffected.
* ``bitflip`` / ``truncate`` — *file* corruption: the site passes the
  just-written file via ``inject(site, path=...)`` and the action XORs
  one bit in the middle byte / truncates the file to half its length.
  Models silent disk corruption after a successful write — exactly what
  the checkpoint verifier's SHA-256 pass must catch.  Only valid at
  sites that supply a path (today: ``checkpoint_corrupt``).

Keys:

* ``after=N``  — fire on the Nth hit of the site (default 1).  Hits are
  counted per spec entry, so ``prefetch:raise:after=3`` lets exactly two
  batches through first — deterministic by construction.
* ``seconds=S`` — sleep length for ``delay`` (default 1.0).
* ``sticky=1`` — keep firing on every hit >= ``after`` instead of once.
* ``prob=P``   — probabilistic trigger: every hit >= ``after`` fires
  independently with probability ``P`` from a *seeded per-spec RNG
  stream* (sha256 of site/action/``seed``), so soak tests can inject
  sustained random faults that replay identically run over run.
  :func:`reset` re-seeds the stream, so re-arming the same spec string
  replays the same fire pattern.  ``seed=N`` (default 0) picks a
  different deterministic stream.

Sites instrumented today: ``device_prefetch`` / ``prefetch`` (the io.py
worker loops), ``checkpoint_io`` (between temp-file write and the atomic
rename), ``shard_write`` (inside the v2 shard writer, bytes down but the
shard not yet published — kill/raise/delay here model a host dying
mid-checkpoint), ``checkpoint_corrupt`` (after a shard is published,
with ``path=`` — ``bitflip``/``truncate`` here model post-write disk
rot), ``collective`` (kvstore DCN barrier / cross-replica sum),
``numerics`` (Module's fused step — poison one batch element with the
returned nan/inf), ``step`` (top of every fit batch — ``hang`` here
trips the step watchdog), ``zero_update`` (around the ZeRO-sharded
fused dispatch — the gradient reduce-scatter going in and the
parameter all-gather coming out; arming it also bounds the dispatch,
so ``delay`` past ``MXNET_KV_TIMEOUT_S`` surfaces the collective
timeout with the kvstore's peer report attached even single-process),
``zero_gather`` (same contract for the ZeRO-3 step: around the
bucketed parameter all-gathers — the per-bucket forward gathers and
the backward re-gathers all run inside the one bounded dispatch, so a
``delay`` past ``MXNET_KV_TIMEOUT_S`` reports the gather as the stuck
collective by name), ``serve_queue`` (the serving scheduler —
crossed at *every* request boundary) plus its phase-specific companions
``serve_admit`` / ``serve_decode`` / ``serve_verify`` /
``serve_respond`` (admission, per-request decode-step, per-request
speculative propose/verify-step, and response boundaries; a fault fails
that one request and releases its slot — surviving slots keep decoding,
the isolation the serve chaos tests assert), and the oversubscription
machinery's ``serve_evict`` (watermark preemption, before the victim's
pages are released — a fault fails the victim alone; its release is
refcount-aware, so shared prefix pages stay intact for other holders)
and ``serve_resume`` (parked-request resume, before the re-prefill — a
fault fails the parked request alone and survivors keep decoding).
The serve sites fire in deterministic slot order each step, so
``after=N`` picks a specific request.  The replica supervisor
(``serve/supervisor.py``) adds three coarser sites: ``serve_replica_kill``
fires at the top of every replica's decode-boundary tick — ``kill``
hard-kills that replica (drain + failover), ``raise`` counts against its
circuit breaker, ``hang`` wedges it until the per-replica step watchdog
trips — ``serve_dispatch`` fires per request at dispatcher admission (a
fault fails that one request, typed), and ``serve_rejoin`` fires at each
ejected replica's rejoin probe (a fault fails the probe and doubles its
backoff).  ``data_decode`` fires inside each data-service decode task
(in the worker *process* with ``num_workers > 0`` — hits are counted
per process — or inline on the consumer thread with 0): ``raise``
surfaces as a typed error at the consumer's ``next()``, ``kill``
hard-exits the worker so the consumer-side dead-worker detection must
fire instead of hanging the ring, ``delay`` models slow decode.
``data_service`` fires at the consumer's ``next()`` itself.  The four
``elastic_*`` sites cross the live-migration phases in order
(``elastic_quiesce`` / ``elastic_rendezvous`` / ``elastic_reshard`` /
``elastic_resume``, see ``parallel/elastic.py``): a ``raise`` at any of
them must leave the job falling back to the last good checkpoint, a
``kill`` must leave it resumable — the chaos matrix in
``tests/test_elastic.py`` asserts exactly that at every phase.  The
network gateway (``serve/gateway.py``) adds four sites at its failure
boundaries: ``gateway_read`` (after a connection's bytes are read,
before parsing — a fault fails that connection typed, isolated from
every other stream), ``gateway_write`` (before each streamed chunk —
a fault is treated as the client vanishing, so the request is
cancelled and its state freed), ``gateway_cancel`` (cancel
propagation — a fault fails the cancel alone and the request decodes
to completion, whose normal finish still frees the slot), and
``gateway_drain`` (drain start — a fault collapses the grace window,
force-cancelling in-flight streams typed immediately).

The parsed spec auto-refreshes when the env var string changes; call
:func:`reset` to re-arm counters when reusing the same string (tests).
"""
from __future__ import annotations

import os
import threading
import time

from ..base import MXNetError

__all__ = ["FaultInjected", "WorkerKilled", "inject", "reset", "active",
           "rearm_after_fork", "SITES", "sites"]

ENV_VAR = "MXNET_FAULT_INJECT"

_ACTIONS = ("raise", "kill", "delay", "hang", "nan", "inf",
            "bitflip", "truncate")

# The fault-site catalog.  Every *production* ``inject(site)`` literal
# must name an entry here, and every entry must be exercised by at
# least one test — mxlint MX005 enforces both statically (tests may
# still inject ad-hoc sites when testing this module itself).  The
# prose above stays the narrative; this dict is the contract.
SITES = {
    "prefetch": "io.py PrefetchingIter worker loop",
    "device_prefetch": "io.py DevicePrefetchIter staging worker loop",
    "checkpoint_io": "between checkpoint temp-file write and the "
                     "atomic rename",
    "shard_write": "inside the v2 shard writer, before publish",
    "checkpoint_corrupt": "after a shard publishes (path= for "
                          "bitflip/truncate disk rot)",
    "collective": "kvstore DCN barrier / cross-replica sum",
    "numerics": "Module fused step — poison one batch element",
    "step": "top of every fit batch (hang trips the step watchdog)",
    "zero_update": "around the ZeRO-sharded fused dispatch",
    "zero_gather": "around the ZeRO-3 bucketed parameter all-gathers",
    "serve_queue": "serving scheduler, every request boundary",
    "serve_admit": "serving scheduler admission boundary",
    "serve_decode": "serving scheduler per-request decode step",
    "serve_verify": "serving scheduler per-request speculative "
                    "propose/verify step",
    "serve_respond": "serving scheduler response boundary",
    "serve_evict": "serving scheduler watermark preemption, before the "
                   "victim's pages are released",
    "serve_resume": "serving scheduler parked-request resume, before "
                    "the re-prefill",
    "serve_replica_kill": "replica supervisor, top of every replica's "
                          "decode-boundary tick (kill = replica death, "
                          "raise = breaker fault, hang = watchdog trip)",
    "serve_dispatch": "replica supervisor dispatcher, per-request "
                      "admission into the bounded queue",
    "serve_rejoin": "replica supervisor rejoin probe of an ejected "
                    "replica (a fault fails the probe, doubling its "
                    "backoff)",
    "kv_quant": "quantized-KV prefill, before the request's pages/"
                "scales are written",
    "kv_window": "hybrid-stack prefill, before any windowed-layer ring "
                 "row or SSM state update is written",
    "data_decode": "inside each data-service decode task (worker "
                   "process, or inline with num_workers=0)",
    "data_service": "data-service consumer next()",
    "elastic_quiesce": "elastic migration quiesce phase, after the "
                       "last-good checkpoint and before the in-memory "
                       "state capture",
    "elastic_rendezvous": "elastic migration re-form phase, before the "
                          "bounded peer-heartbeat wait",
    "elastic_reshard": "elastic migration reshard phase, before the "
                       "captured windows move onto the new plan's "
                       "layout",
    "elastic_resume": "elastic migration resume phase, before the data "
                      "service seeks back to the quiesce boundary",
    "gateway_read": "serve gateway, after a connection's request bytes "
                    "are read and before parsing (a fault fails that "
                    "connection typed; kill drops it abruptly)",
    "gateway_write": "serve gateway, before each streamed chunk is "
                     "written (a fault is treated as the client "
                     "vanishing: the request is cancelled, state freed)",
    "gateway_cancel": "serve gateway cancel propagation, before the "
                      "backend releases the request's slot (a fault "
                      "fails the cancel alone; the request decodes to "
                      "completion, which still frees its state)",
    "gateway_drain": "serve gateway drain start (a fault collapses the "
                     "grace window: in-flight streams are force-"
                     "cancelled typed immediately)",
}


def sites():
    """The registered site catalog (name -> where it fires)."""
    return dict(SITES)


class FaultInjected(MXNetError):
    """The error an injected ``raise`` fault throws (an ``MXNetError`` so
    production error paths treat it exactly like an organic failure)."""


class WorkerKilled(BaseException):
    """Injected silent-death signal for worker threads.  BaseException on
    purpose: it must bypass ``except Exception`` error-forwarding so the
    worker dies without leaving a breadcrumb, like a real hard kill."""


_lock = threading.RLock()
_env_snapshot = None   # env string the current specs were parsed from
_specs = []            # list of spec dicts
_hits = []             # per-spec hit counters, parallel to _specs


def _parse(raw):
    specs = []
    for entry in raw.replace(";", ",").split(","):
        entry = entry.strip()
        if not entry:
            continue
        fields = entry.split(":")
        if len(fields) < 2 or not fields[0] or fields[1] not in _ACTIONS:
            raise MXNetError(
                "bad %s entry %r: want <site>:<action>[:key=value]* with "
                "action one of %s" % (ENV_VAR, entry, ", ".join(_ACTIONS)))
        spec = {"site": fields[0], "action": fields[1], "after": 1,
                "seconds": 1.0, "sticky": False, "prob": None, "seed": 0}
        for kv in fields[2:]:
            key, sep, val = kv.partition("=")
            if key == "after" and sep:
                spec["after"] = int(val)
            elif key == "seconds" and sep:
                spec["seconds"] = float(val)
            elif key == "sticky" and sep:
                spec["sticky"] = val not in ("0", "false", "False")
            elif key == "prob" and sep:
                spec["prob"] = float(val)
                if not 0.0 < spec["prob"] <= 1.0:
                    raise MXNetError(
                        "bad %s field %r in entry %r: prob must be in "
                        "(0, 1]" % (ENV_VAR, kv, entry))
            elif key == "seed" and sep:
                spec["seed"] = int(val)
            else:
                raise MXNetError(
                    "bad %s field %r in entry %r (want after=N, seconds=S, "
                    "sticky=0/1, prob=P or seed=N)" % (ENV_VAR, kv, entry))
        if spec["prob"] is not None:
            spec["rng"] = _spec_rng(spec)
        specs.append(spec)
    return specs


def _spec_rng(spec):
    """Seeded per-spec RNG stream for ``prob=`` triggers.  sha256 of
    site/action/seed — NOT the builtin ``hash``, which is salted per
    process and would break replayability."""
    import hashlib
    import random

    digest = hashlib.sha256(
        ("%s:%s:%d" % (spec["site"], spec["action"],
                       spec["seed"])).encode()).digest()
    return random.Random(int.from_bytes(digest[:8], "big"))


def _refresh_locked():
    global _env_snapshot, _specs, _hits

    raw = os.environ.get(ENV_VAR, "")
    if raw != _env_snapshot:
        # parse BEFORE committing the snapshot: a malformed spec must
        # keep raising on every hook hit, not raise once and then be
        # silently ignored because the snapshot already matched
        specs = _parse(raw) if raw else []
        _specs = specs
        _hits = [0] * len(specs)
        _env_snapshot = raw


def reset():
    """Re-parse the env var and zero all hit counters (tests re-arming
    the same spec string between cases)."""
    global _env_snapshot

    with _lock:
        _env_snapshot = None
        _refresh_locked()


def rearm_after_fork():
    """Replace the module lock in a freshly forked child.  A fork can
    land while another parent thread holds ``_lock``; the child inherits
    the locked state with no owner, so every later :func:`inject` there
    would deadlock.  Decode worker processes call this first."""
    global _lock

    _lock = threading.RLock()


def active(site=None):
    """True when any fault spec (optionally: for ``site``) is armed."""
    with _lock:
        _refresh_locked()
        return any(s["site"] == site or site is None for s in _specs)


def inject(site, path=None):
    """Fault hook.  No-op unless ``MXNET_FAULT_INJECT`` names ``site``;
    otherwise counts the hit and fires the configured action when the
    counter reaches ``after`` (every later hit too with ``sticky=1``).
    Returns the poison value for ``nan``/``inf`` actions, else None.
    ``path`` names the file the ``bitflip``/``truncate`` corruption
    actions mutate; sites that cannot supply one reject those actions.
    """
    if not os.environ.get(ENV_VAR) and _env_snapshot in (None, ""):
        return None  # fast path: nothing armed, nothing to refresh
    delays = []
    hangs = []
    corruptions = []
    poison = None
    with _lock:
        _refresh_locked()
        for i, spec in enumerate(_specs):
            if spec["site"] != site:
                continue
            _hits[i] += 1
            n = _hits[i]
            if spec["prob"] is not None:
                # probabilistic trigger: every hit >= after rolls the
                # spec's seeded stream; the roll happens for skipped
                # pre-`after` hits too so the stream position — and
                # therefore the replayed fire pattern — depends only on
                # the hit count, never on the `after` offset
                if spec["rng"].random() >= spec["prob"] \
                        or n < spec["after"]:
                    continue
            elif n != spec["after"] and not (spec["sticky"] and
                                             n > spec["after"]):
                continue
            if spec["action"] == "delay":
                delays.append(spec["seconds"])
            elif spec["action"] == "hang":
                hangs.append(spec["seconds"])
            elif spec["action"] == "nan":
                poison = float("nan")
            elif spec["action"] == "inf":
                poison = float("inf")
            elif spec["action"] in ("bitflip", "truncate"):
                corruptions.append(spec["action"])
            elif spec["action"] == "kill":
                raise WorkerKilled(
                    "injected worker kill at site %r (hit %d)" % (site, n))
            else:
                raise FaultInjected(
                    "injected fault at site %r (hit %d, %s=%r)"
                    % (site, n, ENV_VAR, _env_snapshot))
    for action in corruptions:  # file I/O outside the lock
        _corrupt_file(action, path, site)
    for s in delays:  # sleep outside the lock: a delay must not serialize
        time.sleep(s)  # other sites behind it
    for s in hangs:
        # sliced sleep: the step watchdog delivers StepHung with
        # PyThreadState_SetAsyncExc, which lands at the next bytecode
        # boundary — a single long time.sleep would swallow it until
        # the full hang elapsed
        deadline = time.monotonic() + s
        while time.monotonic() < deadline:
            time.sleep(0.02)
    return poison


def _corrupt_file(action, path, site):
    """Apply a ``bitflip``/``truncate`` corruption to ``path`` in place —
    after the atomic publish, like real disk rot would."""
    if path is None:
        raise MXNetError(
            "fault action %r at site %r needs a file: the site must call "
            "inject(site, path=...)" % (action, site))
    size = os.path.getsize(path)
    if action == "truncate":
        with open(path, "r+b") as f:
            f.truncate(size // 2)
        return
    with open(path, "r+b") as f:  # bitflip: XOR one bit mid-file
        f.seek(size // 2)
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([(byte[0] if byte else 0) ^ 0x01]))
