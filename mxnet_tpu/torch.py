"""PyTorch interop bridge (the TPU-era analogue of the reference's Torch
plugin: ``plugin/torch`` ``TorchModule``/``TorchCriterion`` ops and the
``python/mxnet/torch.py`` frontend, which bridged *Lua* Torch modules into
MXNet graphs).

Design: a ``torch.nn.Module`` (CPU) becomes a framework op through the same
host-callback machinery as CustomOp (``mxnet_tpu/operator.py`` —
``jax.pure_callback`` forward + ``jax.custom_vjp`` backward), with
``torch.autograd`` supplying the backward pass.  Like the reference plugin,
this runs the foreign framework's kernels on the host — it exists for
interop and porting, not for the TPU hot path (document: not fusable; under
the fused Module path it falls back to the split executor, exactly like
CustomOp).

Surfaces:

* ``TorchModuleOp`` / ``TorchCriterionOp`` — ``CustomOp`` subclasses
  (usable via ``mx.sym.Custom(op_type=...)`` after ``register_module``).
* ``apply(module, *args)`` — imperative one-shot: run a torch module on
  NDArrays, differentiable through the autograd tape.
"""
from __future__ import annotations

from .base import MXNetError
from .ndarray import NDArray, array
from . import operator as _op

__all__ = ["TorchModuleOp", "TorchCriterionOp", "register_module", "apply"]


def _torch():
    try:
        import torch

        return torch
    except ImportError:  # pragma: no cover - torch is baked into the image
        raise MXNetError(
            "the torch bridge needs the 'torch' package") from None


class TorchModuleOp(_op.CustomOp):
    """Wrap a ``torch.nn.Module``: inputs = (data, *module parameters) so
    the module's parameters are trainable framework arguments (reference
    TorchModule keeps them inside the Lua closure; exposing them as op
    inputs is what lets the TPU autograd/optimizer see them)."""

    def __init__(self, module):
        self.module = module.cpu().float()
        self._params = list(self.module.parameters())

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _torch()
        data = torch.from_numpy(in_data[0].asnumpy().copy())
        with torch.no_grad():
            for p, v in zip(self._params, in_data[1:]):
                p.copy_(torch.from_numpy(v.asnumpy().copy()))
            out = self.module(data)
        self.assign(out_data[0], req[0], array(out.numpy()))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _torch()
        data = torch.from_numpy(in_data[0].asnumpy().copy()).requires_grad_(True)
        with torch.no_grad():
            for p, v in zip(self._params, in_data[1:]):
                p.copy_(torch.from_numpy(v.asnumpy().copy()))
        for p in self._params:
            p.requires_grad_(True)
            p.grad = None
        out = self.module(data)
        out.backward(torch.from_numpy(out_grad[0].asnumpy().copy()))
        grads = [data.grad] + [p.grad for p in self._params]
        for i, g in enumerate(grads):
            self.assign(in_grad[i], req[i],
                        array(g.detach().numpy()) if g is not None
                        else in_grad[i] * 0)


class TorchCriterionOp(_op.CustomOp):
    """Wrap a torch loss (criterion): ``forward(data, label) -> loss``
    (reference ``plugin/torch`` TorchCriterion)."""

    def __init__(self, criterion):
        self.criterion = criterion

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _torch()
        data = torch.from_numpy(in_data[0].asnumpy().copy())
        label = torch.from_numpy(in_data[1].asnumpy().copy())
        with torch.no_grad():
            loss = self.criterion(data, label)
        self.assign(out_data[0], req[0], array(loss.numpy().reshape(1)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _torch()
        data = torch.from_numpy(in_data[0].asnumpy().copy()).requires_grad_(True)
        label = torch.from_numpy(in_data[1].asnumpy().copy())
        loss = self.criterion(data, label)
        loss.backward()
        scale = float(out_grad[0].asnumpy().reshape(-1)[0])
        self.assign(in_grad[0], req[0], array(data.grad.numpy()) * scale)
        self.assign(in_grad[1], req[1], in_grad[1] * 0)


def register_module(op_type, module_factory):
    """Register a torch module factory as a Custom op type, so it works in
    Symbol graphs::

        mx.torch.register_module('torch_mlp', lambda: nn.Sequential(...))
        out = mx.sym.Custom(data, op_type='torch_mlp')
    """
    probe = module_factory()
    _register_prop(op_type, lambda: probe, module_factory)
    return [tuple(p.shape) for p in probe.parameters()]


def _register_prop(op_type, get_probe, make_operator_module):
    """Build and register the CustomOpProp.  ``get_probe`` returns the
    module used for shape inference (may return None if it was weakly
    held and collected); ``make_operator_module`` builds the module for
    ``create_operator``."""
    torch = _torch()
    probe = get_probe()
    param_shapes = [tuple(p.shape) for p in probe.parameters()]
    # torch names like "0.weight" become "0_weight": the _weight/_bias
    # suffix lets the default initializer's name patterns apply
    param_names = [n.replace(".", "_")
                   for n, _ in probe.named_parameters()]

    class _Prop(_op.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"] + param_names

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            m = get_probe()
            if m is None:
                raise MXNetError("torch module for op %r was garbage "
                                 "collected" % op_type)
            data_shape = in_shape[0]
            with torch.no_grad():
                out = m(torch.zeros(*data_shape))
            return ([list(data_shape)] +
                    [list(s) for s in param_shapes],
                    [list(out.shape)], [])

        def create_operator(self, ctx, in_shapes, in_dtypes):
            m = make_operator_module()
            if m is None:
                raise MXNetError("torch module for op %r was garbage "
                                 "collected" % op_type)
            return TorchModuleOp(m)

    _Prop.__name__ = "TorchProp_%s" % op_type
    _op.register(op_type)(_Prop)


def apply(module, data):
    """Run a torch module imperatively on an NDArray.  Routed through the
    ``Custom`` registry op, so it records on the autograd tape and
    ``autograd.backward`` reaches both the data and the module's
    parameters (passed as trailing Custom inputs)."""
    import weakref

    from . import ndarray as nd

    if not isinstance(data, NDArray):
        data = array(data)
    op_type = "_torch_apply_%x" % id(module)
    if op_type not in _op._CUSTOM_PROPS:
        # hold the module WEAKLY (a strong closure would keep every
        # transient module alive in the process-global registry forever)
        # and drop the registry entry when it is collected
        ref = weakref.ref(module)
        _register_prop(op_type, ref, ref)
        weakref.finalize(module, _op._CUSTOM_PROPS.pop, op_type, None)
    params = [array(p.detach().numpy()) for p in module.parameters()]
    return nd.Custom(data, *params, op_type=op_type)
