"""Network visualization (reference ``python/mxnet/visualization.py``):
``print_summary`` — layer table with shapes and parameter counts;
``plot_network`` — graphviz Digraph (DOT-text fallback when graphviz is
not installed, which is the case in this build environment)."""
from __future__ import annotations

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def _node_label(node):
    a = node.attrs
    op = node.op.name if node.op is not None else "null"
    if op in ("Convolution", "Deconvolution"):
        kernel = "x".join(str(k) for k in a.get("kernel", ()))
        stride = "x".join(str(s) for s in a.get("stride", (1,)))
        return "%s\n%s/%s, %s" % (op, kernel, stride,
                                  a.get("num_filter", "?"))
    if op == "FullyConnected":
        return "FullyConnected\n%s" % a.get("num_hidden", "?")
    if op == "Pooling":
        return "Pooling\n%s, %s" % (a.get("pool_type", "max"),
                                    tuple(a.get("kernel", ())))
    if op in ("Activation", "LeakyReLU"):
        return "%s\n%s" % (op, a.get("act_type", ""))
    return op


def _per_node_output_shapes(symbol, arg_shapes):
    """Abstractly evaluate the graph once, recording every node's first
    output shape (the summary's 'Output Shape' column)."""
    import jax
    from .ops import registry as _registry

    shapes = {}

    def trace():
        env = {}
        for node in symbol._topo():
            if node.is_variable:
                env[(id(node), 0)] = jax.numpy.zeros(
                    arg_shapes[node.name], "float32")
                continue
            ins = [env[(id(n), i)] for (n, i) in node.inputs]
            attrs = dict(node.attrs)
            if node.op.uses_train_mode:
                attrs["__is_train__"] = False
            if node.op.needs_rng:
                ins = [jax.random.PRNGKey(0)] + ins
            res = node.op.compute(_registry.FrozenAttrs(attrs), *ins)
            if not isinstance(res, tuple):
                res = (res,)
            for i, r in enumerate(res):
                env[(id(node), i)] = r
        return tuple(env[(id(n), 0)] for n in symbol._topo()
                     if not n.is_variable)

    try:
        specs = jax.eval_shape(trace)
    except Exception:
        return {}
    nodes = [n for n in symbol._topo() if not n.is_variable]
    for node, spec in zip(nodes, specs):
        shapes[id(node)] = str(tuple(int(d) for d in spec.shape))
    return shapes


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-by-layer summary (reference ``print_summary``): name, type,
    output shape, parameter count, inputs.  Returns total params."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    if shape is None:
        shape = {}
    arg_shapes = {}
    node_shapes = {}
    if shape:
        from .symbol.symbol import _infer_param_shapes

        arg_shapes = _infer_param_shapes(symbol, dict(shape))
        node_shapes = _per_node_output_shapes(symbol, arg_shapes)

    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for f, p in zip(fields, positions):
            line = (line + str(f))[:p - 1].ljust(p)
        print(line)

    print("=" * line_length)
    row(headers)
    print("=" * line_length)
    total = 0
    arg_names = set(symbol.list_arguments())
    data_names = set(shape)
    seen_params = set()
    for node in symbol._topo():
        if node.is_variable:
            continue
        params = 0
        prevs = []
        for (src, _i) in node.inputs:
            if src.is_variable:
                if src.name in data_names:
                    prevs.append(src.name)
                elif src.name.endswith("_label"):
                    prevs.append(src.name)
                elif src.name in arg_names and src.name in arg_shapes \
                        and src.name not in seen_params:
                    seen_params.add(src.name)
                    n = 1
                    for d in arg_shapes[src.name]:
                        n *= d
                    params += n
            else:
                prevs.append(src.name)
        total += params
        out_shape = node_shapes.get(id(node), "")
        row(["%s (%s)" % (node.name, node.op.name), out_shape, params,
             ",".join(prevs[:2])])
    print("=" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", shape=None, node_attrs=None,
                 save_format="dot"):
    """Build a graphviz ``Digraph`` of the symbol (reference
    ``plot_network``).  Without the graphviz package installed, returns
    the DOT source text instead — same graph, renderable elsewhere."""
    node_attrs = node_attrs or {}
    lines = ["digraph %s {" % title.replace(" ", "_"),
             '  rankdir="BT";']
    ids = {}
    for i, node in enumerate(symbol._topo()):
        ids[id(node)] = "n%d" % i
        if node.is_variable:
            lines.append('  n%d [label="%s", shape=oval];'
                         % (i, node.name))
        else:
            lines.append('  n%d [label="%s", shape=box];'
                         % (i, _node_label(node).replace("\n", "\\n")))
    for node in symbol._topo():
        for (src, _i) in node.inputs:
            lines.append("  %s -> %s;" % (ids[id(src)], ids[id(node)]))
    lines.append("}")
    dot_src = "\n".join(lines)
    try:
        import graphviz  # noqa: F401

        g = graphviz.Source(dot_src)
        return g
    except ImportError:
        return dot_src
